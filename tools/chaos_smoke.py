#!/usr/bin/env python
"""One seeded drop+delay chaos scenario, end to end, for quick local
verification of the resilience layer:

    python tools/chaos_smoke.py [--seed 42] [--nodes 5] [--byzantine 2]
                                [--rounds 24]

Builds a real-crypto chain, runs the N-node sync scenario from
tests/chaos.py with Byzantine peers injecting drops and delays (plus a
little truncation), and prints the convergence verdict, the fault log
summary, the per-node breaker snapshots, and the breaker series from the
metrics scrape.  Exit code 0 iff every honest node converged to the same
verified chain.  Two invocations with the same seed print the same digest.
"""

import argparse
import collections
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tests"))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--nodes", type=int, default=5)
    ap.add_argument("--byzantine", type=int, default=2)
    ap.add_argument("--rounds", type=int, default=24)
    args = ap.parse_args()

    from chaos import ChaosScenario

    scenario = ChaosScenario(
        seed=args.seed, n_nodes=args.nodes, n_byzantine=args.byzantine,
        rounds=args.rounds,
        # the smoke plan: drops + delays (and a little stream truncation);
        # corruption paths are covered by the pytest scenarios
        byzantine_plan=dict(drop=0.35, delay=0.3, delay_s=9.0,
                            corrupt=0.0, truncate=0.15))
    result = scenario.run()

    faults = collections.Counter(f for _, _, _, f in result.events)
    print(f"seed            : {args.seed}")
    print(f"nodes           : {args.nodes} ({args.byzantine} Byzantine: "
          f"{', '.join(sorted(scenario.byzantine))})")
    print(f"rounds          : {args.rounds}")
    print(f"converged       : {result.converged}")
    print(f"chain digest    : {result.chain_digest}")
    print(f"faults injected : {dict(faults) or 'none fired this seed'}")
    for node, snap in sorted(result.breaker_snapshots.items()):
        print(f"breakers[{node}] : {snap}")

    from drand_tpu.metrics import scrape
    lines = [l for l in scrape("group").decode().splitlines()
             if l.startswith("resilience_breaker_state")]
    print("breaker series  :")
    for line in lines:
        print(f"  {line}")
    return 0 if result.converged else 1


if __name__ == "__main__":
    sys.exit(main())
