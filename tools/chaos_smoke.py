#!/usr/bin/env python
"""One seeded drop+delay chaos scenario, end to end, for quick local
verification of the resilience layer:

    python tools/chaos_smoke.py [--seed 42] [--nodes 5] [--byzantine 2]
                                [--rounds 24]

Builds a real-crypto chain, runs the N-node sync scenario from
tests/chaos.py with Byzantine peers injecting drops and delays (plus a
little truncation), and prints the convergence verdict, the fault log
summary, the per-node breaker snapshots, and the breaker series from the
metrics scrape.  Exit code 0 iff every honest node converged to the same
verified chain.  Two invocations with the same seed print the same digest.
"""

import argparse
import collections
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tests"))


def storage_main(args) -> int:
    """--storage mode: seeded at-rest faults (torn write + bit flip +
    deleted row) on one node of a 3-node network; the integrity scan must
    detect them all, the heal path must repair from peers, and the
    post-repair full-crypto rescan must be clean."""
    from chaos import StorageChaosScenario

    scenario = StorageChaosScenario(seed=args.seed, n_nodes=max(args.nodes, 2),
                                    rounds=args.rounds)
    result = scenario.run()
    print(f"seed            : {args.seed}")
    print(f"nodes           : {max(args.nodes, 2)} (victim: node0)")
    print(f"rounds          : {args.rounds}")
    print(f"injected faults : " + ", ".join(
        f"round {r}={k}" for r, k in sorted(result.injected.items())))
    print(f"scan flagged    : {result.detected_rounds}")
    print(f"all detected    : {result.all_detected}")
    print(f"unrepaired      : {result.unrepaired or 'none'}")
    print(f"rescan clean    : {result.rescan_clean}")
    print(f"converged       : {result.converged}")
    print(f"chain digest    : {result.chain_digest}")

    from drand_tpu.metrics import scrape
    lines = [l for l in scrape("group").decode().splitlines()
             if l.startswith("chain_integrity_")]
    print("integrity series:")
    for line in lines:
        print(f"  {line}")
    return 0 if result.ok else 1


def device_main(args) -> int:
    """--device mode: the device-fault failover scenario — a flapping
    device backend under a mixed live/background verify workload.  Every
    future must resolve with verdicts identical to a host-only run,
    failover must land within one watchdog deadline, and the canary
    probe must re-promote the device after recovery."""
    from chaos import DeviceChaosScenario, DeviceFailoverSyncScenario

    scenario = DeviceChaosScenario(seed=args.seed, rounds=args.rounds)
    result = scenario.run()
    print(f"seed            : {args.seed}")
    print(f"rounds          : {args.rounds}")
    print(f"all resolved    : {result.all_resolved}")
    print(f"verdict parity  : {result.verdicts_match_host}")
    print(f"failovers       : {result.failovers}")
    print(f"watchdog trips  : {result.watchdog_trips}")
    print(f"failover latency: {result.failover_latency} "
          f"(deadline {result.deadline})")
    print(f"re-promoted     : {result.repromoted}")
    print(f"device resumed  : {result.device_served_after_recovery}")
    print(f"final state     : {result.final_state}")

    sync = DeviceFailoverSyncScenario(seed=args.seed,
                                      rounds=args.rounds).run()
    print(f"sync converged  : {sync.converged} (device killed mid-sync)")
    print(f"sync elapsed    : {sync.elapsed:.1f}s fake "
          f"(round period {sync.period:.0f}s)")
    print(f"sync degraded   : {sync.degraded}")

    from chaos import GroupIsolationScenario

    iso = GroupIsolationScenario(seed=args.seed).run()
    print(f"group isolation : victim g{iso.victim_group} "
          f"faulted={iso.faulted_groups} "
          f"migrations={iso.migrations} failovers={iso.failovers}")
    print(f"siblings        : {iso.sibling_states} "
          f"untouched={iso.siblings_untouched}")

    from drand_tpu.metrics import scrape
    lines = [l for l in scrape("private").decode().splitlines()
             if l.startswith(("verify_service_failovers",
                              "verify_service_backend_state",
                              "verify_service_watchdog_trips"))]
    print("failover series :")
    for line in lines:
        print(f"  {line}")
    return 0 if result.ok and sync.ok and iso.ok else 1


def reshare_main(args) -> int:
    """--reshare mode: the DKG/reshare lifecycle chaos suite.  The
    headline — a node crashes BETWEEN reshare success and the transition
    round, restarts, commits the pending transition from the ledger, and
    the chain continues under the byte-identical collective public key
    with no invalid partials — plus leader-crash-during-setup (followers
    unwind to DKG_FAILED and the retry succeeds) and crash-restart
    mid-deal-phase (aborted session reported, stale epoch bundles
    rejected by nonce, fresh session succeeds)."""
    import tempfile

    from chaos import (DealCrashRestartScenario, LeaderCrashSetupScenario,
                       ReshareCrashScenario)

    with tempfile.TemporaryDirectory() as root:
        r = ReshareCrashScenario(seed=args.seed,
                                 root=os.path.join(root, "reshare")).run()
        print(f"seed            : {args.seed}")
        print(f"converged       : {r.converged} (head {r.head})")
        print(f"same public key : {r.same_public_key}")
        print(f"rounds verify   : {r.all_rounds_verify}")
        print(f"old state kept  : {r.old_state_served_after_restart} "
              "(crash window: active files untouched)")
        print(f"recovery action : {r.rearm_action} "
              f"(ledger pending={r.pending_before_transition})")
        print(f"ledger committed: {r.committed_after_transition}")

        lc = LeaderCrashSetupScenario(
            seed=args.seed, root=os.path.join(root, "leader")).run()
        print(f"leader crash    : failed->DKG_FAILED="
              f"{lc.status_failed_not_wedged} "
              f"retry={lc.retry_succeeded}")

        dc = DealCrashRestartScenario(
            seed=args.seed, root=os.path.join(root, "deal")).run()
        print(f"mid-deal crash  : aborted->DKG_FAILED="
              f"{dc.status_failed_not_wedged} "
              f"stale-rejected={dc.stale_bundle_rejected} "
              f"retry={dc.retry_succeeded} "
              f"staged-clean={dc.staged_clean} ({dc.detail})")
        if not (r.ok and lc.ok and dc.ok):
            print(f"FAILED: reshare={r!r}\nleader={lc!r}\ndeal={dc!r}")

    from drand_tpu.metrics import scrape
    lines = [l for l in scrape("group").decode().splitlines()
             if l.startswith(("dkg_sessions_total", "dkg_phase",
                              "reshare_transition_pending"))]
    print("dkg series      :")
    for line in lines:
        print(f"  {line}")
    return 0 if r.ok and lc.ok and dc.ok else 1


def overload_main(args) -> int:
    """--overload mode: the serving-plane overload scenario — a seeded
    public read flood plus one sync-hog peer during live rounds.  The
    partials admission p99 must stay under a round period, every shed
    must be well-formed, the verify background lane must pause before
    any normal-class shed, and the ladder must recover to nominal."""
    from chaos import OverloadScenario

    result = OverloadScenario(seed=args.seed).run()
    print(f"seed            : {args.seed}")
    print(f"reads served    : {result.served_reads}")
    print(f"reads shed      : {result.shed_reads} "
          f"(ratio {result.shed_ratio:.2f})")
    print(f"sheds well-formed: {result.sheds_well_formed}")
    print(f"partials        : {result.partials_admitted} admitted, "
          f"p99 wait {result.partials_p99:.3f}s "
          f"(period {result.period:.0f}s)")
    print(f"peer-cap sheds  : {result.peer_cap_sheds}")
    print(f"hog rounds      : {result.hog_rounds} "
          f"(fair-share bound {result.hog_bound:.0f}, "
          f"paced={result.paced})")
    print(f"max level       : {result.max_level}")
    print(f"bg paused at    : {result.bg_pause_at} "
          f"(first normal shed {result.first_normal_shed_at})")
    print(f"ladder ordered  : {result.ladder_ordered}")
    print(f"recovered       : level {result.final_level}, "
          f"bg resumed {result.bg_resumed}")

    from drand_tpu.metrics import scrape
    lines = [l for l in scrape("private").decode().splitlines()
             if l.startswith(("admission_requests", "admission_level",
                              "admission_background_paused"))]
    print("admission series:")
    for line in lines:
        print(f"  {line}")
    return 0 if result.ok else 1


def tenant_main(args) -> int:
    """--tenant mode: the multi-tenant noisy-neighbor scenario — an
    aggressor tenant floods sheddable reads and saturates its device-time
    quota on an expensive chain while a victim tenant's rounds keep
    flowing.  The victim's partials p99 must stay under its period and
    its per-round throughput within 20% of the aggressor-free run (same
    seed); every over-quota rejection must be well-formed and carry the
    tenant label, never a silent drop."""
    from chaos import NoisyNeighborScenario

    r = NoisyNeighborScenario(seed=args.seed).run()
    print(f"seed            : {args.seed}")
    print(f"victim rounds   : {r.victim_rounds}/{r.victim_rounds_baseline}"
          f" (ratio {r.throughput_ratio:.2f}, floor 0.80)")
    print(f"victim partials : p99 {r.victim_partials_p99:.3f}s "
          f"(period {r.period:.0f}s)")
    print(f"victim reads    : {r.victim_reads_served} served")
    print(f"aggro reads     : {r.aggro_reads_served} served, "
          f"{r.aggro_reads_shed} shed "
          f"({r.aggro_quota_sheds} tenant-labelled)")
    print(f"aggro quota     : peak level {r.aggro_quota_peak:.2f} "
          f"(>=1 = over budget)")
    print(f"sheds well-formed: {r.sheds_well_formed} "
          f"(silent drops: {r.silent_drops})")
    print(f"placement       : {r.placement} "
          f"(distinct groups: {len(set(r.placement.values())) >= 2})")
    print(f"device seconds  : {r.device_seconds}")

    from drand_tpu.metrics import scrape
    lines = [l for l in scrape("private").decode().splitlines()
             if l.startswith(("tenant_requests_total",
                              "tenant_device_seconds_total",
                              "tenant_quota_level"))]
    print("tenant series   :")
    for line in lines:
        print(f"  {line}")
    return 0 if r.ok else 1


def handel_main(args) -> int:
    """--handel mode: the committee-scale Handel overlay under seeded
    Byzantine members (invalid candidates, equivocation, out-of-block
    claims, silent holes).  Every honest session must reach the
    threshold within the level budget, demoted peers must stop being
    polled, and the recovered group signature must verify.  Same seed,
    same digest."""
    from chaos import HandelByzantineScenario

    n = max(args.nodes, 16)
    byz = min(args.byzantine, n // 4) or n // 4
    thr = (n - byz) // 2 + 1
    scenario = HandelByzantineScenario(seed=args.seed, n=n, threshold=thr,
                                       n_byzantine=byz)
    r = scenario.run()
    print(f"seed            : {args.seed}")
    print(f"committee       : n={r.n} threshold={r.threshold} "
          f"byzantine={len(r.byz_behaviors)}")
    print(f"behaviors       : {r.byz_behaviors}")
    print(f"honest complete : {r.honest_complete}/{r.n_honest} "
          f"in {r.ticks_used} ticks (level budget {r.level_budget})")
    print(f"demotions       : " + (", ".join(
        f"node{i}->{peers}" for i, peers in sorted(r.demotions.items()))
        or "none"))
    print(f"polled-after-demotion violations: "
          f"{r.polled_after_demotion or 'none'}")
    print(f"recovered valid : {r.recovered_valid}")
    print(f"full weights    : min={min(r.full_weights)} "
          f"max={max(r.full_weights)} (honest={r.n_honest})")
    print(f"digest          : {r.digest}")
    return 0 if r.ok else 1


def identity_main(args) -> int:
    """--identity mode: the stolen-identity scenario — a live 3-node
    mTLS committee under active identity theft.  A CA-signed attacker
    cert with no roster SAN forges a victim's Handel sender_index
    (rejected at ingress, metered, chain stays live), revoked/expired/
    tampered tokens are refused with identity-reason trailers before any
    quota spend lands on the victim tenant, every node's cert rotates
    mid-rekey without a restart, and a no-identity control fleet serves
    plaintext byte-identically with a bearer header present."""
    import tempfile

    from chaos import StolenIdentityScenario

    with tempfile.TemporaryDirectory(prefix="drand-identity-") as root:
        r = StolenIdentityScenario(seed=args.seed, root=root).run()
        print(f"seed            : {args.seed}")
        print(f"plaintext       : rejected={r.plaintext_rejected}")
        print(f"forged packets  : {r.forged_packets} sent, "
              f"{r.impersonation_rejected} rejected "
              f"(victim index {r.victim_index}, "
              f"metered={r.impersonation_metered})")
        print(f"chain liveness  : after forgery="
              f"{r.liveness_after_forgery} "
              f"after rotation={r.liveness_after_rotation}")
        print(f"good token      : served={r.good_token_served}")
        print(f"stolen tokens   : " + ", ".join(
            f"{leg}->{reason}" for leg, reason in
            sorted(r.token_reasons.items())))
        print(f"victim quota    : untouched={r.victim_quota_untouched}")
        print(f"cert rotation   : epochs={r.rotation_epochs} "
              f"rekey-completed={r.rekey_over_rotation}")
        print(f"control fleet   : plaintext={r.control_plaintext_ok} "
              f"header-ignored={r.control_header_ignored}")
        print(f"digest          : {r.digest}")

    from drand_tpu.metrics import scrape
    lines = [l for l in scrape("private").decode().splitlines()
             if l.startswith(("identity_rejections",
                              "identity_cert_reloads",
                              "authz_tokens"))]
    print("identity series :")
    for line in lines:
        print(f"  {line}")
    return 0 if r.ok else 1


def fleet_main(args) -> int:
    """--fleet mode: the process-fleet soak (tests/fleet.py) — N REAL
    daemon processes over live gRPC through the per-link chaos proxy:
    coordinated DKG, Handel rounds, one SIGKILL + restart + catch-up,
    a seeded minority partition + heal, SIGTERM-all teardown.  Exit 0
    iff every invariant held (no fork, liveness, recovery, clean
    exits)."""
    import json
    import tempfile

    from fleet import FleetError, smoke_soak

    base = tempfile.mkdtemp(prefix="drand-fleet-")
    try:
        result = smoke_soak(base, n=max(args.nodes, 5),
                            rounds=max(args.rounds, 5), seed=args.seed,
                            period=args.period, mtls=args.mtls)
    except FleetError as e:
        print(f"FLEET INVARIANT FAILED: {e}", file=sys.stderr)
        print(f"node folders kept for diagnosis: {base}", file=sys.stderr)
        return 1
    print(f"seed            : {result['seed']}")
    print(f"nodes           : {result['n']} (mtls={result['mtls']})")
    print(f"rounds          : {result['rounds']} "
          f"({result['rounds_compared']} fork-compared)")
    print(f"group hash      : {result['group_hash'][:32]}")
    print(f"SIGKILL victim  : {result['victim']} (rejoined + caught up)")
    print(f"partitioned     : {result['minority']} (healed + caught up)")
    print(f"exit codes      : {result['exit_codes']}")
    forwarded = sum(s["bytes_forward"] + s["bytes_backward"]
                    for s in result["proxy_stats"].values())
    resets = sum(s["resets"] for s in result["proxy_stats"].values())
    print(f"proxied traffic : {forwarded} bytes, {resets} stream resets")
    print("verdict         : OK")
    import shutil
    shutil.rmtree(base, ignore_errors=True)
    return 0


def tsan_main(args) -> int:
    """--tsan mode: the threaded serving plane under the runtime lock
    sanitizer.  Sets DRAND_TSAN=1 BEFORE any drand_tpu import (the mode
    functions import lazily, so every lock the scenarios build goes
    through the instrumented factories), then drives the three most
    thread-heavy scenarios — device failover, reshare lifecycle, and
    serving-plane overload — and fails if any scenario fails OR the
    sanitizer recorded a finding (lock-order cycle, non-reentrant
    re-entry).  Long-hold / slow-acquire warnings are printed but never
    fatal: a cold XLA compile under a lock is slow, not wrong."""
    assert "drand_tpu" not in sys.modules, \
        "--tsan must set DRAND_TSAN before the first drand_tpu import"
    os.environ["DRAND_TSAN"] = "1"

    rcs = {}
    for name, fn in (("device", device_main), ("reshare", reshare_main),
                     ("overload", overload_main)):
        print(f"=== tsan scenario: {name} ===")
        rcs[name] = fn(args)

    from drand_tpu.analysis import tsan
    rep = tsan.report()
    print("=== tsan verdict ===")
    print(tsan.render_report(rep))
    print("scenario rcs    : " + ", ".join(
        f"{k}={v}" for k, v in rcs.items()))
    ok = all(v == 0 for v in rcs.values()) and not rep["findings"]
    print(f"tsan clean      : {ok}")
    return 0 if ok else 1


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--nodes", type=int, default=5)
    ap.add_argument("--byzantine", type=int, default=2)
    ap.add_argument("--rounds", type=int, default=24)
    ap.add_argument("--period", type=int, default=3,
                    help="beacon period in seconds (--fleet mode)")
    ap.add_argument("--storage", action="store_true",
                    help="run the at-rest storage-fault scenario "
                         "(integrity scan + quarantine + peer repair) "
                         "instead of the network chaos scenario")
    ap.add_argument("--device", action="store_true",
                    help="run the device-fault failover scenario "
                         "(watchdog + host failover + canary "
                         "re-promotion) instead of the network chaos "
                         "scenario")
    ap.add_argument("--overload", action="store_true",
                    help="run the serving-plane overload scenario "
                         "(read flood + sync-hog peer; admission "
                         "control + degradation ladder) instead of the "
                         "network chaos scenario")
    ap.add_argument("--reshare", action="store_true",
                    help="run the DKG/reshare lifecycle chaos suite "
                         "(crash between reshare success and transition "
                         "+ leader crash in setup + crash-restart "
                         "mid-deal) instead of the network chaos "
                         "scenario")
    ap.add_argument("--handel", action="store_true",
                    help="run the committee-scale Handel overlay "
                         "scenario (Byzantine candidates, demotion, "
                         "level-budget convergence) instead of the "
                         "network chaos scenario; --nodes/--byzantine "
                         "scale the committee (min 16)")
    ap.add_argument("--tenant", action="store_true",
                    help="run the multi-tenant noisy-neighbor scenario "
                         "(aggressor tenant flood + device-quota "
                         "saturation vs a victim tenant's live rounds) "
                         "instead of the network chaos scenario")
    ap.add_argument("--mtls", action="store_true",
                    help="with --fleet: run the whole fleet over mutual "
                         "TLS (per-node certs from a private CA)")
    ap.add_argument("--identity", action="store_true",
                    help="run the stolen-identity scenario: a live "
                         "3-node mTLS committee vs a CA-signed attacker "
                         "cert (forged sender_index, stolen/replayed "
                         "tokens, cert rotation mid-rekey, no-identity "
                         "control run)")
    ap.add_argument("--fleet", action="store_true",
                    help="run the process-fleet soak: N real daemon "
                         "processes over live gRPC through the per-link "
                         "chaos proxy (DKG, Handel rounds, SIGKILL + "
                         "restart, partition + heal, graceful teardown)")
    ap.add_argument("--tsan", action="store_true",
                    help="run the device + reshare + overload scenarios "
                         "under the runtime lock-order sanitizer "
                         "(DRAND_TSAN=1); exit 0 only if every scenario "
                         "passes AND the sanitizer records no findings")
    args = ap.parse_args()

    if args.tsan:
        return tsan_main(args)
    if args.fleet:
        return fleet_main(args)
    if args.identity:
        return identity_main(args)
    if args.storage:
        return storage_main(args)
    if args.device:
        return device_main(args)
    if args.overload:
        return overload_main(args)
    if args.reshare:
        return reshare_main(args)
    if args.handel:
        return handel_main(args)
    if args.tenant:
        return tenant_main(args)

    from chaos import ChaosScenario

    scenario = ChaosScenario(
        seed=args.seed, n_nodes=args.nodes, n_byzantine=args.byzantine,
        rounds=args.rounds,
        # the smoke plan: drops + delays (and a little stream truncation);
        # corruption paths are covered by the pytest scenarios
        byzantine_plan=dict(drop=0.35, delay=0.3, delay_s=9.0,
                            corrupt=0.0, truncate=0.15))
    result = scenario.run()

    faults = collections.Counter(f for _, _, _, f in result.events)
    print(f"seed            : {args.seed}")
    print(f"nodes           : {args.nodes} ({args.byzantine} Byzantine: "
          f"{', '.join(sorted(scenario.byzantine))})")
    print(f"rounds          : {args.rounds}")
    print(f"converged       : {result.converged}")
    print(f"chain digest    : {result.chain_digest}")
    print(f"faults injected : {dict(faults) or 'none fired this seed'}")
    for node, snap in sorted(result.breaker_snapshots.items()):
        print(f"breakers[{node}] : {snap}")

    from drand_tpu.metrics import scrape
    lines = [l for l in scrape("group").decode().splitlines()
             if l.startswith("resilience_breaker_state")]
    print("breaker series  :")
    for line in lines:
        print(f"  {line}")
    return 0 if result.converged else 1


if __name__ == "__main__":
    sys.exit(main())
