#!/usr/bin/env python
"""Lane-width x pipeline-depth autotune sweep (ISSUE 10, ROADMAP item 2).

Sequential scan stages (the 758-step E2 pow, the GLV ladders) cost per
STEP, not per lane, so wider pads amortize them — and a depth-k dispatch
window amortizes the ~74 ms/dispatch RPC latency.  Which (pad, depth)
point wins depends on the accelerator, so it is MEASURED, not assumed:
this tool sweeps pad x depth per scheme kind on the current backend,
streams a signed fixture through `BatchBeaconVerifier.verify_stream`,
and persists the winner to TUNING.json — which the resident verify
service consults at handle creation (crypto/tuning.py; env overrides
win; a container with no chip and no tuning file is unchanged).

    python tools/autotune.py                      # full sweep -> TUNING.json
    python tools/autotune.py --pads 8192,16384,32768 --depths 1,2,4
    python tools/autotune.py --selftest           # tiny CPU sweep into a
                                                  # temp file + proof the
                                                  # service consults it

The full sweep is sized for a chip round (pad 32768 x G2 is hours of
compile on a cold CPU cache); the driver runs it once per chip round,
after bench.py has pre-warmed the compilation cache.

Fail-fast hygiene (the runtime complement of the vet `deadline`
checker): the full sweep opens with a bounded backend-bind probe in a
throwaway subprocess (drand_tpu/accel.py) — a wedged tunnel costs
DRAND_TPU_AUTOTUNE_PROBE_TIMEOUT seconds and a JSON error line, not the
round — and every measured (kind, group, pad, depth) cell commits the
best-so-far winners to TUNING.json before the next cell starts, so a
later hang never discards finished measurements.
"""
# tpu-vet: disable-file=verifier  (the sweep MEASURES raw
# BatchBeaconVerifier configs to pick what the service will use)

import argparse
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/drand_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

KIND_SCHEMES = {"g1": "bls-unchained-on-g1", "g2": "pedersen-bls-unchained"}


def _fixture(kind, n):
    """n device-signed unchained beacons for the kind's scheme."""
    from drand_tpu.chain.beacon import Beacon
    from drand_tpu.crypto import batch, schemes

    sch = schemes.scheme_from_name(KIND_SCHEMES[kind])
    sec, pub = sch.keypair(seed=b"autotune-" + kind.encode())
    msgs = [sch.digest_beacon(r, None) for r in range(1, n + 1)]
    sigs = batch.sign_batch(sch, sec, msgs)
    beacons = [Beacon(round=r, signature=s)
               for r, s in zip(range(1, n + 1), sigs)]
    return sch, sch.public_bytes(pub), beacons


def _group_devices(group_size):
    """The first `group_size` devices of the pool inventory — the device
    group a sweep at that size measures on (None = default placement)."""
    if group_size <= 1:
        return None
    from drand_tpu.crypto.device_pool import jax_devices

    devs = jax_devices()
    if len(devs) < group_size:
        return None
    return devs[:group_size]


def _measure(sch, pub, beacons, pad, depth, group_size=1):
    """Warm rounds/s of one streamed pass at (pad, depth) on a
    `group_size`-device group."""
    from drand_tpu.crypto import batch

    ver = batch.BatchBeaconVerifier(sch, pub, pad_to=pad,
                                    devices=_group_devices(group_size))

    def replay():
        n = 0
        for rounds, ok in ver.verify_stream(iter(beacons), chunk_size=pad,
                                            depth=depth):
            assert ok.all(), "autotune fixture failed verification"
            n += len(rounds)
        return n

    replay()                                  # cold: compile/cache-load
    t0 = time.perf_counter()
    n = replay()
    dt = time.perf_counter() - t0
    return n / dt, ver.pipeline_depth(depth, pad)


def sweep(kinds, pads, depths, n, progress=lambda m: None,
          group_sizes=(1,), commit=lambda winners, rows: None):
    """-> (winners {tuning key: entry}, rows [sweep table]).

    Winners are keyed per GROUP SIZE (ISSUE 11): the bare kind for a
    1-device group (the legacy spelling crypto/tuning.py falls back to)
    and `<kind>@<n>` for an n-device group, so a 1-device and a 4-device
    group never share a TUNING.json winner.

    `commit(winners, rows)` fires after EVERY measured cell with the
    best-so-far winner tables — a later cell that hangs past the driver
    budget loses only itself, never the measurements already taken."""
    rows = []
    winners = {}
    for kind in kinds:
        nn = max(n, 2 * max(pads))            # >= 2 chunks at the widest pad
        progress(f"fixture {kind}: signing {nn} rounds")
        sch, pub, beacons = _fixture(kind, nn)
        for gs in group_sizes:
            if gs > 1 and _group_devices(gs) is None:
                progress(f"{kind}@{gs}: fewer than {gs} devices, skipped")
                continue
            best = None
            entry_key = kind if gs == 1 else f"{kind}@{gs}"
            for pad in pads:
                for depth in depths:
                    progress(f"{kind}@{gs} pad={pad} depth={depth}")
                    rps, eff_depth = _measure(sch, pub, beacons, pad,
                                              depth, group_size=gs)
                    row = {"kind": kind, "group_size": gs, "pad": pad,
                           "depth": depth, "effective_depth": eff_depth,
                           "rounds_per_s": round(rps, 1)}
                    rows.append(row)
                    progress(f"{kind}@{gs} pad={pad} depth={depth}: "
                             f"{rps:.1f} r/s")
                    if best is None or rps > best["rounds_per_s"]:
                        best = row
                        winners[entry_key] = {
                            "pad": best["pad"], "depth": best["depth"],
                            "rounds_per_s": best["rounds_per_s"]}
                    commit(winners, rows)
    return winners, rows


def _selftest(args):
    """Tiny CPU-scale sweep into a temp TUNING.json, then prove the
    verify service CONSULTS it: a fresh service (pad=0 auto) must resolve
    the written winner for a new handle (the ISSUE acceptance)."""
    import jax

    from drand_tpu.crypto import schemes, tuning
    from drand_tpu.crypto.verify_service import VerifyService

    # explicit env overrides would (correctly) beat the file — clear them
    # so the selftest exercises the TUNING.json leg of the precedence
    for var in ("DRAND_VERIFY_PAD", "DRAND_VERIFY_PIPELINE_DEPTH"):
        os.environ.pop(var, None)
    out = args.out or os.path.join(
        tempfile.mkdtemp(prefix="drand_tpu_autotune_"), "TUNING.json")
    platform = jax.default_backend()
    winners, rows = sweep(["g1"], [32, 64], [1, 2], 128,
                          progress=lambda m: print(f"# {m}", file=sys.stderr,
                                                   flush=True))
    tuning.write_tuning(out, platform, winners)
    os.environ["DRAND_TUNING_FILE"] = out

    sch = schemes.scheme_from_name(KIND_SCHEMES["g1"])
    _, pub = sch.keypair(seed=b"autotune-consult")
    svc = VerifyService(pad=0)                # AUTO: must consult the file
    try:
        h = svc.handle(sch, sch.public_bytes(pub), device=True)
        got = next(iter(svc.stats()["tuning"].values()))
        want = winners["g1"]
        consulted = (got["pad"] == want["pad"]
                     and got["depth"] == want["depth"]
                     and getattr(h.backend, "pad_to", None) == want["pad"])
        report = {"ok": bool(consulted), "platform": platform,
                  "tuning_file": out, "winner": want, "consulted": got,
                  "sweep": rows}
        print(json.dumps(report), flush=True)
        return 0 if consulted else 1
    finally:
        svc.stop()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--pads", default="8192,16384,32768")
    ap.add_argument("--depths", default="1,2,4")
    ap.add_argument("--kinds", default="g1,g2")
    ap.add_argument("--group-sizes", default="",
                    help="device-group sizes to sweep (comma list; "
                         "default: 1, plus the full pool when more than "
                         "one device is visible)")
    ap.add_argument("--n", type=int, default=0,
                    help="fixture rounds (default: 2x the widest pad)")
    ap.add_argument("--out", default=None,
                    help="TUNING.json path (default: repo root; selftest: "
                         "a fresh temp file)")
    ap.add_argument("--selftest", action="store_true",
                    help="tiny CPU sweep + proof the service consults "
                         "the result (exit 0/1)")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest(args)

    # Fail-fast preflight (bench.py does the same): bind the backend in a
    # killable subprocess BEFORE this process touches jax — against a
    # wedged axon tunnel, an in-process `jax.devices()` blocks forever in
    # native code and eats the whole round budget (r06: 42 hung probes).
    from drand_tpu.accel import probe_backend
    probe_timeout = int(os.environ.get("DRAND_TPU_AUTOTUNE_PROBE_TIMEOUT",
                                       "120"))
    info, detail = probe_backend(timeout=probe_timeout)
    if info is None:
        print(json.dumps({"ok": False, "probe_error": detail,
                          "probe_timeout": probe_timeout}), flush=True)
        return 1
    print(f"# probe: {detail}", file=sys.stderr, flush=True)

    import jax
    platform = jax.default_backend()
    pads = [int(x) for x in args.pads.split(",") if x.strip()]
    depths = [int(x) for x in args.depths.split(",") if x.strip()]
    kinds = [k.strip() for k in args.kinds.split(",") if k.strip()]
    for k in kinds:
        if k not in KIND_SCHEMES:
            ap.error(f"unknown kind {k!r} (have {sorted(KIND_SCHEMES)})")
    if args.group_sizes.strip():
        group_sizes = [int(x) for x in args.group_sizes.split(",")
                       if x.strip()]
    else:
        from drand_tpu.crypto.device_pool import jax_devices

        n_devs = len(jax_devices())
        group_sizes = [1] + ([n_devs] if n_devs > 1 else [])
    out = args.out or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "TUNING.json")
    from drand_tpu.crypto import tuning

    def commit(winners_so_far, rows_so_far):
        # every cell lands on disk before the next one starts: the winner
        # table goes straight into TUNING.json (atomic temp + rename) and
        # the raw sweep rows into a sidecar for postmortems of a killed run
        if winners_so_far:
            tuning.write_tuning(out, platform, winners_so_far)
        tmp = f"{out}.sweep.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"platform": platform, "complete": False,
                       "rows": rows_so_far}, f, indent=1)
            f.write("\n")
        os.replace(tmp, out + ".sweep.json")

    winners, rows = sweep(kinds, pads, depths, args.n,
                          progress=lambda m: print(f"# {m}", file=sys.stderr,
                                                   flush=True),
                          group_sizes=group_sizes, commit=commit)
    tuning.write_tuning(out, platform, winners)
    tmp = f"{out}.sweep.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"platform": platform, "complete": True, "rows": rows}, f,
                  indent=1)
        f.write("\n")
    os.replace(tmp, out + ".sweep.json")
    print(json.dumps({"ok": True, "platform": platform, "out": out,
                      "winners": winners, "sweep": rows}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
