#!/usr/bin/env python
"""Stage-by-stage wall-clock profile of the G1-sig RLC verify pipeline
(round-3 structure: fused decompress+h2c front end, mixed GLV ladder).

Each stage is jitted separately and timed warm (median of reps) with
intermediates left on device; a trivial no-op program measures the axon
RPC dispatch overhead.  Run on the real chip:

    python tools/profile_stages.py [N ...]
"""
# tpu-vet: disable-file=verifier  (profiling tool measures the raw
# verifier stages; routing through the service would hide them)

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/drand_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir", "/tmp/drand_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

REPS = int(os.environ.get("REPS", "5"))


def timed(label, fn, *args):
    out = fn(*args)                     # compile + warm
    jax.block_until_ready(out)
    ts = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    ms = sorted(ts)[len(ts) // 2] * 1e3
    print(f"  {label:30s} {ms:9.1f} ms", flush=True)
    return out, ms


def profile(n):
    from drand_tpu.crypto import batch, schemes
    from drand_tpu.ops import curve as DC
    from drand_tpu.ops import h2c as DH
    from drand_tpu.ops import pairing as DP

    print(f"\n=== N = {n} ===", flush=True)
    sch = schemes.scheme_from_name(schemes.SHORT_SIG_SCHEME_ID)
    sec, pub = sch.keypair(seed=b"profile")
    ver = batch.BatchBeaconVerifier(sch, sch.public_bytes(pub))
    rounds = list(range(1, n + 1))
    msgs = [sch.digest_beacon(r, None) for r in rounds]
    sigs = batch.sign_batch(sch, sec, msgs)

    t0 = time.perf_counter()
    enc, bad = ver._encode(sigs, msgs, batch._pad_len(n))
    jax.block_until_ready(enc)
    print(f"  {'host _encode':30s} {(time.perf_counter()-t0)*1e3:9.1f} ms")
    sig_x, sign, u0, u1 = enc
    bits = batch._rlc_scalars(n, batch._pad_len(n), split=2)

    _, rpc = timed("axon rpc overhead (noop)", jax.jit(lambda x: x + 1),
                   jnp.zeros((8, 128), jnp.uint32))

    stages = {}
    (sig_jac, parse_ok, hm), stages["front"] = timed(
        "fused decompress+h2c front", jax.jit(DH.g1_decompress_and_hash),
        sig_x, sign, u0, u1)
    _, stages["subgroup"] = timed(
        "g1_in_subgroup (per-elt)", jax.jit(DC.g1_in_subgroup), sig_jac)

    both = jax.jit(
        lambda s, h: jax.tree.map(lambda a, b: jnp.concatenate([a, b], 0), s, h)
    )(sig_jac, hm)
    b0, b1 = bits
    bits2 = (jnp.concatenate([b0, b0], axis=1), jnp.concatenate([b1, b1], axis=1))
    mult, stages["glv_ladder"] = timed(
        "GLV mixed ladder (2N, incl. affine tables)",
        jax.jit(DC.g1_glv_msm_terms), both, *bits2)
    red, stages["sums"] = timed(
        "sum_points x2", jax.jit(lambda m: (
            DC.G1_DEV.sum_points(jax.tree.map(lambda t: t[:n], m)),
            DC.G1_DEV.sum_points(jax.tree.map(lambda t: t[n:], m)))), mult)
    aff, stages["to_affine"] = timed(
        "to_affine x2 (tail)", jax.jit(lambda ab: (
            DC.G1_DEV.to_affine(ab[0]), DC.G1_DEV.to_affine(ab[1]))), red)

    def pair(affs):
        (ax, ay, _), (bx, by, _) = affs
        px = jnp.stack([ax, bx])
        py = jnp.stack([ay, by])
        qx = jax.tree.map(lambda a, b: jnp.stack([a, b]),
                          ver.fixed_aff[0], ver.pk_aff[0])
        qy = jax.tree.map(lambda a, b: jnp.stack([a, b]),
                          ver.fixed_aff[1], ver.pk_aff[1])
        return DP.paired_product_is_one(px, py, (qx, qy), 2)

    ok, stages["pairing"] = timed("pairing product", jax.jit(pair), aff)
    assert bool(np.asarray(ok)), "pipeline verify failed"

    total = sum(stages.values())
    print(f"  {'-- stage sum':30s} {total:9.1f} ms   "
          f"(minus {len(stages)}x rpc {rpc:.0f} = "
          f"{total - len(stages)*rpc:.1f} ms)")

    _, e2e = timed("end-to-end _rlc_ok program",
                   lambda: ver._rlc_ok(enc, n))
    print(f"  {'=> rounds/s (e2e program)':30s} {n/ (e2e/1e3):9.1f}")



def profile_g2(n):
    """Per-stage profile of the G2-sig RLC pipeline (the default
    pedersen-bls-chained/-unchained family; VERDICT r3 #3's missing
    table).  Mirrors profile() over the round-4 structure: fused
    single-scan front end + psi-split joint ladder."""
    from drand_tpu.crypto import batch, schemes
    from drand_tpu.ops import curve as DC
    from drand_tpu.ops import h2c as DH
    from drand_tpu.ops import pairing as DP

    print(f"\n=== G2  N = {n} ===", flush=True)
    sch = schemes.scheme_from_name(schemes.UNCHAINED_SCHEME_ID)
    sec, pub = sch.keypair(seed=b"profile-g2")
    ver = batch.BatchBeaconVerifier(sch, sch.public_bytes(pub))
    rounds = list(range(1, n + 1))
    msgs = [sch.digest_beacon(r, None) for r in rounds]
    sigs = batch.sign_batch(sch, sec, msgs)

    t0 = time.perf_counter()
    enc, bad = ver._encode(sigs, msgs, batch._pad_len(n))
    jax.block_until_ready(enc)
    print(f"  {'host _encode':30s} {(time.perf_counter()-t0)*1e3:9.1f} ms")
    sig_x, sign, u0, u1 = enc
    b0, b1, b2, b3 = batch._rlc_scalars(n, batch._pad_len(n), split=4)

    _, rpc = timed("axon rpc overhead (noop)", jax.jit(lambda x: x + 1),
                   jnp.zeros((8, 128), jnp.uint32))

    stages = {}
    (sig_jac, parse_ok, hm), stages["front"] = timed(
        "fused decompress+h2c front", jax.jit(DH.g2_decompress_and_hash),
        sig_x[0], sig_x[1], sign, u0, u1)
    _, stages["subgroup"] = timed(
        "g2_in_subgroup (per-elt)", jax.jit(DC.g2_in_subgroup), sig_jac)

    base = jax.jit(lambda s, h: jax.tree.map(
        lambda *ts: jnp.concatenate(ts, 0),
        s, DC.g2_psi(s), h, DC.g2_psi(h)))(sig_jac, hm)
    bl = jnp.concatenate([b0, b1, b0, b1], axis=1)
    bh = jnp.concatenate([b2, b3, b2, b3], axis=1)
    mult, stages["glv_ladder"] = timed(
        "psi-split joint ladder (4N)",
        jax.jit(DC.g2_glv_msm_terms), base, bl, bh)
    n2 = 2 * b0.shape[1]
    red, stages["sums"] = timed(
        "sum_points x2", jax.jit(lambda m: (
            DC.G2_DEV.sum_points(jax.tree.map(lambda t: t[:n2], m)),
            DC.G2_DEV.sum_points(jax.tree.map(lambda t: t[n2:], m)))), mult)
    aff, stages["to_affine"] = timed(
        "to_affine x2 (tail)", jax.jit(lambda ab: (
            DC.G2_DEV.to_affine(ab[0]), DC.G2_DEV.to_affine(ab[1]))), red)

    def pair(affs):
        (ax, ay, _), (bx, by, _) = affs
        px = jnp.stack([ver.fixed_aff[0], ver.pk_aff[0]])
        py = jnp.stack([ver.fixed_aff[1], ver.pk_aff[1]])
        qx = jax.tree.map(lambda a, b: jnp.stack([a, b]), ax, bx)
        qy = jax.tree.map(lambda a, b: jnp.stack([a, b]), ay, by)
        return DP.paired_product_is_one(px, py, (qx, qy), 2)

    ok, stages["pairing"] = timed("pairing product", jax.jit(pair), aff)
    assert bool(np.asarray(ok)), "pipeline verify failed"

    total = sum(stages.values())
    print(f"  {'-- stage sum':30s} {total:9.1f} ms   "
          f"(minus {len(stages)}x rpc {rpc:.0f} = "
          f"{total - len(stages)*rpc:.1f} ms)")

    _, e2e = timed("end-to-end _rlc_ok program",
                   lambda: ver._rlc_ok(enc, n))
    print(f"  {'=> rounds/s (e2e program)':30s} {n/ (e2e/1e3):9.1f}")


def profile_pack(n, g2=False, reps=None):
    """--pack mode (ISSUE 14): host pack seconds per chunk PRE (host
    hash-to-field, the old path) vs POST (raw message words, device
    h2f), plus the warm end-to-end RLC pass per front — the committed
    before/after number for the pack term of the pack|queue|device
    split.  Prints one JSON line."""
    import json

    from drand_tpu.crypto import batch, schemes
    from drand_tpu.ops import h2c as DHH

    reps = reps or REPS
    sid = (schemes.UNCHAINED_SCHEME_ID if g2
           else schemes.SHORT_SIG_SCHEME_ID)
    sch = schemes.scheme_from_name(sid)
    sec, pub = sch.keypair(seed=b"profile-pack")
    rounds = list(range(1, n + 1))
    msgs = [sch.digest_beacon(r, None) for r in rounds]
    sigs = batch.sign_batch(sch, sec, msgs)

    out = {"mode": "pack_profile", "n": n, "kind": "g2" if g2 else "g1",
           "h2f_min_n": batch.h2f_device_min_n()}
    for label, h2f in (("host", False), ("device", True)):
        ver = batch.BatchBeaconVerifier(sch, sch.public_bytes(pub),
                                        h2f_device=h2f)
        ts = []
        hh0 = DHH.host_h2f_count()
        for _ in range(reps):
            t0 = time.perf_counter()
            packed = ver.pack_chunk(rounds, sigs)
            ts.append(time.perf_counter() - t0)
        out[f"pack_{label}_s_per_chunk"] = round(sorted(ts)[len(ts) // 2], 4)
        out[f"pack_{label}_host_hashed_msgs"] = \
            (DHH.host_h2f_count() - hh0) // reps
        # warm end-to-end pass so the pack win is read NEXT TO the device
        # cost it trades against (the device front re-hashes per pass)
        ver.resolve_packed(packed, ver.dispatch_packed(packed))
        packed = ver.pack_chunk(rounds, sigs)
        t0 = time.perf_counter()
        ok = ver.resolve_packed(packed, ver.dispatch_packed(packed))
        out[f"e2e_{label}_s"] = round(time.perf_counter() - t0, 4)
        assert ok.all(), "pack-profile fixture failed verification"
    out["pack_speedup"] = round(
        out["pack_host_s_per_chunk"] /
        max(1e-9, out["pack_device_s_per_chunk"]), 2)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    args = sys.argv[1:]
    g2 = "--g2" in args
    pack = "--pack" in args
    ns = [int(a) for a in args if not a.startswith("--")] or [4096]
    for n in ns:
        if pack:
            profile_pack(n, g2=g2)
        else:
            (profile_g2 if g2 else profile)(n)
