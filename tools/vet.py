#!/usr/bin/env python3
"""tpu-vet CLI: project-native static analysis for drand_tpu.

Usage:
    python tools/vet.py drand_tpu/                 # text report
    python tools/vet.py --format json drand_tpu/
    python tools/vet.py --format sarif drand_tpu/
    python tools/vet.py --checkers clock,lock drand_tpu/
    python tools/vet.py --baseline vet-baseline.json drand_tpu/
    python tools/vet.py --write-baseline vet-baseline.json drand_tpu/
    python tools/vet.py --changed drand_tpu tools  # only git-dirty files

--changed scopes the *reported* files to those touched per git (staged,
unstaged, and untracked), but still parses every file under the given
paths so the interprocedural checkers resolve calls into unchanged
code — an incremental run reports the same findings for a changed file
as a full run would.

Exit codes: 0 = clean, 1 = unsuppressed findings (or unparseable files),
2 = usage / internal error.

Imports no JAX: analysis parses target files, it never executes them —
a full-package run completes in a couple of seconds on the 2-core
CPU-only container.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from drand_tpu.analysis import (checker_names, load_baseline,  # noqa: E402
                                run_vet, write_baseline)
from drand_tpu.analysis.checkers import by_names  # noqa: E402


def _git_changed_files(scan_paths, base_ref=None):
    """Python files git considers touched, restricted to `scan_paths`.

    Union of unstaged, staged, and untracked (non-ignored) files, against
    the repository that CONTAINS the scan paths (not the one holding this
    tool).  With `base_ref`, files differing from the merge base of that
    ref (`git diff REF...`) join the union — the CI fast lane passes the
    PR's target branch here so a clean worktree still reports the whole
    branch diff.  Raises RuntimeError when git is unavailable or the
    paths are not inside a work tree.
    """
    import subprocess

    def run(cmd, cwd):
        try:
            out = subprocess.run(cmd, cwd=cwd, capture_output=True,
                                 text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired) as e:
            raise RuntimeError(f"git unavailable: {e}")
        if out.returncode != 0:
            raise RuntimeError(out.stderr.strip() or
                               f"{' '.join(cmd)} failed")
        return out.stdout

    first = os.path.abspath(scan_paths[0])
    anchor = first if os.path.isdir(first) else os.path.dirname(first)
    repo_root = run(["git", "rev-parse", "--show-toplevel"], anchor).strip()
    cmds = [["git", "diff", "--name-only", "HEAD"],
            ["git", "diff", "--name-only", "--cached"],
            ["git", "ls-files", "--others", "--exclude-standard"]]
    if base_ref:
        cmds.append(["git", "diff", "--name-only", f"{base_ref}..."])
    names = set()
    for cmd in cmds:
        names.update(ln.strip() for ln in run(cmd, repo_root).splitlines()
                     if ln.strip())
    roots = [os.path.abspath(p) for p in scan_paths]
    changed = []
    for name in sorted(names):
        if not name.endswith(".py"):
            continue
        ap = os.path.join(repo_root, name)
        if not os.path.isfile(ap):
            continue  # deleted files have no content to vet
        if any(ap == r or ap.startswith(r + os.sep) for r in roots):
            changed.append(ap)
    return changed


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="tpu-vet", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("paths", nargs="*", default=None,
                        help="files/directories to scan "
                             "(default: drand_tpu/)")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text")
    parser.add_argument("--changed", action="store_true",
                        help="scan only files git reports as changed "
                             "(staged + unstaged + untracked) under the "
                             "given paths; the rest are parsed for "
                             "cross-file resolution but not reported")
    parser.add_argument("--base-ref", default=None, metavar="REF",
                        help="with --changed, also include files that "
                             "differ from the merge base of REF "
                             "(git diff REF...) — the CI fast lane passes "
                             "the PR target branch here")
    parser.add_argument("--audit-suppressions", action="store_true",
                        help="also fail (exit 1) on stale suppression "
                             "comments and unused baseline budget; run "
                             "this on full scans only — a partial scan "
                             "cannot tell unused from out-of-scope")
    parser.add_argument("--checkers", default=None,
                        help="comma-separated subset "
                             f"(default: {','.join(checker_names())})")
    parser.add_argument("--baseline", default=None,
                        help="JSON baseline of grandfathered findings")
    parser.add_argument("--write-baseline", default=None, metavar="PATH",
                        help="write the current findings as a baseline "
                             "and exit 0")
    parser.add_argument("--list", action="store_true",
                        help="list available checkers and exit")
    args = parser.parse_args(argv)

    if args.list:
        from drand_tpu.analysis import ALL_CHECKERS
        for c in ALL_CHECKERS:
            print(f"{c.name:8s} {c.description}")
        return 0

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = args.paths or [os.path.join(repo_root, "drand_tpu")]
    for p in paths:
        if not os.path.exists(p):
            print(f"tpu-vet: no such path: {p}", file=sys.stderr)
            return 2

    checkers = None
    if args.checkers:
        try:
            checkers = by_names(
                [n.strip() for n in args.checkers.split(",") if n.strip()])
        except KeyError as e:
            print(f"tpu-vet: {e.args[0]}", file=sys.stderr)
            return 2

    baseline = None
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError) as e:
            print(f"tpu-vet: bad baseline: {e}", file=sys.stderr)
            return 2

    context_paths = ()
    if args.changed:
        try:
            changed = _git_changed_files(paths, base_ref=args.base_ref)
        except RuntimeError as e:
            print(f"tpu-vet: --changed needs git: {e}", file=sys.stderr)
            return 2
        if not changed:
            print("0 finding(s): no changed python files under "
                  + ", ".join(paths))
            return 0
        context_paths, paths = tuple(paths), changed

    try:
        report = run_vet(paths, checkers=checkers, baseline=baseline,
                         context_paths=context_paths)
    except Exception as e:  # noqa: BLE001 — a crash is an exit-2 bug, not findings
        print(f"tpu-vet: internal error: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        write_baseline(args.write_baseline, report)
        print(f"baseline written: {args.write_baseline} "
              f"({len(report.findings) + len(report.baselined)} findings)")
        return 0

    if args.format == "json":
        print(report.to_json())
    elif args.format == "sarif":
        print(report.to_sarif())
    else:
        print(report.render_text())

    rc = 0 if report.clean else 1
    if args.audit_suppressions:
        for line in report.stale_suppressions:
            print(f"stale-suppression: {line}", file=sys.stderr)
        for key in report.stale_baseline:
            print(f"stale-baseline: {key} (budget never consumed)",
                  file=sys.stderr)
        if report.stale_suppressions or report.stale_baseline:
            n = len(report.stale_suppressions) + len(report.stale_baseline)
            print(f"tpu-vet: {n} stale suppression/baseline entr"
                  f"{'y' if n == 1 else 'ies'} — remove them",
                  file=sys.stderr)
            rc = rc or 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
