"""Stage-level wall-clock profile of the batch-verify pipeline on the chip.

Each stage is jitted separately (axon adds ~0.1s dispatch per call — noted
in the numbers), so this is for RELATIVE stage weights, not absolutes.
Usage: python tools/chip_profile.py [N]
"""
# tpu-vet: disable-file=verifier  (profiling tool measures the raw
# verifier stages; routing through the service would hide them)
import sys, time
import jax
jax.config.update("jax_compilation_cache_dir", "/tmp/drand_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")
from drand_tpu.crypto import batch, schemes
from drand_tpu.ops import curve as DC, h2c as DH, limbs as L, pairing as DP

N = int(sys.argv[1]) if len(sys.argv) > 1 else 4096

sch = schemes.scheme_from_name(schemes.SHORT_SIG_SCHEME_ID)
sec, pub = sch.keypair(seed=b"profile")
verifier = batch.BatchBeaconVerifier(sch, sch.public_bytes(pub))
rounds = list(range(1, N + 1))
msgs = [sch.digest_beacon(r, None) for r in rounds]
sigs = batch.sign_batch(sch, sec, msgs)
(sig_x, sign, u0, u1), bad = verifier._encode(sigs, msgs, batch._pad_len(N))
bits = batch._rlc_scalars(N, batch._pad_len(N))

def timeit(name, fn, *args):
    out = jax.block_until_ready(fn(*args))     # compile + run
    t0 = time.perf_counter()
    out = jax.block_until_ready(fn(*args))
    print(f"{name:28s} {1e3*(time.perf_counter()-t0):8.1f} ms", flush=True)
    return out

def recover(sig_x, sign):
    return DH.g1_recover_y(sig_x, sign)

sig_jac, _ = timeit("decompress (device y)", jax.jit(recover), sig_x, sign)
sub_ok = timeit("subgroup check", jax.jit(DC.g1_in_subgroup), sig_jac)
hm = timeit("hash_to_g1 (h2c)", jax.jit(DH.hash_to_g1_jac), u0, u1)

def rlc_ladder(sig_jac, hm, bits):
    both = jax.tree.map(lambda a, b: jnp.concatenate([a, b], 0), sig_jac, hm)
    bits2 = jnp.concatenate([bits, bits], axis=1)
    return DC.G1_DEV.scalar_mul_bits(both, bits2)

mult = timeit("RLC ladder (2N x 128b)", jax.jit(rlc_ladder), sig_jac, hm, bits)

def sums(mult):
    n = bits.shape[1]
    A = DC.G1_DEV.sum_points(jax.tree.map(lambda t: t[:n], mult))
    B = DC.G1_DEV.sum_points(jax.tree.map(lambda t: t[n:], mult))
    return A, B

AB = timeit("point sums (2 trees)", jax.jit(sums), mult)

def affine(AB):
    A, B = AB
    ax, ay, _ = DC.G1_DEV.to_affine(A)
    bx, by, _ = DC.G1_DEV.to_affine(B)
    return ax, ay, bx, by

aff = timeit("to_affine (2 pts)", jax.jit(affine), AB)

def pairing_check(aff):
    ax, ay, bx, by = aff
    px = jnp.stack([ax, bx])
    py = jnp.stack([ay, by])
    qx = jax.tree.map(lambda a, b: jnp.stack([a, b]),
                      verifier.fixed_aff[0], verifier.pk_aff[0])
    qy = jax.tree.map(lambda a, b: jnp.stack([a, b]),
                      verifier.fixed_aff[1], verifier.pk_aff[1])
    return DP.paired_product_is_one(px, py, (qx, qy), 2)

ok = timeit("pairing product check", jax.jit(pairing_check), aff)
print("verified:", bool(ok))

t0 = time.perf_counter()
okf = verifier.verify_batch(rounds, sigs)
print(f"{'full verify_batch (warm)':28s} {1e3*(time.perf_counter()-t0):8.1f} ms  all={bool(okf.all())}")
