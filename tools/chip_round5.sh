#!/bin/bash
# Round-5 on-chip sequence (PERF.md / VERDICT r4 #1): run the moment the
# axon tunnel is reachable.  Each stage logs to /tmp/r5_chip_*.log and a
# failure stops the sequence (later stages trust earlier ones).
#
#   1. chip_validate_r4  — every r4 Mosaic kernel vs host goldens ON CHIP
#   2. bench.py          — all five configs; doubles as the cache prewarm
#   3. profile_stages    — per-stage device tables for PERF.md (G1 + G2)
#   4. 3M streamed replay — honest config-5 scale number (streamed_3m_s)
#
# After stage 2: do NOT edit drand_tpu/ops/*, crypto/batch.py, h2c.py or
# any traced-kernel file — Mosaic cache keys embed file:line and every
# edit forces a full recompile of every on-chip program (memory:
# jax-cache-key-instability).  Freeze first, prewarm second.
set -u
cd "$(dirname "$0")/.."

run() {
  local name="$1"; shift
  echo "=== $name: $* ==="
  local t0=$SECONDS
  "$@" > "/tmp/r5_chip_${name}.log" 2>&1
  local rc=$?
  echo "=== $name rc=$rc wall=$((SECONDS - t0))s (log /tmp/r5_chip_${name}.log)"
  [ $rc -ne 0 ] && tail -5 "/tmp/r5_chip_${name}.log"
  return $rc
}

run validate timeout 3600 python tools/chip_validate_r4.py || exit 1
run bench timeout 5400 python bench.py || exit 1
run profile_g1 timeout 1800 python tools/profile_stages.py 8192
run profile_g2 timeout 2400 python tools/profile_stages.py --g2 8192
# 366 x 8192 = 2,998,272 rounds streamed from a populated store; fixture
# generation on first run is device-signed and cached in /tmp (setup, not
# measurement) but adds real wall time — keep it last.
DRAND_TPU_BENCH_CONFIGS=5 DRAND_TPU_BENCH_N=2998272 \
  run stream3m timeout 9000 python bench.py
echo "=== chip sequence done; see /tmp/r5_chip_*.log"
