#!/usr/bin/env python
"""Round-4 on-chip validation: the new Mosaic kernels (Fp2 pow scan, G2
psi-split GLV ladder, recursive sum reduction, fused G2 front end) have
CPU-identical math (tests pin the direct lowering), but the compiled
Mosaic kernels themselves only run on the TPU — this drives each through
the package boundary at small N and cross-checks against the host golden
code before any bench/prewarm run trusts them.

    python tools/chip_validate_r4.py
"""
# tpu-vet: disable-file=clock  (offline operator tool: wall-clock timing
# of a one-shot validation run; no beacon schedule logic to fake-clock)
# tpu-vet: disable-file=verifier  (validation must drive the raw kernels
# and the real device inventory directly, bypassing the verify service
# on purpose)

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/drand_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir", "/tmp/drand_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

t0 = time.time()


def mark(s):
    print(f"[{time.time() - t0:7.1f}s] {s}", flush=True)


def main():
    assert jax.default_backend() in ("tpu", "axon"), jax.default_backend()
    mark(f"devices: {jax.devices()}")

    import random

    from drand_tpu.crypto.host import curve as C
    from drand_tpu.crypto.host import field as HF
    from drand_tpu.crypto.host import h2c as HH
    from drand_tpu.crypto.host import serialize as S
    from drand_tpu.crypto.host.params import DST_G2, P, R, X as BLS_X
    from drand_tpu.ops import curve as DC
    from drand_tpu.ops import h2c as DH
    from drand_tpu.ops import tower as T

    random.seed(7)

    # 1. Fp2 pow kernel
    xs = [(random.randrange(P), random.randrange(P)) for _ in range(4)]
    e = (P * P - 9) // 16
    a = (jnp.stack([T.encode_fp2(x)[0] for x in xs]),
         jnp.stack([T.encode_fp2(x)[1] for x in xs]))
    out = jax.jit(lambda a: T.fp2_pow_fixed(a, e))(a)
    got = [T.decode_fp2((out[0][i], out[1][i])) for i in range(4)]
    assert got == [HF.fp2_pow(x, e) for x in xs], "fp2 pow kernel"
    mark("fp2 pow kernel ok")

    # 2. fused G2 front end (sqrt_ratio scan + candidates + isogeny)
    msgs = [b"chip-%d" % i for i in range(4)]
    u0, u1 = DH.hash_msgs_to_field_g2(msgs, DST_G2)
    pts = jax.jit(DH.hash_to_g2_jac)(u0, u1)
    got = DC.decode_g2_points(pts)
    assert got == [HH.hash_to_curve_g2(m, DST_G2) for m in msgs], "g2 h2c"
    mark("G2 hash-to-curve kernel chain ok")

    from drand_tpu.crypto.batch import _wire_parse
    wire = [S.g2_to_bytes(p) for p in got]
    xw, sign, bad = _wire_parse(wire, True)
    sig_jac, ok, hm = jax.jit(DH.g2_decompress_and_hash)(
        jnp.asarray(np.ascontiguousarray(xw[:, 0])),
        jnp.asarray(np.ascontiguousarray(xw[:, 1])),
        jnp.asarray(sign), u0, u1)
    assert np.asarray(ok).all()
    assert DC.decode_g2_points(sig_jac) == got
    assert DC.decode_g2_points(hm) == got
    mark("fused G2 decompress+hash ok")

    # 3. psi-split GLV ladder kernel
    ks = [random.randrange(1, R) for _ in range(2)]
    host_pts = [C.G2.mul(C.G2.gen, k) for k in ks]
    q = DC.encode_g2_points(host_pts)
    k0 = [random.randrange(2 ** 32) for _ in range(2)]
    k1 = [random.randrange(2 ** 32) for _ in range(2)]
    b0 = DC.scalars_to_bits(k0, nbits=32)
    b1 = DC.scalars_to_bits(k1, nbits=32)
    gl = DC.decode_g2_points(jax.jit(DC.g2_glv_msm_terms)(q, b0, b1))
    full = [k0[i] + BLS_X ** 2 * k1[i] for i in range(2)]
    assert gl == [C.G2.mul(host_pts[i], full[i] % R) for i in range(2)], \
        "g2 glv kernel"
    mark("G2 psi-split GLV ladder kernel ok")

    # 4. recursive sum reduction at a two-level width (1024 lanes)
    n = 1024
    ks1 = [random.randrange(1, R) for _ in range(8)]
    hp = [C.G1.mul(C.G1.gen, k) for k in ks1]
    rows = [hp[i % 8] for i in range(n)]
    p1 = DC.encode_g1_points(rows)
    s = jax.jit(DC.G1_DEV.sum_points)(p1)
    want = None
    for pt in rows:
        want = C.G1.add(want, pt) if want else pt
    assert DC.decode_g1_points(jax.tree.map(lambda t: t[None], s))[0] == want, \
        "sum recursion"
    mark("recursive sum_points kernel ok (1024 lanes, 2 levels)")

    # 5. end-to-end small verify, both scheme families
    from drand_tpu.crypto import batch, schemes
    for sid in (schemes.SHORT_SIG_SCHEME_ID, schemes.UNCHAINED_SCHEME_ID):
        sch = schemes.scheme_from_name(sid)
        sec, pub = sch.keypair(seed=b"chipval")
        ms = [sch.digest_beacon(r, None) for r in range(1, 9)]
        sigs = batch.sign_batch(sch, sec, ms)
        ver = batch.BatchBeaconVerifier(sch, sch.public_bytes(pub))
        assert ver.verify_batch(list(range(1, 9)), sigs).all(), sid
        # one corrupted signature must be caught
        bad_sigs = list(sigs)
        bad_sigs[3] = sigs[4]
        got = ver.verify_batch(list(range(1, 9)), bad_sigs)
        assert not got[3] and got.sum() == 7, (sid, got)
        mark(f"end-to-end verify ok ({sid})")

    print("CHIP VALIDATION: ALL OK")


if __name__ == "__main__":
    main()
