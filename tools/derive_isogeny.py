"""Derive the BLS12-381 G1 11-isogeny map (RFC 9380 appendix E.2 equivalent).

With no network access and no local copy of the RFC constants, we *derive* the
isogeny from first principles:

  1. Build the 11-division polynomial of E1': y^2 = x^3 + A*x + B
     (A = ISO_A1, B = ISO_B1 from params).
  2. Factor out the degree-5 kernel polynomial(s) (x-coords of the order-11
     subgroups) via distinct/equal-degree factorization.
  3. Apply Velu/Kohel's formulas to get the normalized isogeny x-map
     N(x)/h(x)^2 and y-map y*(N'h - 2Nh')/h^3, and the codomain curve.
  4. Post-compose with the isomorphism (x,y) -> (c^2 x, c^3 y) landing on
     E1: y^2 = x^3 + 4, enumerating all 6th roots c (automorphism ambiguity).
  5. Disambiguate the candidate maps end-to-end against the public drand
     mainnet G1-scheme beacon (crypto/schemes_test.go round-3 vector): only
     the RFC 9380 map makes the real-world signature verify.

Writes drand_tpu/crypto/host/_iso_g1.py.  Run once: python tools/derive_isogeny.py
"""
# tpu-vet: disable-file=clock  (offline derivation script: time.time()
# is progress reporting for an hours-long symbolic computation)

import sys, os, random, time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from drand_tpu.crypto.host.params import P, ISO_A1, ISO_B1
random.seed(1138)

# ---------------------------------------------------------------------------
# Dense polynomial arithmetic over Fp (lists, constant term first)
# ---------------------------------------------------------------------------

def pnorm(a):
    while a and a[-1] == 0:
        a.pop()
    return a

def padd(a, b):
    n = max(len(a), len(b))
    return pnorm([((a[i] if i < len(a) else 0) + (b[i] if i < len(b) else 0)) % P for i in range(n)])

def psub(a, b):
    n = max(len(a), len(b))
    return pnorm([((a[i] if i < len(a) else 0) - (b[i] if i < len(b) else 0)) % P for i in range(n)])

def pmul(a, b):
    if not a or not b:
        return []
    out = [0] * (len(a) + len(b) - 1)
    for i, ai in enumerate(a):
        if ai:
            for j, bj in enumerate(b):
                out[i + j] = (out[i + j] + ai * bj) % P
    return pnorm(out)

def pscale(a, k):
    k %= P
    return pnorm([ai * k % P for ai in a])

def pdivmod(a, b):
    """quotient, remainder; b nonzero."""
    a = a[:]
    db, da = len(b) - 1, len(a) - 1
    if da < db:
        return [], pnorm(a)
    binv = pow(b[-1], P - 2, P)
    q = [0] * (da - db + 1)
    for k in range(da - db, -1, -1):
        if len(a) - 1 < db + k:
            continue
        c = a[db + k] * binv % P
        if c == 0:
            continue
        q[k] = c
        for j in range(db + 1):
            a[k + j] = (a[k + j] - c * b[j]) % P
        pnorm(a)
    return pnorm(q), pnorm(a)

def pmod(a, b):
    return pdivmod(a, b)[1]

def pgcd(a, b):
    while b:
        a, b = b, pmod(a, b)
    if a:
        a = pscale(a, pow(a[-1], P - 2, P))  # monic
    return a

def pmulmod(a, b, m):
    return pmod(pmul(a, b), m)

def ppowmod(a, e, m):
    out = [1]
    base = pmod(a, m)
    while e:
        if e & 1:
            out = pmulmod(out, base, m)
        base = pmulmod(base, base, m)
        e >>= 1
    return out

def pderiv(a):
    return pnorm([a[i] * i % P for i in range(1, len(a))])

def peval(a, x):
    acc = 0
    for c in reversed(a):
        acc = (acc * x + c) % P
    return acc

# ---------------------------------------------------------------------------
# Division polynomial psi_11 for y^2 = x^3 + Ax + B
# Representation: (poly, e) meaning poly(x) * (2y)^e, e in {0,1}; (2y)^2 = 4F.
# ---------------------------------------------------------------------------

A, B = ISO_A1, ISO_B1
Fpoly = [B % P, A % P, 0, 1]  # x^3 + Ax + B
F4 = pscale(Fpoly, 4)

def ymul(a, b):
    pa, ea = a
    pb, eb = b
    e = ea + eb
    out = pmul(pa, pb)
    while e >= 2:
        out = pmul(out, F4)
        e -= 2
    return (out, e)

def ysub(a, b):
    assert a[1] == b[1], "parity mismatch"
    return (psub(a[0], b[0]), a[1])

def ypow(a, k):
    out = ([1], 0)
    for _ in range(k):
        out = ymul(out, a)
    return out

def division_poly_11():
    psi = {1: ([1], 0), 2: ([1], 1)}
    psi[3] = (pnorm([(-A * A) % P, 12 * B % P, 6 * A % P, 0, 3]), 0)
    g4 = pnorm([(-8 * B * B - A**3) % P, (-4 * A * B) % P, (-5 * A * A) % P,
                20 * B % P, 5 * A % P, 0, 1])
    psi[4] = (pscale(g4, 2), 1)  # psi4 = 2*(2y)*g4 = 4y*g4
    # psi5 = psi4*psi2^3 - psi1*psi3^3
    psi[5] = ysub(ymul(psi[4], ypow(psi[2], 3)), ypow(psi[3], 3))
    # psi6 = psi3*(psi5*psi2^2 - psi1*psi4^2)/psi2  -> compute via generic rule:
    # psi_{2m} = psi_m*(psi_{m+2}*psi_{m-1}^2 - psi_{m-2}*psi_{m+1}^2)/(2y)
    def even(m):
        num = ysub(ymul(psi[m + 2], ypow(psi[m - 1], 2)), ymul(psi[m - 2], ypow(psi[m + 1], 2)))
        prod = ymul(psi[m], num)
        pp, e = prod
        assert e == 1, f"even psi_{2*m} parity {e}"
        return (pp, 1)  # dividing by (2y) then multiplying by... keep as is
    # careful: psi_{2m} = psi_m * num / (2y).  prod = psi_m*num has e==1 meaning
    # poly*(2y); dividing by (2y) leaves a pure polynomial -> but psi_even must
    # carry a 2y factor.  Resolve parities explicitly below instead.
    # m=3: psi6 = psi3*num/(2y); num = psi5*psi2^2 - psi1*psi4^2
    num = ysub(ymul(psi[5], ypow(psi[2], 2)), ypow(psi[4], 2))
    # num has e=0 (both terms even powers of 2y)
    assert num[1] == 0
    # psi3*num is pure; dividing by 2y... psi6 = (2y)*g6 requires num divisible by 4F
    q, r = pdivmod(pmul(psi[3][0], num[0]), F4)
    assert not r, "psi6: expected divisibility by 4F"
    psi[6] = (q, 1)  # psi6 = psi3*num/(2y) = (2y)*[psi3*num/4F]
    # psi7 = psi5*psi3^3 - psi2*psi4^3   (m=3)
    psi[7] = ysub(ymul(psi[5], ypow(psi[3], 3)), ymul(psi[2], ypow(psi[4], 3)))
    # psi11 = psi7*psi5^3 - psi4*psi6^3  (m=5)
    p11 = ysub(ymul(psi[7], ypow(psi[5], 3)), ymul(psi[4], ypow(psi[6], 3)))
    assert p11[1] == 0
    return p11[0]

# ---------------------------------------------------------------------------
# Factorization helpers
# ---------------------------------------------------------------------------

def frobenius_powers(m):
    """x^(p^k) mod m for k = 1..5 via modular composition."""
    xp = ppowmod([0, 1], P, m)
    frob = [None, xp]
    for k in range(2, 6):
        frob.append(pcompose(frob[k - 1], xp, m))
    return frob

def pcompose(f, g, m):
    """f(g(x)) mod m via Horner."""
    acc = []
    for c in reversed(f):
        acc = padd(pmulmod(acc, g, m), [c])
    return pmod(acc, m)

def equal_degree_split(f, d):
    """Cantor-Zassenhaus: f = product of irreducibles of degree d; return factors."""
    n = len(f) - 1
    if n == d:
        return [f]
    while True:
        g = [random.randrange(P) for _ in range(n)]
        g = pnorm(g)
        e = (pow(P, d) - 1) // 2
        h = ppowmod(g, e, f)
        h = psub(h, [1])
        c = pgcd(h, f)
        if c and 0 < len(c) - 1 < n:
            q, r = pdivmod(f, c)
            assert not r
            return equal_degree_split(c, d) + equal_degree_split(pscale(q, pow(q[-1], P-2, P)), d)

# ---------------------------------------------------------------------------
# Velu/Kohel isogeny from kernel polynomial
# ---------------------------------------------------------------------------

def newton_power_sums(h, upto):
    """p1..p_upto for monic h of degree d (roots with multiplicity)."""
    d = len(h) - 1
    # h = x^d + c_{d-1} x^{d-1} + ... ; e_k = (-1)^k * c_{d-k}
    e = [0] * (d + 1)
    e[0] = 1
    for k in range(1, d + 1):
        e[k] = (-1) ** k * h[d - k] % P
    ps = [0] * (upto + 1)
    for k in range(1, upto + 1):
        s = 0
        for i in range(1, min(k, d + 1)):  # k>d: full Newton sum i=1..d
            s += (-1) ** (i - 1) * e[i] * ps[k - i]
        if k <= d:
            s += (-1) ** (k - 1) * k * e[k]
        ps[k] = s % P
    return ps

def _sum_over_kernel_roots(num, den, h, psums):
    """sum over roots alpha of h of num(alpha)/den(alpha), via reduction mod h
    and power sums of h's roots."""
    dinv = pinvmod(den, h)
    c = pmulmod(num, dinv, h)
    # sum_j c_j * p_j  (p_0 = deg h)
    total = 0
    d = len(h) - 1
    for j, cj in enumerate(c):
        total += cj * (d if j == 0 else psums[j])
    return total % P


def pinvmod(a, m):
    """inverse of a mod m (extended euclid over Fp[x])."""
    r0, r1 = m[:], pmod(a, m)
    s0, s1 = [], [1]
    while r1:
        q, r2 = pdivmod(r0, r1)
        r0, r1 = r1, r2
        s0, s1 = s1, psub(s0, pmul(q, s1))
    # r0 = gcd (degree 0 expected)
    assert len(r0) == 1, "not invertible mod h"
    return pscale(s0, pow(r0[0], P - 2, P))


def lagrange_interp(pts):
    """Polynomial through points [(x_i, y_i)] mod p (O(n^2))."""
    n = len(pts)
    poly = []
    for i, (xi, yi) in enumerate(pts):
        # basis poly prod_{j!=i} (x - x_j)/(x_i - x_j)
        num = [1]
        denom = 1
        for j, (xj, _) in enumerate(pts):
            if j == i:
                continue
            num = pmul(num, [(-xj) % P, 1])
            denom = denom * (xi - xj) % P
        poly = padd(poly, pscale(num, yi * pow(denom, P - 2, P) % P))
    return poly


def velu_from_kernel(h):
    """Normalized Velu isogeny with kernel poly h, built numerically from
    phi(x) = x + sum_{Q != O} (x_{P+Q} - x_Q).  Returns (Nx, Dx, b_codomain)."""
    d = len(h) - 1
    psums = newton_power_sums(h, d + 3)
    h2 = pmul(h, h)

    def phi_at(x0):
        f0 = peval(Fpoly, x0)
        # per +-pair of kernel points with x-coord alpha:
        #   (x_{P+Q} - alpha) + (x_{P-Q} - alpha)
        #     = 2(F(x0)+F(alpha))/(x0-alpha)^2 - 2*x0 - 4*alpha
        # Sum over roots alpha of h.
        num = padd([2 * f0 % P], pscale(Fpoly, 2))            # 2F(x0) + 2F(alpha)
        den = pmul([(-x0) % P, 1], [(-x0) % P, 1])            # (alpha - x0)^2
        s = _sum_over_kernel_roots(num, den, h, psums)
        s = (s - 2 * x0 * d - 4 * psums[1]) % P
        return (x0 + s) % P

    # interpolate N(x) = phi(x) * h(x)^2, degree 2d+1
    pts = []
    x0 = 7
    while len(pts) < 2 * d + 2 + 3:
        if peval(h, x0) != 0:
            pts.append((x0, phi_at(x0) * peval(h2, x0) % P))
        x0 += 1
    Nx = lagrange_interp(pts[: 2 * d + 2])
    for xv, yv in pts[2 * d + 2:]:
        assert peval(Nx, xv) == yv, "interpolation inconsistent"
    assert len(Nx) - 1 == 2 * d + 1, f"unexpected deg Nx = {len(Nx)-1}"

    # codomain b from a sample image point: y-map = y * phi'(x)
    hp = pderiv(h)
    My = psub(pmul(pderiv(Nx), h), pscale(pmul(Nx, hp), 2))   # (N'h - 2Nh')
    Ky = pmul(h2, h)
    while True:
        xs, ys = sample_point_Eprime()
        if peval(h, xs) == 0:
            continue
        xo = peval(Nx, xs) * pow(peval(h2, xs), P - 2, P) % P
        yo = ys * peval(My, xs) % P * pow(peval(Ky, xs), P - 2, P) % P
        b_cod = (yo * yo - pow(xo, 3, P)) % P
        # sanity on a second point
        xs2, ys2 = sample_point_Eprime()
        if peval(h, xs2) == 0:
            continue
        xo2 = peval(Nx, xs2) * pow(peval(h2, xs2), P - 2, P) % P
        yo2 = ys2 * peval(My, xs2) % P * pow(peval(Ky, xs2), P - 2, P) % P
        assert (yo2 * yo2 - pow(xo2, 3, P)) % P == b_cod, "codomain has a != 0?"
        return (Nx, h2, b_cod)

def sample_point_Eprime():
    while True:
        x = random.randrange(P)
        fy = peval(Fpoly, x)
        y = pow(fy, (P + 1) // 4, P)
        if y * y % P == fy:
            return (x, y)

def main():
    t0 = time.time()
    print("building psi_11 ...")
    psi11 = division_poly_11()
    print(f"  deg = {len(psi11)-1}  ({time.time()-t0:.1f}s)")
    assert len(psi11) - 1 == 60
    psi11 = pscale(psi11, pow(psi11[-1], P - 2, P))

    print("computing Frobenius powers mod psi_11 ...")
    frob = frobenius_powers(psi11)
    print(f"  done ({time.time()-t0:.1f}s)")

    kernels = []
    g1 = pgcd(psub(frob[1], [0, 1]), psi11)
    print(f"deg of rational-root part: {len(g1)-1 if g1 else 0}")
    if g1 and len(g1) - 1 == 5:
        # exactly one kernel's worth of rational x-coords: g1 IS the kernel poly
        kernels.append(g1)
    else:
        if g1:
            raise NotImplementedError(f"unexpected rational-root degree {len(g1)-1}")
        # degree-5 orbits: x-coords fixed by frob^5
        g5 = pgcd(psub(frob[5], [0, 1]), psi11)
        print(f"deg fixed by frob^5: {len(g5)-1 if g5 else 0}")
        if g5 and (len(g5) - 1) % 5 == 0 and len(g5) > 1:
            kernels.extend(equal_degree_split(g5, 5))
    print(f"candidate kernel polys: {len(kernels)}  ({time.time()-t0:.1f}s)")

    results = []
    for h in kernels:
        out = velu_from_kernel(h)
        if out is None:
            print("  kernel rejected (codomain not j=0)")
            continue
        Nx, Dx, b_cod = out
        results.append((h, Nx, Dx, b_cod))
        print(f"  kernel ok: codomain b = {hex(b_cod)[:20]}...")

    candidates = []
    for h, Nx, Dx, b_cod in results:
        # isomorphism (x,y)->(c^2 x, c^3 y) sends y^2=x^3+b to y^2=x^3+c^6*b,
        # so land on b=4 with c^6 = 4 / b_cod
        target = 4 * pow(b_cod, P - 2, P) % P
        # find all 6th roots of target in Fp
        roots = nth_roots(target, 6)
        print(f"  {len(roots)} sixth-roots of b_cod/4")
        hp = pderiv(h)
        # y-map numerator/denominator: y * (Nx' h - 2 Nx h') / h^3
        My = psub(pmul(pderiv(Nx), h), pscale(pmul(Nx, hp), 2))
        Ky = pmul(pmul(h, h), h)
        for c in roots:
            c2, c3 = c * c % P, pow(c, 3, P)
            cand = (pscale(Nx, c2), Dx, pscale(My, c3), Ky)
            # sanity: maps E' points onto E
            ok = True
            for _ in range(4):
                x, y = sample_point_Eprime()
                xo = peval(cand[0], x) * pow(peval(cand[1], x), P - 2, P) % P
                yo = y * peval(cand[2], x) % P * pow(peval(cand[3], x), P - 2, P) % P
                if (yo * yo - xo**3 - 4) % P:
                    ok = False
                    break
            if ok:
                candidates.append(cand)
        print(f"  validated candidates so far: {len(candidates)}")

    print(f"total on-curve candidate maps: {len(candidates)} ({time.time()-t0:.1f}s)")
    disambiguate(candidates)

def nth_roots(a, n):
    """All n-th roots of a in Fp (p-1 divisible by 6)."""
    if a == 0:
        return [0]
    # check a is an n-th power: a^((p-1)/g) == 1 with g = gcd(n, p-1)
    from math import gcd
    g = gcd(n, P - 1)
    if pow(a, (P - 1) // g, P) != 1:
        return []
    # find one root by Tonelli-ish: n | p-1 here (p = 1 mod 6)
    # use the fact p = 3 mod 4 and p = 1 mod 3: 6th root = sqrt(cbrt)
    def cbrt(v):
        # p = 1 mod 3: cube roots exist iff v^((p-1)/3)==1; find via exponent
        if v == 0:
            return 0
        e = pow(v, (P - 1) // 3, P)
        if e != 1:
            return None
        # write p = 3k+1; x^3 = v; if gcd(3,(p-1)/3): use Adleman-Manders-Miller lite:
        # try exponent inv(3) mod (p-1)/3^s ... do simple search via random
        # structure: let m = (p-1)//3; solutions are v^t where 3t = 1 mod m if gcd(3,m)=1
        m = (P - 1) // 3
        if m % 3 != 0:
            t = pow(3, -1, m)
            r = pow(v, t, P)
            if pow(r, 3, P) == v:
                return r
        # fallback: AMM general
        return amm_root(v, 3)
    def sqrtp(v):
        s = pow(v, (P + 1) // 4, P)
        return s if s * s % P == v else None
    c = cbrt(a)
    if c is None:
        return []
    s = sqrtp(c)
    if s is None:
        # try other cube roots: multiply by primitive cube root of unity
        w3 = find_root_of_unity(3)
        found = None
        for k in (1, 2):
            cc = c * pow(w3, k, P) % P
            s = sqrtp(cc)
            if s is not None:
                found = s
                break
        if found is None:
            return []
        s = found
    w6 = find_root_of_unity(6)
    roots = sorted({s * pow(w6, k, P) % P for k in range(6) if pow(s * pow(w6, k, P) % P, 6, P) == a})
    return roots

_rou_cache = {}
def find_root_of_unity(n):
    if n in _rou_cache:
        return _rou_cache[n]
    while True:
        g = random.randrange(2, P)
        r = pow(g, (P - 1) // n, P)
        if all(pow(r, n // q, P) != 1 for q in {2, 3} if n % q == 0):
            _rou_cache[n] = r
            return r

def amm_root(v, ell):
    """Adleman-Manders-Miller ell-th root for ell | p-1 (returns one root or None)."""
    t, s = P - 1, 0
    while t % ell == 0:
        t //= ell
        s += 1
    if pow(v, (P - 1) // ell, P) != 1:
        return None
    while True:
        rho = random.randrange(2, P)
        if pow(rho, (P - 1) // ell, P) != 1:
            break
    g = pow(rho, t, P)  # generator of the ell-Sylow subgroup (order ell^s)
    alpha = pow(ell, -1, t)
    x = pow(v, alpha, P)
    c = pow(x, ell, P) * pow(v, P - 2, P) % P  # in Sylow subgroup
    # discrete log of c base g (order ell^s), digit by digit
    k = 0
    gamma = pow(g, ell ** (s - 1), P)  # order ell
    for i in range(s):
        e = pow(c * pow(g, (-k) % (ell ** s * 1), P) % P, ell ** (s - 1 - i), P)
        d, acc = 0, 1
        while acc != e:
            acc = acc * gamma % P
            d += 1
            assert d < ell, "dlog digit not found"
        k += d * ell ** i
    if k % ell != 0:
        return None
    m = (-(k // ell)) % (ell ** s)
    y = pow(g, m, P)
    root = x * y % P
    assert pow(root, ell, P) == v
    return root

def disambiguate(candidates):
    """Test each candidate map end-to-end on the drand G1-scheme mainnet vector."""
    import hashlib
    import drand_tpu.crypto.host.h2c as h2c
    from drand_tpu.crypto.host.serialize import g1_from_bytes, g2_from_bytes
    from drand_tpu.crypto.host.pairing import pairing_check
    from drand_tpu.crypto.host.curve import G2 as G2curve, g1_clear_cofactor

    # drand "fastnet" G1-scheme vector: round 3, bls-unchained-on-g1
    pub = g2_from_bytes(bytes.fromhex(
        "876f6fa8073736e22f6ff4badaab35c637503718f7a452d178ce69c45d2d8129"
        "a54ad2f988ab10c9666f87ab603c59bf013409a5b500555da31720f8eec294d9"
        "809b8796f40d5372c71a44ca61226f1eb978310392f98074a608747f77e66c5a"))
    sig = g1_from_bytes(bytes.fromhex(
        "ac7c3ca14bc88bd014260f22dc016b4fe586f9313c3a549c83d195811a99a5d2"
        "d4999d4df6daec73ff51fafadd6d5bb5"))
    msg = hashlib.sha256((3).to_bytes(8, "big")).digest()

    dsts = [b"BLS_SIG_BLS12381G1_XMD:SHA-256_SSWU_RO_NUL_",
            b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_NUL_"]
    winner = None
    for ci, cand in enumerate(candidates):
        XN, XD, YN, YD = cand

        def iso(pt):
            if pt is None:
                return None
            x, y = pt
            xo = peval(XN, x) * pow(peval(XD, x), P - 2, P) % P
            yo = y * peval(YN, x) % P * pow(peval(YD, x), P - 2, P) % P
            return (xo, yo)

        for dst in dsts:
            u0, u1 = h2c.hash_to_field_fp(msg, dst, 2)
            q0 = h2c._sswu_fp(u0)
            q1 = h2c._sswu_fp(u1)
            r = h2c._affine_add_fp(q0, q1, A)
            pt = g1_clear_cofactor(iso(r))
            ok = pairing_check([(pt, pub), (h2c.G1.neg(sig), G2curve.gen)])
            print(f"  candidate {ci} dst={dst[:24]}...: verify={ok}")
            if ok:
                winner = (cand, dst)
    if winner is None:
        print("NO CANDIDATE VERIFIED — investigate")
        sys.exit(1)
    (XN, XD, YN, YD), dst = winner
    path = os.path.join(os.path.dirname(__file__), "..", "drand_tpu", "crypto", "host", "_iso_g1.py")
    with open(path, "w") as f:
        f.write('"""Generated by tools/derive_isogeny.py — BLS12-381 G1 11-isogeny map.\n\n')
        f.write("Coefficient lists are constant-term-first.  Derived from the curve\n")
        f.write("parameters via division-polynomial kernel extraction + Velu's formulas,\n")
        f.write("pinned by the drand mainnet G1-scheme known-answer vector.\n")
        f.write(f'Verifying DST: {dst!r}\n"""\n\n')
        for name, coeffs in (("XNUM", XN), ("XDEN", XD), ("YNUM", YN), ("YDEN", YD)):
            f.write(f"{name} = [\n")
            for c in coeffs:
                f.write(f"    0x{c:096x},\n")
            f.write("]\n\n")
    print(f"wrote {path}; verifying DST = {dst!r}")

if __name__ == "__main__":
    main()
