#!/usr/bin/env python
"""Operator CLI for the process-fleet chaos harness (tests/fleet.py):

    python tools/fleet.py smoke  [--nodes 5] [--rounds 5] [--seed 7]
    python tools/fleet.py soak   [--nodes 32] [--rounds 20] [--seed 7]
    python tools/fleet.py plan   [--nodes 9] [--rounds 30] [--seed 7]

`smoke` runs the canned acceptance scenario (DKG + Handel rounds +
SIGKILL/restart + partition/heal + graceful teardown) at tier-1 size.
`soak` spawns a bigger fleet and executes the full seeded FaultPlan —
kills, rolling restarts, freezes, partitions, link delay/reset — then
checks every invariant.  `plan` just prints the deterministic fault
schedule for a seed (same seed => same schedule, byte for byte).

Every run is bounded: subprocess reaps, ready-file polls, and round
waits all carry deadlines (enforced statically by tpu-vet's `deadline`
checker, which scopes this file by name) — a wedged fleet dies in
minutes, not hangs a terminal.
"""

import argparse
import json
import os
import shutil
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tests"))


def cmd_plan(args) -> int:
    from fleet import FaultPlan
    plan = FaultPlan(args.seed, args.nodes, args.rounds)
    print(f"seed={plan.seed} n={plan.n} rounds={plan.rounds} "
          f"digest={plan.digest()}")
    for at, kind, params in plan.events:
        print(f"  round {at:>3}: {kind:<16} {json.dumps(params)}")
    return 0


def cmd_smoke(args) -> int:
    from fleet import FleetError, smoke_soak
    base = args.dir or tempfile.mkdtemp(prefix="drand-fleet-")
    try:
        result = smoke_soak(base, n=args.nodes, rounds=args.rounds,
                            seed=args.seed, period=args.period,
                            mtls=args.mtls)
    except FleetError as e:
        print(f"FLEET INVARIANT FAILED: {e}", file=sys.stderr)
        print(f"folders kept for diagnosis: {base}", file=sys.stderr)
        return 1
    print(json.dumps({k: v for k, v in result.items()
                      if k != "proxy_stats"}, indent=2))
    if not args.keep:
        shutil.rmtree(base, ignore_errors=True)
    return 0


def cmd_soak(args) -> int:
    from fleet import FaultPlan, Fleet, FleetError, FleetInvariants
    base = args.dir or tempfile.mkdtemp(prefix="drand-fleet-")
    plan = FaultPlan(args.seed, args.nodes, args.rounds)
    print(f"fault plan digest {plan.digest()} "
          f"({len(plan.events)} events)")
    try:
        with Fleet(args.nodes, base, period=args.period,
                   seed=args.seed, mtls=args.mtls) as fleet:
            fleet.start()
            fleet.run_dkg()
            fleet.execute(plan)
            inv = FleetInvariants(fleet)
            compared = inv.assert_no_fork(plan.rounds)
            inv.assert_restart_counts()
            codes = fleet.stop_all()
            inv.assert_clean_exit(codes)
    except FleetError as e:
        print(f"FLEET INVARIANT FAILED: {e}", file=sys.stderr)
        print(f"folders kept for diagnosis: {base}", file=sys.stderr)
        return 1
    print(f"soak OK: {args.nodes} nodes, {plan.rounds} rounds, "
          f"{compared} fork-compared, exits {codes}")
    if not args.keep:
        shutil.rmtree(base, ignore_errors=True)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name, fn in (("plan", cmd_plan), ("smoke", cmd_smoke),
                     ("soak", cmd_soak)):
        p = sub.add_parser(name)
        p.add_argument("--nodes", type=int,
                       default=5 if name != "soak" else 32)
        p.add_argument("--rounds", type=int,
                       default=5 if name != "soak" else 20)
        p.add_argument("--seed", type=int, default=7)
        p.add_argument("--period", type=int, default=3)
        p.add_argument("--dir", help="fleet base dir (default: tmpdir)")
        p.add_argument("--keep", action="store_true",
                       help="keep node folders after a green run")
        p.add_argument("--mtls", action="store_true",
                       help="provision a private CA + per-node certs "
                            "and run every gRPC plane over mutual TLS "
                            "(net/identity.py)")
        p.set_defaults(fn=fn)
    args = ap.parse_args()
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
