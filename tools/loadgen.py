#!/usr/bin/env python
"""Serving-plane load generator (ROADMAP item 5a: "add a load-generation
harness and measure rounds served/sec").

Drives the REST edge and/or the public gRPC plane with either a CLOSED
loop (N clients back-to-back — measures capacity) or an OPEN loop
(Poisson-ish arrivals at --rate req/s — measures behavior under a fixed
offered load, the regime where shedding matters), and reports:

    rounds_served_per_s   successful reads per second of wall time
    shed_ratio            429/RESOURCE_EXHAUSTED responses / attempts
    shed_well_formed      every 429 carried Retry-After (and every gRPC
                          shed was RESOURCE_EXHAUSTED, not a mystery)
    latency_p50/p99       client-observed seconds
    admission             the daemon's /health admission block (level +
                          per-class queue-wait p99, incl. the partials/
                          critical p99 the acceptance criterion names)

Usage:
    python tools/loadgen.py --rest http://127.0.0.1:8080 --mode closed \
        --clients 16 --duration 10
    python tools/loadgen.py --grpc 127.0.0.1:4444 --mode open --rate 500
    python tools/loadgen.py --selftest [--json]

--selftest needs no running daemon: it spins an in-process REST edge over
a real-crypto chain with a deliberately tiny admission pool, floods it,
and exits 0 iff reads were served, sheds happened, and every shed was
well-formed — the CI hook bench.py records (loadgen_* keys)."""

import argparse
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import List, Optional

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tests"))


@dataclass
class LoadReport:
    target: str
    mode: str
    duration: float
    attempted: int = 0
    ok: int = 0
    shed: int = 0
    errors: int = 0
    malformed_sheds: int = 0
    latencies: List[float] = field(default_factory=list)
    admission: Optional[dict] = None
    # --tenants: per-chain breakdown (key = chain hash / tenant label)
    by_tenant: Optional[dict] = None

    @property
    def rounds_served_per_s(self) -> float:
        return self.ok / self.duration if self.duration > 0 else 0.0

    @property
    def shed_ratio(self) -> float:
        return self.shed / self.attempted if self.attempted else 0.0

    @property
    def shed_well_formed(self) -> bool:
        return self.malformed_sheds == 0

    def _pct(self, q: float) -> float:
        if not self.latencies:
            return 0.0
        s = sorted(self.latencies)
        return s[min(len(s) - 1, int(round(q * (len(s) - 1))))]

    def to_dict(self) -> dict:
        return {
            "target": self.target, "mode": self.mode,
            "duration_s": round(self.duration, 2),
            "attempted": self.attempted, "ok": self.ok,
            "shed": self.shed, "errors": self.errors,
            "rounds_served_per_s": round(self.rounds_served_per_s, 1),
            "shed_ratio": round(self.shed_ratio, 4),
            "shed_well_formed": self.shed_well_formed,
            "latency_p50_s": round(self._pct(0.50), 4),
            "latency_p99_s": round(self._pct(0.99), 4),
            "admission": self.admission,
            **({"by_tenant": self.by_tenant} if self.by_tenant else {}),
        }

    def render(self) -> str:
        d = self.to_dict()
        lines = [f"{k:22}: {v}" for k, v in d.items()
                 if k not in ("admission", "by_tenant")]
        if d["admission"]:
            lines.append(f"{'admission':22}: {json.dumps(d['admission'])}")
        for tenant, counts in (d.get("by_tenant") or {}).items():
            lines.append(f"{'tenant ' + tenant[:12]:22}: "
                         f"{json.dumps(counts)}")
        return "\n".join(lines)


# -- REST driver ---------------------------------------------------------------


def _rest_once(base: str, path: str, report: LoadReport,
               lock: threading.Lock, tenant_key: Optional[str] = None,
               token: Optional[str] = None) -> None:
    t0 = time.perf_counter()
    status, retry_after = 0, None
    req = urllib.request.Request(base + path)
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            status = r.status
            r.read()
    except urllib.error.HTTPError as e:
        status = e.code
        retry_after = e.headers.get("Retry-After")
        e.read()
    except Exception:
        status = -1
    dt = time.perf_counter() - t0
    with lock:
        report.attempted += 1
        if tenant_key is not None:
            if report.by_tenant is None:
                report.by_tenant = {}
            t = report.by_tenant.setdefault(
                tenant_key, {"attempted": 0, "ok": 0, "shed": 0,
                             "errors": 0, "authenticated": bool(token)})
            t["attempted"] += 1
        if status in (200, 304):
            report.ok += 1
            report.latencies.append(dt)
            if tenant_key is not None:
                t["ok"] += 1
        elif status == 429:
            report.shed += 1
            if retry_after is None:
                report.malformed_sheds += 1
            if tenant_key is not None:
                t["shed"] += 1
        else:
            report.errors += 1
            if tenant_key is not None:
                t["errors"] += 1


def _grpc_once(client, peer, report: LoadReport,
               lock: threading.Lock, token: Optional[str] = None) -> None:
    import grpc
    t0 = time.perf_counter()
    ok = shed = err = malformed = 0
    try:
        client.public_rand(peer, round_=0, token=token)
        ok = 1
    except grpc.RpcError as e:
        if e.code() == grpc.StatusCode.RESOURCE_EXHAUSTED:
            shed = 1
            md = dict(e.trailing_metadata() or ())
            if "retry-after" not in md:
                malformed = 1
        else:
            err = 1
    except Exception:
        err = 1
    dt = time.perf_counter() - t0
    with lock:
        report.attempted += 1
        report.ok += ok
        report.shed += shed
        report.errors += err
        report.malformed_sheds += malformed
        if ok:
            report.latencies.append(dt)


def run_load(fire, target: str, mode: str, clients: int, rate: float,
             duration: float) -> LoadReport:
    """`fire(report, lock)` performs ONE request and records it."""
    report = LoadReport(target=target, mode=mode, duration=duration)
    lock = threading.Lock()
    stop = threading.Event()
    threads: List[threading.Thread] = []
    t0 = time.perf_counter()

    if mode == "closed":
        def worker():
            while not stop.is_set():
                fire(report, lock)
        threads = [threading.Thread(target=worker, daemon=True,
                                    name=f"loadgen-{i}")
                   for i in range(clients)]
        for t in threads:
            t.start()
        stop.wait(duration)
        stop.set()
        for t in threads:
            t.join(timeout=5)
    else:                               # open loop: fixed offered rate
        gap = 1.0 / max(1.0, rate)
        next_at = time.perf_counter()
        while time.perf_counter() - t0 < duration:
            now = time.perf_counter()
            if now < next_at:
                stop.wait(min(gap, next_at - now))
                continue
            next_at += gap
            th = threading.Thread(target=fire, args=(report, lock),
                                  daemon=True, name="loadgen-fire")
            th.start()
            threads.append(th)
            if len(threads) > 4096:     # reap finished arrivals
                threads = [t for t in threads if t.is_alive()]
        deadline = time.perf_counter() + 10
        for t in threads:
            t.join(timeout=max(0.1, deadline - time.perf_counter()))
    report.duration = time.perf_counter() - t0
    return report


def _fetch_admission(base: str) -> Optional[dict]:
    try:
        with urllib.request.urlopen(base + "/health", timeout=5) as r:
            return json.loads(r.read()).get("admission")
    except urllib.error.HTTPError as e:
        try:        # /health 503s while the chain lags; body still parses
            return json.loads(e.read()).get("admission")
        except Exception:
            return None
    except Exception:
        return None


# -- selftest: in-process REST edge over a real-crypto chain ------------------


def _shim_daemon(chain, head: int):
    """The daemon slice RestServer consumes, over a TrueChain."""
    from types import SimpleNamespace

    from drand_tpu.chain.errors import ErrNoBeaconStored
    from drand_tpu.chain.info import Info
    from drand_tpu.log import Logger

    info = Info(public_key=chain.public, period=30,
                genesis_time=1_000, genesis_seed=chain.genesis_seed,
                scheme=chain.scheme.id, beacon_id="default")

    def get_beacon(round_):
        r = head if round_ == 0 else round_
        b = chain.beacons.get(r)
        if b is None:
            raise ErrNoBeaconStored(f"round {r}")
        return b

    cb = SimpleNamespace(add_callback=lambda *a, **k: None,
                         remove_callback=lambda *a, **k: None)
    bp = SimpleNamespace(
        handler=SimpleNamespace(chain=SimpleNamespace(cbstore=cb)),
        beacon_id="default", chain_info=lambda: info,
        get_beacon=get_beacon)
    return SimpleNamespace(processes={"default": bp},
                           chain_hashes={info.hash_string(): "default"},
                           log=Logger("loadgen"))


def selftest(duration: float, clients: int, emit_json: bool) -> int:
    from chaos import TrueChain

    from drand_tpu.http_server import RestServer
    from drand_tpu.net.admission import AdmissionController

    chain = TrueChain(n=64)
    daemon = _shim_daemon(chain, head=64)
    # a deliberately tiny pool so the closed-loop flood sheds: capacity 6
    # minus 2 reserved = 4 sheddable tokens against `clients` workers
    ctrl = AdmissionController(capacity=6, critical_reserve=2,
                               shed_wait=0.05, recover_wait=0.01,
                               dwell=3600.0)
    server = RestServer(daemon, "127.0.0.1:0", admission=ctrl, workers=4)
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        report = run_load(
            lambda rep, lock: _rest_once(base, "/public/latest", rep, lock),
            target=base, mode="closed", clients=clients, rate=0.0,
            duration=duration)
        report.admission = {
            "level": ctrl.level(),
            "wait_p99": ctrl.snapshot()["wait_p99"],
        }
    finally:
        server.stop()
    print(json.dumps(report.to_dict()) if emit_json else report.render(),
          flush=True)
    ok = (report.ok > 0 and report.shed > 0 and report.shed_well_formed
          and report.errors == 0)
    return 0 if ok else 1


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rest", help="REST base URL (http://host:port)")
    ap.add_argument("--grpc", help="gRPC address (host:port)")
    ap.add_argument("--mode", choices=("closed", "open"), default="closed")
    ap.add_argument("--clients", type=int, default=8,
                    help="closed-loop worker count")
    ap.add_argument("--rate", type=float, default=200.0,
                    help="open-loop offered req/s")
    ap.add_argument("--duration", type=float, default=5.0)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--selftest", action="store_true",
                    help="in-process flood against a tiny admission pool "
                         "(no daemon needed); exit 0 iff served+shed+"
                         "well-formed")
    ap.add_argument("--tenants",
                    help="comma-separated chain hashes (multi-tenant "
                         "daemon): REST requests round-robin across "
                         "/{hash}/public/latest and the report breaks "
                         "ok/shed down per chain — drive one tenant's "
                         "hash hot to watch its quota shed while the "
                         "others keep serving")
    ap.add_argument("--token", action="append", default=[],
                    metavar="[HASH=]TOKEN",
                    help="bearer token (core/authz.py, mint via "
                         "`drand auth mint`): HASH=TOKEN attaches the "
                         "token to that --tenants lane only, a bare "
                         "TOKEN rides on every request — lanes without "
                         "one stay anonymous, so a mixed run measures "
                         "authenticated and anonymous read paths side "
                         "by side (per-lane `authenticated` in the "
                         "report)")
    args = ap.parse_args()

    # "--token HASH=TOKEN" per tenant lane; "--token TOKEN" for all
    tokens, default_token = {}, None
    for spec in args.token:
        if "=" in spec:
            h, _, tok = spec.partition("=")
            tokens[h.strip()] = tok.strip()
        else:
            default_token = spec.strip()

    if args.selftest:
        return selftest(args.duration, max(args.clients, 16), args.json)
    if not args.rest and not args.grpc:
        ap.error("need --rest and/or --grpc (or --selftest)")

    rc = 0
    if args.rest:
        base = args.rest.rstrip("/")
        if args.tenants:
            hashes = [h.strip() for h in args.tenants.split(",")
                      if h.strip()]
            rr = {"i": 0}
            rr_lock = threading.Lock()

            def fire(rep, lock):
                with rr_lock:
                    h = hashes[rr["i"] % len(hashes)]
                    rr["i"] += 1
                _rest_once(base, f"/{h}/public/latest", rep, lock,
                           tenant_key=h,
                           token=tokens.get(h, default_token))
        else:
            def fire(rep, lock):
                _rest_once(base, "/public/latest", rep, lock,
                           token=default_token)
        report = run_load(
            fire, target=base, mode=args.mode, clients=args.clients,
            rate=args.rate, duration=args.duration)
        report.admission = _fetch_admission(base)
        print(json.dumps(report.to_dict()) if args.json
              else report.render(), flush=True)
        rc |= 0 if report.shed_well_formed and report.ok else 1
    if args.grpc:
        from drand_tpu.net import Peer, ProtocolClient
        client = ProtocolClient()
        peer = Peer(args.grpc)
        try:
            report = run_load(
                lambda rep, lock: _grpc_once(client, peer, rep, lock,
                                             token=default_token),
                target=args.grpc, mode=args.mode, clients=args.clients,
                rate=args.rate, duration=args.duration)
        finally:
            client.close()
        print(json.dumps(report.to_dict()) if args.json
              else report.render(), flush=True)
        rc |= 0 if report.shed_well_formed and report.ok else 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
