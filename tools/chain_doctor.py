#!/usr/bin/env python
"""Chain doctor: offline scan / repair / report for a stored beacon chain.

    scan    — walk the store, report gaps / torn rows / broken linkage /
              invalid signatures (full mode runs the batched device
              verifier; --host falls back to CPU pairings).
    repair  — scan, quarantine the corrupt rows, re-fetch the union of
              corrupt + missing rounds from a healthy source (--from-db
              another chain.db, or --peers running nodes over gRPC),
              re-verify, write back, and prove health with a post-repair
              full-crypto rescan.
    report  — scan and emit the machine-readable JSON report.

Chain identity comes from --info (a chain-info JSON file, hash-checked) or
from --scheme/--pubkey[/--genesis-seed].  Examples:

    python tools/chain_doctor.py scan --db ~/.drand/multibeacon/default/db/chain.db \
        --info chain-info.json
    python tools/chain_doctor.py repair --db chain.db --scheme pedersen-bls-chained \
        --pubkey 868f00..af31 --genesis-seed 176f..390a --from-db backup.db

Exit codes: 0 = clean (or fully repaired), 1 = findings remain, 2 = usage/
environment error.
"""
# tpu-vet: disable-file=verifier  (offline doctor runs against a store
# with no daemon: it constructs its own batch verifier by design)

import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _identity(args):
    """(scheme, public_key_bytes, genesis_seed|None) from the CLI args."""
    from drand_tpu.chain.info import Info
    from drand_tpu.crypto.schemes import scheme_from_name
    if args.info:
        with open(args.info, "rb") as f:
            info = Info.from_json(f.read())
        return scheme_from_name(info.scheme), info.public_key, \
            info.genesis_seed
    if not (args.scheme and args.pubkey):
        # exit 2, not the bare-string SystemExit's 1 — 1 means "findings
        # remain" in this tool's contract
        print("need --info, or --scheme and --pubkey", file=sys.stderr)
        raise SystemExit(2)
    seed = bytes.fromhex(args.genesis_seed) if args.genesis_seed else None
    return scheme_from_name(args.scheme), bytes.fromhex(args.pubkey), seed


def _verifier(scheme, pubkey, host: bool):
    if host:
        from drand_tpu.crypto.hostverify import HostBatchVerifier
        return HostBatchVerifier(scheme, pubkey)
    from drand_tpu.crypto.batch import BatchBeaconVerifier
    return BatchBeaconVerifier(scheme, pubkey)


def _scanner(args):
    from drand_tpu.chain.integrity import IntegrityScanner
    from drand_tpu.chain.sqlitedb import SqliteStore
    scheme, pubkey, seed = _identity(args)
    store = SqliteStore(args.db)
    verifier = None
    if args.mode == "full":
        verifier = _verifier(scheme, pubkey, args.host)
    scanner = IntegrityScanner(store, scheme, verifier=verifier,
                               genesis_seed=seed, chunk=args.chunk,
                               beacon_id=args.beacon_id)
    return scanner, store, scheme, pubkey, seed


def _progress(done, upto):
    print(f"  scanned up to round {done}/{upto}", file=sys.stderr)


def cmd_scan(args) -> int:
    scanner, store, *_ = _scanner(args)
    try:
        report = scanner.scan(mode=args.mode, upto=args.upto,
                              progress=_progress)
    finally:
        store.close()
    if args.json:
        print(report.to_json())
    else:
        print(f"chain doctor scan: {report.summary()}")
        for f in report.findings:
            detail = f" — {f.detail}" if f.detail else ""
            print(f"  round {f.round}: {f.kind}{detail}")
    return 0 if report.clean else 1


def cmd_report(args) -> int:
    args.json = True
    return cmd_scan(args)


def _local_fetch(src_path: str):
    """fetch(peer, from_round) over another sqlite chain file.  The source
    opens with require_previous so chained repairs carry the previous_sig
    the verifier needs."""
    from drand_tpu.chain.sqlitedb import SqliteStore
    src = SqliteStore(src_path, require_previous=True)

    def fetch(peer, from_round: int):
        cur = src.cursor()
        b = cur.seek(max(1, from_round))
        while b is not None:
            yield b
            b = cur.next()

    return fetch, src


def _grpc_fetch(args):
    from drand_tpu.net import Peer
    from drand_tpu.net.client import ProtocolClient
    client = ProtocolClient()
    peers = [Peer(a.strip(), args.tls) for a in args.peers.split(",") if a.strip()]

    def fetch(peer, from_round: int):
        return client.sync_chain(peer, from_round, args.beacon_id)

    return fetch, peers


def cmd_repair(args) -> int:
    from drand_tpu.beacon.clock import RealClock
    from drand_tpu.beacon.sync import SyncManager
    from drand_tpu.core.follow import FollowFacade

    scanner, store, scheme, pubkey, seed = _scanner(args)
    src = None
    try:
        report = scanner.scan(mode=args.mode, upto=args.upto,
                              progress=_progress)
        print(f"scan: {report.summary()}")
        if report.clean:
            return 0
        if scheme.chained and seed is None:
            print("repair of a chained scheme needs --genesis-seed or "
                  "--info (round 1 anchors on it)", file=sys.stderr)
            return 2
        if args.from_db:
            fetch, src = _local_fetch(args.from_db)
            peers = ["local"]
        elif args.peers:
            fetch, peers = _grpc_fetch(args)
        else:
            print("repair needs --from-db or --peers", file=sys.stderr)
            return 2
        verifier = _verifier(scheme, pubkey, args.host)
        # the post-repair rescan below is always full-crypto, even when the
        # initial scan was linkage-only — make sure the scanner can run it
        if scanner.verifier is None:
            scanner.verifier = verifier
        facade = FollowFacade(store, scheme.chained, seed or b"")
        syncm = SyncManager(
            chain=facade, scheme=scheme, public_key_bytes=pubkey,
            period=30, clock=RealClock(), fetch=fetch, peers=peers,
            verifier=verifier)
        remaining = syncm.heal(store, report, peers,
                               beacon_id=args.beacon_id)
        if remaining:
            print(f"UNREPAIRED rounds (still quarantined): {remaining}")
            return 1
        # prove health: post-repair full-crypto rescan
        rescan = scanner.scan(mode="full", upto=args.upto)
        print(f"post-repair rescan: {rescan.summary()}")
        return 0 if rescan.clean else 1
    finally:
        store.close()
        if src is not None:
            src.close()


def main() -> int:
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name, fn in (("scan", cmd_scan), ("repair", cmd_repair),
                     ("report", cmd_report)):
        p = sub.add_parser(name)
        p.set_defaults(fn=fn)
        p.add_argument("--db", required=True, help="sqlite chain.db path")
        p.add_argument("--info", help="chain-info JSON file")
        p.add_argument("--scheme", help="scheme id (e.g. pedersen-bls-chained)")
        p.add_argument("--pubkey", help="collective public key, hex")
        p.add_argument("--genesis-seed", help="genesis seed, hex")
        p.add_argument("--beacon-id", default="default")
        p.add_argument("--mode", choices=["full", "linkage"], default="full")
        p.add_argument("--upto", type=int, default=None)
        p.add_argument("--chunk", type=int, default=512)
        p.add_argument("--host", action="store_true",
                       help="CPU pairings instead of the device batch path")
        if name == "scan":
            p.add_argument("--json", action="store_true")
        if name == "repair":
            p.add_argument("--from-db", help="healthy chain.db to copy from")
            p.add_argument("--peers", help="comma-separated node addresses")
            p.add_argument("--tls", action="store_true")
    args = ap.parse_args()
    try:
        return args.fn(args)
    except SystemExit:
        raise
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
