#!/usr/bin/env python
"""Round benchmark: all five BASELINE.json configs on one chip.

Output protocol (VERDICT r3 #1 — a driver kill must never erase finished
results): after EVERY config the parent prints a FULL cumulative JSON
result line to stdout (flushed).  The last line parses as the round
result whenever the process dies; configs not yet run are null.

The headline metric is config 5 — STREAMED verification of fresh beacons
replayed from a populated SqliteStore with host packing double-buffered
against device compute (BASELINE config 5: the honest end-to-end number,
not a warm re-verify of one resident batch).

The baseline anchor is the serial-CPU figure from BASELINE.md: a single
pairing-based verification is milliseconds-scale on one core, pinned at
500 rounds/sec (reference harness crypto/schemes_test.go:15-45).

Configs (BASELINE.json north_star):
  1. chained_catchup   1k  pedersen-bls-chained rounds (client/verify.go
                       :139-160 walk, batched; linkage checked host-side)
  2. unchained_resident 106,496 (13 x 8192) bls-unchained-on-g1 rounds
                       pre-encoded device-resident, verified in 13
                       same-shaped RLC passes (kernel throughput at the
                       BASELINE-specified 100k scale)
  3. partials_recover  10k rounds x t=7-of-13 in 2048-round chunks:
                       batched partial verify + Lagrange recovery
                       (chainstore.go:202-207), recovered sigs re-verified
  4. mixed_4chains     4 concurrent chains (2 schemes x {chained,
                       unchained} x {G1,G2} mix) verified chunk-interleaved
  5. streamed_store    106,496 rounds (13 x 8192) streamed from
                       SqliteStore, double buffered (the headline; an
                       exact chunk multiple so every chunk shares ONE
                       compiled program shape)
  6. coalesced_service the same replay submitted through the resident
                       verify service in quarter-chunk spans: coalescing
                       merges 4 submissions per PAD-lane dispatch
                       (dispatch counter recorded in stats), double
                       buffering via the service's pipelined executor
  7. multidevice_scaleout (ISSUE 11): one chain per device group served
                       CONCURRENTLY through per-group dispatch streams
                       (per-group throughput recorded), then one huge
                       batch round-axis-sharded across the FULL pool;
                       n_devices/group_map land in the JSON (on a
                       1-device chip this degenerates to one group +
                       an unsharded huge batch — still measured, never
                       marked degraded for that)
  9. multitenant_serving (ISSUE 15): N tenants with heterogeneous
                       schemes (G1 vs G2 cost) served through the
                       tenancy layer — weighted placement, per-tenant
                       read admission (one tenant deliberately
                       rate-capped), measured per-tenant device time;
                       per-tenant r/s, quota rejections and the
                       placement map land in the JSON

Compiled-program economy: every verifier pads to PAD=8192 (pad_to), so
each RLC program shape compiles once.  Since ISSUE 14 the message FRONT
is part of the flavor: configs 5/6 stream the donating G1-RLC with the
raw-message device-h2f front (message-bytes-in — the steady-state
serving path), config 2's resident re-verify keeps the host-expanded
"fields" front (hash once, re-verify many), config 1's chained chunk
carries the digest front (its genesis slot has a seed-width
previous_sig), and config 4 adds the non-donating raw fronts — about
seven RLC programs plus partials-verify@(2048x7), the fused
decompress+recover GLV program and the fixture signing pipelines.  All
configs run inside ONE child process so each program compiles (or
cache-loads) at most once; the parent restarts the child for the
remaining configs if it hangs or dies.

Fixture chains are generated once and cached under /tmp/drand_tpu_bench
(generation is setup, not measurement).  DRAND_TPU_BENCH_CONFIGS=1,5
limits the run; DRAND_TPU_BENCH_N scales config 5.
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/drand_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

BASELINE_RPS = 500.0  # serial kyber CPU anchor (BASELINE.md)
CACHE = "/tmp/drand_tpu_bench"
GENESIS_PREV = b"\x09" * 32  # chained fixture genesis-seed stand-in
PAD = int(os.environ.get("DRAND_TPU_BENCH_PAD", "8192"))
# 13 x 8192: >=100k (BASELINE spec) AND an exact multiple of the chunk so
# the streamed path never compiles a second (tail-sized) program
N_STREAM = int(os.environ.get("DRAND_TPU_BENCH_N", str(13 * PAD)))
N_RESIDENT = int(os.environ.get("DRAND_TPU_BENCH_N_RESIDENT", str(13 * PAD)))
N_CHAINED = int(os.environ.get("DRAND_TPU_BENCH_N_CHAINED", "1024"))
N_PARTIAL_ROUNDS = int(os.environ.get("DRAND_TPU_BENCH_N_PARTIALS", "10240"))
PARTIAL_CHUNK = int(os.environ.get("DRAND_TPU_BENCH_PARTIAL_CHUNK", "2048"))
N_MIXED = int(os.environ.get("DRAND_TPU_BENCH_N_MIXED", "4096"))
# config 7: rounds per chain (2 pad-chunks each) and how many chains at
# most — one per device group, capped so fixture signing stays bounded
N_MD = int(os.environ.get("DRAND_TPU_BENCH_N_MD", str(2 * PAD)))
MD_MAX_CHAINS = int(os.environ.get("DRAND_TPU_BENCH_MD_CHAINS", "4"))
CHUNK = int(os.environ.get("DRAND_TPU_BENCH_CHUNK", str(PAD)))
# config 8 (ISSUE 13): committee size for the in-process Handel
# aggregation + device-DKG measurements; rounds timed after warmup.
# The signing-fixture and host-commit setup scale with COMMITTEE_N, so
# CPU smokes should set DRAND_TPU_BENCH_COMMITTEE_N=64 or so.
COMMITTEE_N = int(os.environ.get("DRAND_TPU_BENCH_COMMITTEE_N", "1024"))
COMMITTEE_ROUNDS = int(os.environ.get("DRAND_TPU_BENCH_COMMITTEE_ROUNDS",
                                      "4"))
COMMITTEE_DKG_T = int(os.environ.get("DRAND_TPU_BENCH_COMMITTEE_T", "32"))
# config 9 (ISSUE 15): rounds per tenant replay, timed passes, and how
# many tenants at most (heterogeneous-scheme lineup is defined in the
# config; trimming it trims from the tail)
N_TENANT = int(os.environ.get("DRAND_TPU_BENCH_TENANT_N", str(2 * PAD)))
TENANT_PASSES = int(os.environ.get("DRAND_TPU_BENCH_TENANT_PASSES", "2"))
TENANT_MAX = int(os.environ.get("DRAND_TPU_BENCH_TENANT_MAX", "4"))


def _progress(msg):
    """Child -> parent heartbeat: config 3 is a chain of several big cold
    compiles (the partials-verify program alone is tens of minutes on a
    cold CPU cache), and the parent's no-progress watchdog must not kill
    a config that is legitimately still compiling its next stage."""
    print(json.dumps({"progress": msg}), flush=True)


def _configs():
    raw = os.environ.get("DRAND_TPU_BENCH_CONFIGS", "1,2,3,4,5,6,7,8,9")
    out = set()
    for x in raw.split(","):
        x = x.strip()
        if x.isdigit() and 1 <= int(x) <= 9:
            out.add(int(x))
    return out or {1, 2, 3, 4, 5, 6, 7, 8, 9}


def _jax_setup():
    import jax

    plat = os.environ.get("DRAND_TPU_BENCH_PLATFORM")
    if plat:
        # the axon sitecustomize force-sets jax_platforms at interpreter
        # start, overriding the env var — pin at config level (CPU smoke
        # tests of the bench protocol; the driver runs without this)
        from jax.extend.backend import clear_backends

        jax.config.update("jax_platforms", plat)
        clear_backends()
    jax.config.update("jax_compilation_cache_dir", "/tmp/drand_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)


# ---------------------------------------------------------------------------
# Fixture generation (cached; setup is NOT timed)
# ---------------------------------------------------------------------------

def _store_path(tag):
    os.makedirs(CACHE, exist_ok=True)
    return os.path.join(CACHE, f"{tag}.db")


def _unchained_store(scheme_id, n, seed, tag):
    """SqliteStore with n device-signed unchained beacons (cached)."""
    from drand_tpu.chain.beacon import Beacon
    from drand_tpu.chain.sqlitedb import SqliteStore
    from drand_tpu.crypto import batch, schemes

    sch = schemes.scheme_from_name(scheme_id)
    sec, pub = sch.keypair(seed=seed)
    path = _store_path(f"{tag}-{seed.hex()}-{n}")
    store = SqliteStore(path)
    if len(store) >= n:
        return sch, sch.public_bytes(pub), store
    rounds = list(range(len(store) + 1, n + 1))
    for lo in range(0, len(rounds), CHUNK):
        part = rounds[lo:lo + CHUNK]
        msgs = [sch.digest_beacon(r, None) for r in part]
        sigs = batch.sign_batch(sch, sec, msgs)
        for r, s in zip(part, sigs):
            store.put(Beacon(round=r, signature=s))
        if (lo // CHUNK) % 32 == 0:
            # heartbeat: a multi-million-round fixture (the 3M replay)
            # signs for longer than the parent's no-progress watchdog
            _progress(f"fixture {tag}: {lo + len(part)}/{len(rounds)}")
    return sch, sch.public_bytes(pub), store


def _chained_chain(n):
    """Sequentially-signed chained chain (cached on disk as a store)."""
    from drand_tpu.chain.beacon import Beacon
    from drand_tpu.chain.sqlitedb import SqliteStore
    from drand_tpu.crypto import schemes

    sch = schemes.scheme_from_name(schemes.DEFAULT_SCHEME_ID)
    sec, pub = sch.keypair(seed=b"bench-chained")
    path = _store_path(f"chained-{n}")
    store = SqliteStore(path, require_previous=True)
    beacons = []
    if len(store) >= n:
        cur = store.cursor()
        b = cur.first()
        while b is not None:
            beacons.append(b)
            b = cur.next()
        # round 1's previous_sig is the genesis seed, which the trimmed
        # store cannot reconstruct (no round 0) — restore it
        from drand_tpu.chain.beacon import Beacon as _B
        beacons[0] = _B(round=beacons[0].round,
                        signature=beacons[0].signature,
                        previous_sig=GENESIS_PREV)
        return sch, sch.public_bytes(pub), beacons
    prev = GENESIS_PREV
    for r in range(1, n + 1):
        msg = sch.digest_beacon(r, prev)
        sig = sch.sign(sec, msg)
        b = Beacon(round=r, signature=sig, previous_sig=prev)
        store.put(b)
        beacons.append(b)
        prev = sig
    return sch, sch.public_bytes(pub), beacons


def _verifier(sch, pub):
    from drand_tpu.crypto import batch

    return batch.BatchBeaconVerifier(sch, pub, pad_to=PAD)


# ---------------------------------------------------------------------------
# Configs
# ---------------------------------------------------------------------------

def bench_chained_catchup(stats):
    sch, pub, beacons = _chained_chain(N_CHAINED)
    ver = _verifier(sch, pub)
    t0 = time.perf_counter()
    ok, _ = ver.verify_chain(beacons)         # warm/compile
    warm = time.perf_counter() - t0
    assert ok
    t0 = time.perf_counter()
    ok, _ = ver.verify_chain(beacons)
    dt = time.perf_counter() - t0
    assert ok
    # the G2-RLC program's first-call cost minus its steady-state cost is
    # (approximately) the compile/cache-load time — the r3 blocker was a
    # >90-min G2 cold compile; this records what it is now (VERDICT r4 #4)
    stats["g2_compile_s"] = round(warm - dt, 1)
    return len(beacons) / dt


def bench_unchained_resident():
    """Device-resident RLC throughput at the BASELINE-specified scale
    (config 2: 100k rounds -> 13 x 8192 = 106,496, an exact multiple of
    the canonical pad so every chunk shares ONE compiled program).  All
    chunks are encoded up front (setup, untimed) and stay resident; the
    timed region is pure device verification passes."""
    import jax

    from drand_tpu.crypto import schemes

    sch, pub, store = _unchained_store(
        schemes.SHORT_SIG_SCHEME_ID, N_RESIDENT, b"drand-tpu-bench", "g1")
    ver = _verifier(sch, pub)

    encs = []
    for lo in range(0, N_RESIDENT, PAD):
        rounds = list(range(lo + 1, min(lo + PAD, N_RESIDENT) + 1))
        sigs = [store.get(r).signature for r in rounds]
        msgs = [sch.digest_beacon(r, None) for r in rounds]
        enc, bad = ver._encode(sigs, msgs, PAD)   # ragged tail pads inert
        assert not bad.any()
        # pre-shard in SETUP so multi-device timed passes do no layout
        # moves (single chip: no-op); later device_puts to the same
        # sharding are then cheap no-transfers
        enc = ver._shard_round_axis(enc)
        jax.block_until_ready(enc)
        encs.append((enc, len(rounds)))
    ok = ver._rlc_ok(*encs[0])                    # warm/compile
    assert ok
    t0 = time.perf_counter()
    oks = [ver._rlc_ok(enc, n) for enc, n in encs]
    dt = time.perf_counter() - t0
    assert all(oks)
    return N_RESIDENT / dt


def bench_partials_recover():
    from drand_tpu.crypto import batch, schemes, tbls
    from drand_tpu.crypto.partials import BatchPartialVerifier

    t, n_nodes = 7, 13
    sch = schemes.scheme_from_name(schemes.SHORT_SIG_SCHEME_ID)
    poly = tbls.PriPoly.random(t, secret=0xBE7C4)
    shares = poly.shares(n_nodes)
    pub_poly = poly.commit(sch.key_group)
    nr, ck = N_PARTIAL_ROUNDS, PARTIAL_CHUNK
    msgs = [sch.digest_beacon(r, None) for r in range(1, nr + 1)]
    # t partials per round from signers 0..t-1 (device-signed per signer,
    # in chunk-sized batches so signing shares the ck-shaped program)
    per_signer = []
    for j in range(t):
        sigs = []
        for lo in range(0, nr, ck):
            sigs.extend(batch.sign_batch(sch, shares[j].value,
                                         msgs[lo:lo + ck]))
        per_signer.append(sigs)
    rows = [[j.to_bytes(2, "big") + per_signer[j][r] for j in range(t)]
            for r in range(nr)]
    raw_grid = [[per_signer[j][r] for j in range(t)] for r in range(nr)]

    bpv = BatchPartialVerifier(sch, pub_poly, n_nodes)

    def run(heartbeat=False):
        out = []
        for lo in range(0, nr, ck):
            grid = raw_grid[lo:lo + ck]       # ragged final chunk: size
            okm = bpv.verify_partials(msgs[lo:lo + ck], rows[lo:lo + ck])
            assert okm.all()
            if heartbeat:                     # partials program compiled
                _progress("partials_verify compiled")
            out.extend(batch.recover_batch(
                sch, [list(range(t))] * len(grid), grid))
            if heartbeat:
                _progress("recover compiled")
                heartbeat = False
        return out

    sigs = run(heartbeat=True)                 # warm/compile
    t0 = time.perf_counter()
    sigs = run()
    dt = time.perf_counter() - t0
    # recovered signatures must verify against the collective key
    _progress("timed; re-verifying recovered sigs vs collective key")
    ver = _verifier(sch, sch.key_group.to_bytes(pub_poly.public_key()))
    for lo in range(0, nr, ck):
        part = sigs[lo:lo + ck]
        assert ver.verify_batch(list(range(lo + 1, lo + 1 + len(part))),
                                part).all()
    return nr / dt


def bench_mixed_4chains():
    from drand_tpu.crypto import schemes

    chains = []
    sch, pub, beacons = _chained_chain(N_CHAINED)
    chains.append((_verifier(sch, pub), beacons))
    for scheme_id, tag in ((schemes.UNCHAINED_SCHEME_ID, "g2u"),
                           (schemes.SHORT_SIG_SCHEME_ID, "g1"),
                           (schemes.SHORT_SIG_SCHEME_ID, "g1b")):
        s, p, store = _unchained_store(scheme_id, N_MIXED, tag.encode(), tag)
        bs = [store.get(r) for r in range(1, N_MIXED + 1)]
        chains.append((_verifier(s, p), bs))

    def run_all():
        total = 0
        for ver, bs in chains:
            ok, _ = ver.verify_chain(bs)
            assert ok
            total += len(bs)
        return total

    total = run_all()                          # warm/compile
    t0 = time.perf_counter()
    total = run_all()
    dt = time.perf_counter() - t0
    return total / dt


def bench_streamed_store(stats):
    from drand_tpu.crypto import schemes

    sch, pub, store = _unchained_store(
        schemes.SHORT_SIG_SCHEME_ID, N_STREAM, b"drand-tpu-bench-stream",
        "g1stream")
    ver = _verifier(sch, pub)
    # effective dispatch-pipeline depth (DRAND_VERIFY_PIPELINE_DEPTH,
    # clamped by the per-chunk footprint budget)
    stats["streamed_depth"] = ver.pipeline_depth(None, CHUNK)

    def replay():
        def it():
            cur = store.cursor()
            b = cur.first()
            while b is not None:
                yield b
                b = cur.next()
        n = 0
        for rounds, ok in ver.verify_stream(it(), chunk_size=CHUNK):
            assert ok.all()
            n += len(rounds)
        return n

    from drand_tpu.crypto import batch as _batch

    t0 = time.perf_counter()
    n = replay()                               # cold (incl. compile/cache)
    stats["streamed_cold_s"] = round(time.perf_counter() - t0, 1)
    pack0 = _batch.pack_seconds()
    t0 = time.perf_counter()
    n = replay()                               # warm steady-state
    dt = time.perf_counter() - t0
    assert n == N_STREAM
    # host pack seconds over the warm replay (ISSUE 14): the term the
    # device hash-to-field front removes the per-message hashing from
    stats["streamed_pack_s"] = round(_batch.pack_seconds() - pack0, 2)
    stats["streamed_h2f_device"] = bool(
        ver.h2f_device if ver.h2f_device is not None
        else _batch.h2f_device_default(PAD))
    return n / dt


def bench_coalesced_service(stats):
    """Config 6 (ISSUE 6): the same streamed replay as config 5, but
    submitted through the resident verify service in quarter-chunk spans
    from a consumer's point of view — the service coalesces them back
    into PAD-lane dispatches, double-buffers host packing against device
    compute, and the dispatch counter proves the reduction (4 submissions
    coalesce per device dispatch)."""
    from drand_tpu.crypto import schemes
    from drand_tpu.crypto.verify_service import VerifyService

    sch, pub, store = _unchained_store(
        schemes.SHORT_SIG_SCHEME_ID, N_STREAM, b"drand-tpu-bench-stream",
        "g1stream")                            # config 5's fixture, shared
    svc = VerifyService(pad=PAD, background_window=0.01)
    handle = svc.handle(sch, pub)
    sub = max(1, PAD // 4)

    def replay():
        futs = []
        buf_rounds, buf_sigs = [], []
        cur = store.cursor()
        b = cur.first()
        while b is not None:
            buf_rounds.append(b.round)
            buf_sigs.append(b.signature)
            if len(buf_rounds) == sub:
                futs.append(handle.submit(buf_rounds, buf_sigs))
                buf_rounds, buf_sigs = [], []
            b = cur.next()
        if buf_rounds:
            futs.append(handle.submit(buf_rounds, buf_sigs))
        n = 0
        for f in futs:
            ok = f.result()
            assert ok.all()
            n += len(ok)
        return n, len(futs)

    try:
        n, _ = replay()                        # cold (compile/cache-load)
        _progress("coalesced_service warm")
        before = svc.stats()
        t0 = time.perf_counter()
        n, submissions = replay()
        dt = time.perf_counter() - t0
        assert n == N_STREAM
        st = svc.stats()
        stats["coalesced_submissions"] = submissions
        stats["coalesced_dispatches"] = st["dispatches"] - \
            before["dispatches"]
        # occupancy observability (ISSUE 10): effective in-flight depth
        # and the queue-time vs device-time split over the warm replay
        stats["coalesced_inflight_depth"] = st["inflight_depth_max"]
        stats["coalesced_pack_s"] = round(
            st["pack_time_s"] - before["pack_time_s"], 2)
        stats["coalesced_queue_s"] = round(
            st["queue_time_s"] - before["queue_time_s"], 2)
        stats["coalesced_device_s"] = round(
            st["device_time_s"] - before["device_time_s"], 2)
        stats["coalesced_tuning"] = st["tuning"]
        # delta'd over the WARM replay only (cumulative stats would blend
        # the cold run's interleaving in)
        slots = st["dispatch_slots"] - before["dispatch_slots"]
        stats["coalesced_fill_ratio"] = round(
            (st["dispatch_lanes"] - before["dispatch_lanes"]) /
            max(1, slots), 3)
        # which backend actually served: a failover mid-run means these
        # numbers are HOST numbers — the r04 silent-zero must never be
        # misread as a device figure again
        stats["coalesced_service_backend"] = (
            "host_fallback" if st["failovers"] > before["failovers"]
            or "degraded" in st["backends"].values()
            or "probing" in st["backends"].values() else "device")
        if st["watchdog_trips"] > before["watchdog_trips"]:
            stats["coalesced_watchdog_trips"] = (
                st["watchdog_trips"] - before["watchdog_trips"])
        return n / dt
    finally:
        svc.stop()


def bench_multidevice_scaleout(stats):
    """Config 7 (ISSUE 11): the device pool on the serving path.  One
    chain per device group, submitted CONCURRENTLY through the service's
    per-group dispatch streams (per-group throughput + the concurrency
    proof recorded), then one huge batch whose single submission crosses
    the shard threshold and round-axis-shards across the FULL pool.  On
    a 1-device chip the pool degenerates to one group and the huge batch
    runs unsharded — still measured, and NOT a degraded run."""
    import threading

    from drand_tpu.crypto import schemes
    from drand_tpu.crypto.verify_service import VerifyService

    # AUTO shard threshold (pad x max(2, n_devices)): the per-group
    # replays below submit half-fixture spans that stay UNDER it, the
    # huge batch is sized exactly AT it — so the sharded dispatch is a
    # full pool-wide chunk, not mostly pad slots.  The watchdog floor is
    # raised to compile scale — config 7's group- and pool-pinned
    # programs are FRESH compile flavors (placement lands in the
    # executable cache key), and a cold compile tripping the watchdog
    # would silently turn this into a host measurement (the backend
    # self-report below would catch it, but the bench should measure
    # the device, not the failover)
    svc = VerifyService(pad=PAD, background_window=0.0,
                        watchdog_floor=3600.0)
    try:
        n_groups = 1
        chains = []
        for i in range(MD_MAX_CHAINS):
            sch, pub, store = _unchained_store(
                schemes.SHORT_SIG_SCHEME_ID, N_MD,
                f"md-{i}".encode(), f"md{i}")
            handle = svc.handle(sch, pub)
            if i == 0:
                n_groups = svc.stats()["n_groups"]
            chains.append((handle, store))
            if len(chains) >= n_groups:
                break       # one chain per group is the point
        _progress(f"multidevice fixtures ready: {len(chains)} chains "
                  f"over {n_groups} groups")

        def replay(handle, store, n_rounds=N_MD, split=2):
            """One replay of `n_rounds`.  The per-group phases submit in
            `split` under-threshold spans so they measure the GROUP
            stream; the huge-batch phase submits ONE threshold-sized
            span (split=1), deliberately crossing into the pool-wide
            sharded path."""
            rounds = list(range(1, n_rounds + 1))
            sigs = [store.get(r).signature for r in rounds]
            step = (n_rounds + split - 1) // split
            futs = [handle.submit(rounds[lo:lo + step], sigs[lo:lo + step],
                                  lane="live", flush_now=True)
                    for lo in range(0, n_rounds, step)]
            n = 0
            for f in futs:
                ok = f.result()
                assert ok.all()
                n += len(ok)
            return n

        for handle, store in chains:        # warm/compile, serial
            replay(handle, store)
        _progress("multidevice warm; timing concurrent per-group replay")
        per_group = {}
        errs = []

        def worker(handle, store):
            try:
                t0 = time.perf_counter()
                n = replay(handle, store)
                per_group[svc._slots[handle.key].label] = round(
                    n / (time.perf_counter() - t0), 1)
            except Exception as e:          # surfaced after join
                errs.append(e)

        before = svc.stats()
        t0 = time.perf_counter()
        threads = [threading.Thread(target=worker, args=c) for c in chains]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        if errs:
            raise errs[0]
        st = svc.stats()
        total = len(chains) * N_MD / dt

        # the huge-batch half: ONE submission sized pad x max(2,
        # n_devices) — exactly the AUTO shard threshold, i.e. one FULL
        # pool-wide chunk, so the sharded number measures the pool and
        # not pad slots.  (pool_sharding permitting; 1 device =
        # unsharded, recorded.)  The fixture store extends on demand.
        h0, _ = chains[0]
        huge_n = PAD * max(2, st["n_devices"])
        _, _, store0 = _unchained_store(
            schemes.SHORT_SIG_SCHEME_ID, huge_n, b"md-0", "md0")
        _progress(f"multidevice huge batch: {huge_n} rounds")
        replay(h0, store0, huge_n, split=1)     # warm the pool program
        t0 = time.perf_counter()
        n = replay(h0, store0, huge_n, split=1)
        sharded_dt = time.perf_counter() - t0
        st2 = svc.stats()
        stats["multidevice_huge_n"] = huge_n
        stats["multidevice_n_devices"] = st2["n_devices"]
        stats["multidevice_n_groups"] = st2["n_groups"]
        stats["multidevice_group_map"] = st2["group_map"]
        stats["multidevice_per_group_rps"] = per_group
        stats["multidevice_concurrent_streams"] = \
            st2["concurrent_streams_max"]
        stats["multidevice_sharded"] = \
            st2["sharded_dispatches"] > st["sharded_dispatches"]
        stats["multidevice_sharded_rps"] = round(n / sharded_dt, 1)
        stats["multidevice_migrations"] = st2["migrations"]
        # self-report the serving backend like config 6: a mid-run
        # failover means these are HOST numbers
        stats["multidevice_scaleout_backend"] = (
            "host_fallback" if st2["failovers"] > before["failovers"]
            or "degraded" in st2["backends"].values()
            or "probing" in st2["backends"].values() else "device")
        return total
    finally:
        svc.stop()


def bench_multitenant_serving(stats):
    """Config 9 (ISSUE 15): N tenants with heterogeneous schemes (G1 vs
    G2 partial cost) and periods, served through the TENANCY layer — the
    registry's weighted placement assigns each tenant's chain a device
    group, every read admission runs the per-tenant sub-budgets, and the
    verify service attributes measured device time per tenant.  Recorded:
    per-tenant rounds/s, quota rejections (one tenant is deliberately
    rate-capped), the chain→group placement map, and per-tenant device
    seconds.  Value = total verified rounds/s across tenants.

    Since PR 19 the lanes also exercise the identity plane: every
    tenant but one presents a real macaroon-style bearer token
    (core/authz.py) that is verified before each span's admission —
    the same check the REST/gRPC edges run — and the remaining lane
    stays anonymous, so `multitenant_authenticated` records both read
    paths side by side."""
    import shutil
    import tempfile
    import threading

    from drand_tpu.core.authz import TokenAuthority
    from drand_tpu.core.tenancy import TenantConfig, TenantRegistry
    from drand_tpu.crypto import schemes
    from drand_tpu.crypto.verify_service import VerifyService
    from drand_tpu.net.admission import AdmissionController, CLASS_SHEDDABLE

    registry = TenantRegistry()     # in-memory: bench, not a daemon
    ctrl = AdmissionController(tenancy=registry, capacity=64,
                               critical_reserve=8)
    svc = VerifyService(pad=PAD, background_window=0.0,
                        watchdog_floor=3600.0)
    svc.set_tenancy(registry)
    # heterogeneous tenants: scheme changes per-round device cost
    # (G1 vs G2 RLC flavors), period is the nominal read cadence the
    # rate quota is sized against; "capped" gets a bucket far below its
    # offered load so quota rejections are measured, not hypothetical
    tenants = [
        ("anchor", schemes.SHORT_SIG_SCHEME_ID, dict(weight=2.0,
                                                     anti_affinity=True)),
        ("burst", schemes.SHORT_SIG_SCHEME_ID, dict(weight=1.0)),
        ("heavy-g2", schemes.UNCHAINED_SCHEME_ID, dict(weight=1.0)),
        ("capped", schemes.SHORT_SIG_SCHEME_ID, dict(weight=0.5, rate=4.0,
                                                     burst=4)),
    ][:max(2, TENANT_MAX)]
    periods = {"anchor": 3, "burst": 30, "heavy-g2": 30, "capped": 30}
    authority_dir = tempfile.mkdtemp(prefix="drand-bench-authz-")
    authority = TokenAuthority(authority_dir)
    lane_tokens = {}
    chains = {}
    for name, scheme_id, kw in tenants:
        chain_id = f"{name}-chain"
        registry.set_tenant(TenantConfig(name=name, chains=(chain_id,),
                                         **kw))
        sch, pub, store = _unchained_store(
            scheme_id, N_TENANT, f"mt-{name}".encode(),
            f"mt-{name}")
        registry.register_chain(chain_id, pk=pub)
        chains[name] = (svc.handle(sch, pub), store)
        # every lane but "capped" reads with a real bearer token; the
        # capped lane stays anonymous so both paths are measured
        if name != "capped":
            lane_tokens[name], _ = authority.mint(name, chains=(chain_id,))
    _progress(f"multitenant fixtures ready: {len(chains)} tenants "
              f"({len(lane_tokens)} token-bearing)")

    def replay(name, count_sheds=False):
        handle, store = chains[name]
        rounds = list(range(1, N_TENANT + 1))
        sigs = [store.get(r).signature for r in rounds]
        step = max(1, N_TENANT // 4)
        served = sheds = 0
        futs = []
        token = lane_tokens.get(name)
        for lo in range(0, N_TENANT, step):
            # authenticated lanes verify their token before every
            # span's admission — the exact order the edges use
            # (token check BEFORE quota spend)
            if token is not None:
                v = authority.verify(token, chain=f"{name}-chain")
                assert v.ok and v.tenant == name, v
            # every span is admitted AS the tenant (the serving-path
            # read admission the REST/gRPC edges perform)
            ticket, s = ctrl.try_admit(CLASS_SHEDDABLE, tenant=name)
            if ticket is None:
                sheds += 1
                assert s.tenant == name and s.retry_after > 0
                continue
            try:
                futs.append((handle.submit(
                    rounds[lo:lo + step], sigs[lo:lo + step],
                    lane="live", flush_now=True), lo, step))
            finally:
                ticket.release()
        for f, lo, _ in futs:
            ok = f.result()
            assert ok.all()
            served += len(ok)
        return served, sheds

    try:
        for name, _, _ in tenants:          # warm/compile, serial
            replay(name)
            _progress(f"multitenant warm: {name}")
        per_tenant = {}
        rejections = {}
        served_total = {}
        errs = []

        def worker(name):
            try:
                t0 = time.perf_counter()
                total_served = total_shed = 0
                for _ in range(TENANT_PASSES):
                    served, sheds = replay(name)
                    total_served += served
                    total_shed += sheds
                dt = time.perf_counter() - t0
                per_tenant[name] = round(total_served / dt, 1)
                rejections[name] = total_shed
                served_total[name] = total_served
            except Exception as e:
                errs.append(e)

        before = svc.stats()
        t0 = time.perf_counter()
        threads = [threading.Thread(target=worker, args=(name,))
                   for name, _, _ in tenants]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        if errs:
            raise errs[0]
        st = svc.stats()
        total_rounds = sum(served_total.values())
        stats["multitenant_n_tenants"] = len(tenants)
        stats["multitenant_rounds_per_tenant"] = N_TENANT * TENANT_PASSES
        stats["multitenant_schemes"] = {n: s for n, s, _ in tenants}
        stats["multitenant_periods"] = {n: periods[n]
                                        for n, _, _ in tenants}
        stats["multitenant_per_tenant_rps"] = per_tenant
        stats["multitenant_quota_rejections"] = rejections
        stats["multitenant_authenticated"] = {
            n: n in lane_tokens for n, _, _ in tenants}
        stats["multitenant_placement"] = {
            st["tenant_map"].get(label, "?"): gid
            for label, gid in st["group_map"].items()}
        stats["multitenant_device_seconds"] = {
            n: round(registry.device_seconds_total(n), 3)
            for n, _, _ in tenants}
        stats["multitenant_serving_backend"] = (
            "host_fallback" if st["failovers"] > before["failovers"]
            or "degraded" in st["backends"].values() else "device")
        # the capped tenant must actually have been rate-limited — a
        # zero here means the quota plumbing silently did nothing
        if any(n == "capped" for n, _, _ in tenants) \
                and rejections.get("capped", 0) == 0:
            stats["multitenant_warning"] = "capped tenant was never shed"
        return total_rounds / dt
    finally:
        svc.stop()
        shutil.rmtree(authority_dir, ignore_errors=True)


def bench_committee_scale(stats):
    """Config 8 (ISSUE 13): the committee-scale engine, in-process.

    (a) n=COMMITTEE_N Handel aggregation: one observed node's session
        per round is fed ideal-honest candidate aggregates for every
        tree level, and the whole committee's partials verify in the
        session's ONE windowed `verify` call per round — the
        (1, n)-shaped partials RLC program, so aggregating a
        thousand-signer round costs one dispatch, not n pairings.
        Timed after a warmup round compiles the program; value =
        aggregation rounds/s.
    (b) device DKG share verification at the same n: dispatch count and
        wall time for the full bundle-set check plus the reshare
        constant-term pin (the <= 4 dispatch acceptance, self-reported
        in stats).
    """
    from drand_tpu.beacon import handel as HD
    from drand_tpu.beacon.chainstore import DevicePartialVerifier
    from drand_tpu.crypto import dkg_device, schemes, tbls
    from drand_tpu.crypto.host.params import R as _R
    import random as _random

    n, rounds = COMMITTEE_N, COMMITTEE_ROUNDS
    rng = _random.Random(0xC0117EE)
    sch = schemes.scheme_from_name(schemes.SHORT_SIG_SCHEME_ID)
    # polynomial degree decoupled from the protocol threshold (recovery
    # interpolates from any >= t shares); keeps host setup bounded
    poly = tbls.PriPoly([rng.randrange(_R) for _ in range(8)])
    pub_poly = poly.commit(sch.key_group)
    thr = n // 2 + 1
    _progress(f"committee fixture: signing {n} partials x "
              f"{rounds + 1} rounds")
    shares = [poly.eval(i).value for i in range(n)]
    from drand_tpu.crypto import batch as _batch
    per_round = []
    for r in range(1, rounds + 2):      # +1 warmup round
        msg = sch.digest_beacon(r, None)
        # one msg under n different keys does not batch on-device; the
        # host (native) signer is the fixture generator, not measured
        per_round.append((msg, {i: i.to_bytes(2, "big")
                                + sch.sign(shares[i], msg)
                                for i in range(n)}))

    verifier = DevicePartialVerifier(sch, pub_poly, n)
    levels = HD.num_levels(n)
    cfg = HD.HandelConfig(min_group=2, fanout=4, window=2 * levels + 2,
                          bad_limit=3)

    def one_round(r, msg, partials):
        done = {}
        sess = HD.HandelSession(cfg, n, 0, thr, r, None, msg, verifier,
                                send=lambda *a: None,
                                on_complete=lambda p: done.update(p))
        sess.add_own(partials[0])
        # ideal-honest peers: one full-side candidate per level, all
        # delivered before the tick so the window coalesces the whole
        # committee into one verify call
        for level in range(1, levels + 1):
            block = HD.level_block(n, 0, level)
            sender = block[0]
            side = HD.own_block(n, sender, level)
            sess.receive(level, sender,
                         HD.Aggregate({i: partials[i] for i in side}))
        sess.tick()
        assert len(sess.verified) == n and len(done) >= thr
        return done

    before_d = _batch.dispatch_count()
    one_round(1, *per_round[0])                 # warmup/compile
    _progress("committee aggregation program compiled")
    warm_dispatches = _batch.dispatch_count() - before_d
    t0 = time.perf_counter()
    for r in range(rounds):
        one_round(r + 2, *per_round[r + 1])
    dt = time.perf_counter() - t0
    stats["committee_scale_n"] = n
    stats["committee_scale_levels"] = levels
    stats["committee_scale_dispatches_per_round"] = warm_dispatches
    stats["committee_scale_agg_rounds_per_s"] = round(rounds / dt, 3)

    # (b) device DKG share-verify at n
    _progress(f"committee DKG fixture: {n} dealers x t={COMMITTEE_DKG_T}")
    g = sch.key_group
    t = COMMITTEE_DKG_T
    dpolys = [tbls.PriPoly([rng.randrange(_R) for _ in range(t)])
              for _ in range(n)]
    dcommits = [[g.curve.mul(g.curve.gen, c) for c in p.coeffs]
                for p in dpolys]
    holder = 3
    dshares = [p.eval(holder).value for p in dpolys]
    before = dkg_device.dispatch_count()
    t0 = time.perf_counter()
    ok = dkg_device.verify_shares(g, dcommits, holder, dshares)
    old = dcommits[0]
    ctm = dkg_device.constant_terms_match(
        g, old, range(n), [tbls.PubPoly(g, old).eval(d) for d in range(n)])
    dkg_dt = time.perf_counter() - t0
    assert all(ok) and all(ctm)
    stats["committee_dkg_n"] = n
    stats["committee_dkg_t"] = t
    stats["committee_dkg_dispatches"] = \
        dkg_device.dispatch_count() - before
    stats["committee_dkg_wall_s"] = round(dkg_dt, 2)
    return rounds / dt


_RUNNERS = {
    1: "chained_catchup",
    2: "unchained_resident",
    3: "partials_recover",
    4: "mixed_4chains",
    5: "streamed_store",
    6: "coalesced_service",
    7: "multidevice_scaleout",
    8: "committee_scale",
    9: "multitenant_serving",
}
# Order: config 2 compiles/loads the shared G1@PAD program that 5, 6, 7,
# 9, 3 and 4 reuse; G2 (1, then 4) go after the G1 family so a G2
# compile overrun cannot starve the G1 numbers (9 sits between — its
# heavy-g2 tenant shares config 4's G2-unchained flavor); 8 last (its
# (1, n) partials program is unique to it).
_ORDER = [2, 5, 6, 7, 3, 1, 4, 9, 8]


def _child(indices):
    """Child: run the given configs IN ONE PROCESS (compiled programs are
    shared), printing one flushed JSON line per finished config."""
    _jax_setup()
    for idx in indices:
        stats = {}
        fns = {
            1: lambda: bench_chained_catchup(stats),
            2: bench_unchained_resident,
            3: bench_partials_recover,
            4: bench_mixed_4chains,
            5: lambda: bench_streamed_store(stats),
            6: lambda: bench_coalesced_service(stats),
            7: lambda: bench_multidevice_scaleout(stats),
            8: lambda: bench_committee_scale(stats),
            9: lambda: bench_multitenant_serving(stats),
        }
        t0 = time.monotonic()
        try:
            value = fns[idx]()
            stats[f"{_RUNNERS[idx]}_wall_s"] = round(time.monotonic() - t0, 1)
            # configs 1-5 drive BatchBeaconVerifier directly: success means
            # the device really served (a dead chip errors out, it cannot
            # silently produce numbers); config 6 self-reports via the
            # service's failover stats above
            stats.setdefault(f"{_RUNNERS[idx]}_backend", "device")
            print(json.dumps({"config": idx, "value": round(value, 1),
                              "stats": stats}), flush=True)
        except Exception as e:  # one failed config must not hide the others
            print(json.dumps({"config": idx, "value": None,
                              "error": f"{type(e).__name__}: {e}"[:200]}),
                  flush=True)


_LAST_EMIT = {"line": None}


def _emit(configs, stats):
    """Print the full cumulative result line (the driver parses the last).

    Consecutive DUPLICATE lines are suppressed: the r05 tail printed the
    identical cumulative record three times (the reader thread emits
    after the final config, then main() emitted again unconditionally) —
    the final record now lands exactly once unless something changed."""
    headline, headline_config = 0.0, None
    for name in ("streamed_store", "unchained_resident"):
        if configs.get(name):
            headline, headline_config = configs[name], name
            break
    else:
        for name, v in configs.items():
            if v:
                headline, headline_config = v, name
                break
    # which backend served each config (device | host_fallback), and ONE
    # top-level degraded flag: a run where anything fell back to the host
    # path, errored, or never reached the chip must be impossible to
    # misread as healthy device numbers (the r04 silent-zero postmortem)
    backends = {name: stats.get(f"{name}_backend") for name in configs
                if stats.get(f"{name}_backend")}
    degraded = (any(b != "device" for b in backends.values())
                or any(f"{name}_error" in stats for name in configs)
                or "probe_error" in stats
                or headline == 0.0)
    out = {
        "metric": "beacon_verify_rounds_per_sec",
        "value": headline,
        "headline_config": headline_config,
        "unit": "rounds/s",
        "vs_baseline": round(headline / BASELINE_RPS, 3),
        "degraded": degraded,
        # serving-plane headline (tools/loadgen.py --selftest, CPU-only):
        # REST reads served/sec through the bounded edge + admission
        # controller, and the shed ratio under the deliberate overload
        "loadgen": {
            k.replace("loadgen_", ""): stats[k]
            for k in ("loadgen_rounds_served_per_s", "loadgen_shed_ratio",
                      "loadgen_shed_well_formed", "loadgen_error")
            if k in stats} or None,
        # the pad x depth occupancy sweep (tools/autotune.py; ISSUE 10)
        "tuning": {
            k.replace("tuning_", ""): stats[k]
            for k in ("tuning_platform", "tuning_winner", "tuning_sweep",
                      "tuning_file_entries", "tuning_error")
            if k in stats} or None,
        "backends": backends,
        "configs": configs,
        "n": {"streamed_store": N_STREAM, "unchained_resident": N_RESIDENT,
              "chained_catchup": N_CHAINED,
              "partials_recover": N_PARTIAL_ROUNDS,
              "mixed_4chains": N_CHAINED + 3 * N_MIXED,
              "coalesced_service": N_STREAM,
              "multidevice_scaleout": N_MD,
              "committee_scale": COMMITTEE_N,
              "multitenant_serving": N_TENANT * TENANT_PASSES,
              **stats},
    }
    line = json.dumps(out)
    if line != _LAST_EMIT["line"]:
        _LAST_EMIT["line"] = line
        print(line, flush=True)
    return headline


def _sweep_numbers(stats):
    """Record the pad x depth occupancy sweep (ISSUE 10): the autotune
    selftest — a tiny CPU-safe sweep that also proves the service
    consults its TUNING.json — runs on every bench round so the BENCH
    artifact carries the depth/width numbers next to the verify numbers.
    DRAND_TPU_BENCH_SWEEP=0 skips it (it costs a couple of tiny-pad
    compiles); failure is recorded, never fatal."""
    import subprocess
    if os.environ.get("DRAND_TPU_BENCH_SWEEP", "1") == "0":
        return
    at = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "tools", "autotune.py")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)          # cache-key hygiene (r3 postmortem)
    plat = os.environ.get("DRAND_TPU_BENCH_PLATFORM")
    if plat:
        env["JAX_PLATFORMS"] = plat
    try:
        proc = subprocess.run(
            [sys.executable, at, "--selftest"],
            capture_output=True, text=True, timeout=900, env=env)
        line = [l for l in proc.stdout.splitlines() if l.startswith("{")][-1]
        rep = json.loads(line)
        stats["tuning_platform"] = rep.get("platform")
        stats["tuning_winner"] = rep.get("winner")
        stats["tuning_sweep"] = rep.get("sweep")
        if proc.returncode != 0 or not rep.get("ok"):
            stats["tuning_error"] = (
                f"selftest exit {proc.returncode}: consulted="
                f"{rep.get('consulted')}")
    except Exception as e:
        stats["tuning_error"] = f"{type(e).__name__}: {e}"[:200]
    # the round's committed TUNING.json (if any): what the service would
    # actually consult on this host — recorded so a chip round's sweep
    # results are part of its BENCH artifact
    from drand_tpu.crypto import tuning
    path = tuning.tuning_path()
    if path:
        stats["tuning_file_entries"] = tuning.load_entries(path)


def _loadgen_numbers(stats):
    """Record the serving-plane headline (ROADMAP 5a): a short in-process
    loadgen selftest — CPU-only, independent of the chip — whose
    rounds-served/sec + shed-ratio land next to the degraded flag.  Any
    failure is recorded, never fatal: the verify numbers must not hostage
    the edge numbers or vice versa."""
    import subprocess
    lg = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "tools", "loadgen.py")
    try:
        proc = subprocess.run(
            [sys.executable, lg, "--selftest", "--json", "--duration", "3"],
            capture_output=True, text=True, timeout=120)
        line = [l for l in proc.stdout.splitlines()
                if l.startswith("{")][-1]
        rep = json.loads(line)
        stats["loadgen_rounds_served_per_s"] = rep["rounds_served_per_s"]
        stats["loadgen_shed_ratio"] = rep["shed_ratio"]
        stats["loadgen_shed_well_formed"] = rep["shed_well_formed"]
        if proc.returncode != 0:
            # numbers are recorded, but a failing selftest (errors, or a
            # flood that never shed) must be visible in the artifact
            stats["loadgen_error"] = (
                f"selftest exit {proc.returncode}: ok={rep.get('ok')} "
                f"shed={rep.get('shed')} errors={rep.get('errors')}")
    except Exception as e:
        stats["loadgen_error"] = f"{type(e).__name__}: {e}"[:200]


def main():
    import subprocess
    import threading

    which = _configs()
    order = [i for i in _ORDER if i in which]
    configs = {_RUNNERS[i]: None for i in order}
    stats = {}
    _loadgen_numbers(stats)
    _sweep_numbers(stats)
    # per-config ceiling (a hung compile RPC blocks in native code and can
    # only be killed from outside) and a whole-bench budget
    cfg_budget = int(os.environ.get("DRAND_TPU_BENCH_CONFIG_TIMEOUT", "2400"))
    total_budget = int(os.environ.get("DRAND_TPU_BENCH_TOTAL_TIMEOUT", "5400"))
    deadline = time.monotonic() + total_budget

    # children must see a clean accelerator env: a driver-exported
    # XLA_FLAGS / JAX_PLATFORMS would change the compilation-cache key and
    # force a from-scratch compile of every program (r3 postmortem).
    # DRAND_TPU_BENCH_PLATFORM pins the child platform explicitly (local
    # CPU smoke tests of the bench protocol).
    env = dict(os.environ)
    for k in ("XLA_FLAGS", "JAX_PLATFORMS"):
        env.pop(k, None)
    plat = os.environ.get("DRAND_TPU_BENCH_PLATFORM")
    if plat:
        env["JAX_PLATFORMS"] = plat

    # Pre-flight probe, then poll-and-retry while the backend is down
    # (VERDICT r4 weak#1): never hand a child to a dead accelerator — keep
    # emitting the cumulative (possibly all-null) result line so the round
    # record shows how long the tunnel was down and why numbers are absent.
    from drand_tpu.accel import probe_backend

    probe_timeout = int(os.environ.get("DRAND_TPU_BENCH_PROBE_TIMEOUT", "120"))
    attempts = 0
    while True:
        info, detail = probe_backend(env, probe_timeout, platform=plat)
        attempts += 1
        if info is not None:
            stats["probe"] = detail
            stats.pop("probe_error", None)
            break
        stats["probe_error"] = detail
        stats["probe_attempts"] = attempts
        print(f"# probe {attempts}: {detail}", file=sys.stderr, flush=True)
        _emit(configs, stats)
        # min useful run ~3 min; keep polling while that is still possible
        if time.monotonic() > deadline - 180:
            for idx in order:
                stats.setdefault(f"{_RUNNERS[idx]}_error",
                                 "skipped: backend unavailable all run")
            _emit(configs, stats)
            sys.exit(1)
        time.sleep(45)

    remaining = list(order)
    attempt = 0
    while remaining and time.monotonic() < deadline - 30 and attempt < 4:
        attempt += 1
        print(f"# child {attempt}: configs {remaining}", file=sys.stderr,
              flush=True)
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--run",
             ",".join(map(str, remaining))],
            stdout=subprocess.PIPE, stderr=sys.stderr, text=True, env=env)

        done_here = []
        last_progress = time.monotonic()

        def _reader():
            nonlocal last_progress
            for line in proc.stdout:
                try:
                    res = json.loads(line)
                except ValueError:
                    continue
                if "progress" in res:         # intra-config heartbeat
                    last_progress = time.monotonic()
                    print(f"#   .. {res['progress']}", file=sys.stderr,
                          flush=True)
                    continue
                idx = res.get("config")
                name = _RUNNERS.get(idx)
                if name is None:
                    continue
                last_progress = time.monotonic()
                done_here.append(idx)
                if res.get("value"):
                    configs[name] = res["value"]
                elif res.get("error"):
                    stats[f"{name}_error"] = res["error"]
                stats.update(res.get("stats", {}))
                print(f"#   {name} -> {res.get('value')}", file=sys.stderr,
                      flush=True)
                _emit(configs, stats)

        th = threading.Thread(target=_reader, daemon=True)
        th.start()
        while proc.poll() is None:
            now = time.monotonic()
            if now > deadline or now - last_progress > cfg_budget:
                which_cfg = next((i for i in remaining
                                  if i not in done_here), None)
                if which_cfg is not None:
                    stats[f"{_RUNNERS[which_cfg]}_error"] = (
                        "timeout: killed after "
                        f"{now - last_progress:.0f}s without progress")
                proc.kill()
                break
            time.sleep(1.0)
        proc.wait(timeout=30)
        th.join(timeout=10)
        # drop finished configs; on timeout also drop the one that hung
        remaining = [i for i in remaining if i not in done_here]
        if remaining and proc.returncode != 0:
            hung = remaining[0]
            if f"{_RUNNERS[hung]}_error" not in stats:
                stats[f"{_RUNNERS[hung]}_error"] = (
                    f"child exit {proc.returncode}")
            remaining = remaining[1:]

    for idx in remaining:                 # never attempted: say why
        name = _RUNNERS[idx]
        if f"{name}_error" not in stats:
            stats[f"{name}_error"] = "skipped: total bench budget exhausted"
    headline = _emit(configs, stats)
    if headline == 0.0:
        sys.exit(1)


if __name__ == "__main__":
    if len(sys.argv) == 3 and sys.argv[1] == "--run":
        _child([int(x) for x in sys.argv[2].split(",")])
    else:
        main()
