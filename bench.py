#!/usr/bin/env python
"""Round benchmark: all five BASELINE.json configs on one chip.

Prints exactly ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
     "configs": {...}, "n": {...}}

The headline metric is config 5 — STREAMED verification of fresh beacons
replayed from a populated SqliteStore with host packing double-buffered
against device compute (BASELINE config 5 / VERDICT r2 #10: the honest
end-to-end number, not a warm re-verify of one resident batch).

The baseline anchor is the serial-CPU figure from BASELINE.md: a single
pairing-based verification is milliseconds-scale on one core, pinned at
500 rounds/sec (reference harness crypto/schemes_test.go:15-45).

Configs (BASELINE.json north_star):
  1. chained_catchup   1k  pedersen-bls-chained rounds (client/verify.go
                       :139-160 walk, batched; linkage checked host-side)
  2. unchained_resident 16k bls-unchained-on-g1 rounds, resident batch
                       (kernel throughput; the r1/r2 headline, kept for
                       continuity)
  3. partials_recover  2k rounds x t=7-of-13: batched partial verify +
                       Lagrange recovery (chainstore.go:202-207)
  4. mixed_4chains     4 concurrent chains (2 schemes x {chained,
                       unchained} x {G1,G2} mix) verified chunk-interleaved
  5. streamed_store    >=100k rounds streamed from SqliteStore, double
                       buffered (the headline)

Fixture chains are generated once and cached under /tmp/drand_tpu_bench
(generation is setup, not measurement).  DRAND_TPU_BENCH_CONFIGS=1,5
limits the run; DRAND_TPU_BENCH_N scales config 5.
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/drand_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir", "/tmp/drand_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)

BASELINE_RPS = 500.0  # serial kyber CPU anchor (BASELINE.md)
CACHE = "/tmp/drand_tpu_bench"
GENESIS_PREV = b"\x09" * 32  # chained fixture genesis-seed stand-in
N_STREAM = int(os.environ.get("DRAND_TPU_BENCH_N", "102400"))
# default == CHUNK so configs 2 and 5 share one compiled program shape
N_RESIDENT = int(os.environ.get("DRAND_TPU_BENCH_N_RESIDENT", "8192"))
N_CHAINED = int(os.environ.get("DRAND_TPU_BENCH_N_CHAINED", "1024"))
N_PARTIAL_ROUNDS = int(os.environ.get("DRAND_TPU_BENCH_N_PARTIALS", "2048"))
N_MIXED = int(os.environ.get("DRAND_TPU_BENCH_N_MIXED", "4096"))
CHUNK = int(os.environ.get("DRAND_TPU_BENCH_CHUNK", "8192"))


def _configs():
    raw = os.environ.get("DRAND_TPU_BENCH_CONFIGS", "1,2,3,4,5")
    out = set()
    for x in raw.split(","):
        x = x.strip()
        if x.isdigit() and 1 <= int(x) <= 5:
            out.add(int(x))
    return out or {1, 2, 3, 4, 5}


# ---------------------------------------------------------------------------
# Fixture generation (cached; setup is NOT timed)
# ---------------------------------------------------------------------------

def _store_path(tag):
    os.makedirs(CACHE, exist_ok=True)
    return os.path.join(CACHE, f"{tag}.db")


def _unchained_store(scheme_id, n, seed, tag):
    """SqliteStore with n device-signed unchained beacons (cached)."""
    from drand_tpu.chain.beacon import Beacon
    from drand_tpu.chain.sqlitedb import SqliteStore
    from drand_tpu.crypto import batch, schemes

    sch = schemes.scheme_from_name(scheme_id)
    sec, pub = sch.keypair(seed=seed)
    path = _store_path(f"{tag}-{seed.hex()}-{n}")
    store = SqliteStore(path)
    if len(store) >= n:
        return sch, sch.public_bytes(pub), store
    rounds = list(range(len(store) + 1, n + 1))
    for lo in range(0, len(rounds), CHUNK):
        part = rounds[lo:lo + CHUNK]
        msgs = [sch.digest_beacon(r, None) for r in part]
        sigs = batch.sign_batch(sch, sec, msgs)
        for r, s in zip(part, sigs):
            store.put(Beacon(round=r, signature=s))
    return sch, sch.public_bytes(pub), store


def _chained_chain(n):
    """Sequentially-signed chained chain (cached on disk as a store)."""
    from drand_tpu.chain.beacon import Beacon
    from drand_tpu.chain.sqlitedb import SqliteStore
    from drand_tpu.crypto import schemes

    sch = schemes.scheme_from_name(schemes.DEFAULT_SCHEME_ID)
    sec, pub = sch.keypair(seed=b"bench-chained")
    path = _store_path(f"chained-{n}")
    store = SqliteStore(path, require_previous=True)
    beacons = []
    if len(store) >= n:
        cur = store.cursor()
        b = cur.first()
        while b is not None:
            beacons.append(b)
            b = cur.next()
        # round 1's previous_sig is the genesis seed, which the trimmed
        # store cannot reconstruct (no round 0) — restore it
        from drand_tpu.chain.beacon import Beacon as _B
        beacons[0] = _B(round=beacons[0].round,
                        signature=beacons[0].signature,
                        previous_sig=GENESIS_PREV)
        return sch, sch.public_bytes(pub), beacons
    prev = GENESIS_PREV
    for r in range(1, n + 1):
        msg = sch.digest_beacon(r, prev)
        sig = sch.sign(sec, msg)
        b = Beacon(round=r, signature=sig, previous_sig=prev)
        store.put(b)
        beacons.append(b)
        prev = sig
    return sch, sch.public_bytes(pub), beacons


# ---------------------------------------------------------------------------
# Configs
# ---------------------------------------------------------------------------

def bench_chained_catchup():
    from drand_tpu.crypto import batch

    sch, pub, beacons = _chained_chain(N_CHAINED)
    ver = batch.BatchBeaconVerifier(sch, pub)
    ok, _ = ver.verify_chain(beacons)         # warm/compile
    assert ok
    t0 = time.perf_counter()
    ok, _ = ver.verify_chain(beacons)
    dt = time.perf_counter() - t0
    assert ok
    return len(beacons) / dt


def bench_unchained_resident():
    from drand_tpu.crypto import batch, schemes

    sch, pub, store = _unchained_store(
        schemes.SHORT_SIG_SCHEME_ID, N_RESIDENT, b"drand-tpu-bench", "g1")
    rounds = list(range(1, N_RESIDENT + 1))
    sigs = [store.get(r).signature for r in rounds]
    ver = batch.BatchBeaconVerifier(sch, pub)
    assert ver.verify_batch(rounds, sigs).all()   # warm/compile
    t0 = time.perf_counter()
    ok = ver.verify_batch(rounds, sigs)
    dt = time.perf_counter() - t0
    assert ok.all()
    return N_RESIDENT / dt


def bench_partials_recover():
    from drand_tpu.crypto import batch, schemes, tbls
    from drand_tpu.crypto.partials import BatchPartialVerifier

    t, n_nodes = 7, 13
    sch = schemes.scheme_from_name(schemes.SHORT_SIG_SCHEME_ID)
    poly = tbls.PriPoly.random(t, secret=0xBE7C4)
    shares = poly.shares(n_nodes)
    pub_poly = poly.commit(sch.key_group)
    nr = N_PARTIAL_ROUNDS
    msgs = [sch.digest_beacon(r, None) for r in range(1, nr + 1)]
    # t partials per round from signers 0..t-1 (device-signed per signer)
    per_signer = [batch.sign_batch(sch, shares[j].value, msgs)
                  for j in range(t)]
    rows = [[j.to_bytes(2, "big") + per_signer[j][r] for j in range(t)]
            for r in range(nr)]
    indices = [[j for j in range(t)]] * nr
    raw_grid = [[per_signer[j][r] for j in range(t)] for r in range(nr)]

    bpv = BatchPartialVerifier(sch, pub_poly, n_nodes)

    def run():
        okm = bpv.verify_partials(msgs, rows)
        assert okm.all()
        sigs = batch.recover_batch(sch, indices, raw_grid)
        return sigs

    sigs = run()                               # warm/compile
    t0 = time.perf_counter()
    sigs = run()
    dt = time.perf_counter() - t0
    # recovered signatures must verify against the collective key
    ver = batch.BatchBeaconVerifier(
        sch, sch.key_group.to_bytes(pub_poly.public_key()))
    assert ver.verify_batch(list(range(1, nr + 1)), sigs).all()
    return nr / dt


def bench_mixed_4chains():
    from drand_tpu.crypto import batch, schemes

    chains = []
    sch, pub, beacons = _chained_chain(N_CHAINED)
    chains.append((batch.BatchBeaconVerifier(sch, pub), beacons))
    for scheme_id, tag in ((schemes.UNCHAINED_SCHEME_ID, "g2u"),
                           (schemes.SHORT_SIG_SCHEME_ID, "g1"),
                           (schemes.SHORT_SIG_SCHEME_ID, "g1b")):
        s, p, store = _unchained_store(scheme_id, N_MIXED, tag.encode(), tag)
        bs = [store.get(r) for r in range(1, N_MIXED + 1)]
        chains.append((batch.BatchBeaconVerifier(s, p), bs))

    def run_all():
        total = 0
        for ver, bs in chains:
            ok, _ = ver.verify_chain(bs)
            assert ok
            total += len(bs)
        return total

    total = run_all()                          # warm/compile
    t0 = time.perf_counter()
    total = run_all()
    dt = time.perf_counter() - t0
    return total / dt


def bench_streamed_store(stats):
    from drand_tpu.crypto import batch, schemes

    sch, pub, store = _unchained_store(
        schemes.SHORT_SIG_SCHEME_ID, N_STREAM, b"drand-tpu-bench-stream",
        "g1stream")
    ver = batch.BatchBeaconVerifier(sch, pub)

    def replay():
        def it():
            cur = store.cursor()
            b = cur.first()
            while b is not None:
                yield b
                b = cur.next()
        n = 0
        for rounds, ok in ver.verify_stream(it(), chunk_size=CHUNK):
            assert ok.all()
            n += len(rounds)
        return n

    t0 = time.perf_counter()
    n = replay()                               # cold (incl. compile/cache)
    stats["streamed_cold_s"] = round(time.perf_counter() - t0, 1)
    t0 = time.perf_counter()
    n = replay()                               # warm steady-state
    dt = time.perf_counter() - t0
    assert n == N_STREAM
    return n / dt


_RUNNERS = {
    1: "chained_catchup",
    2: "unchained_resident",
    3: "partials_recover",
    4: "mixed_4chains",
    5: "streamed_store",
}
# Warm-first order: config 2 compiles the shared G1 verify program that 5
# reuses; the G2 configs (1, 4) go last — their first-ever chip compile has
# been observed to exceed 90 min through the tunnel, so they must not
# starve the rest of the budget.
_ORDER = [2, 5, 3, 1, 4]


def _run_one(idx: int):
    """Child-process entry: run one config, print one JSON result line."""
    stats = {}
    fns = {
        1: bench_chained_catchup,
        2: bench_unchained_resident,
        3: bench_partials_recover,
        4: bench_mixed_4chains,
        5: lambda: bench_streamed_store(stats),
    }
    value = fns[idx]()
    print(json.dumps({"value": round(value, 1), "stats": stats}))


def main():
    import subprocess

    which = _configs()
    configs, stats = {}, {}
    budget = int(os.environ.get("DRAND_TPU_BENCH_CONFIG_TIMEOUT", "2400"))
    total_budget = int(os.environ.get("DRAND_TPU_BENCH_TOTAL_TIMEOUT",
                                      "5400"))
    t_start = time.monotonic()
    for idx in [i for i in _ORDER if i in which]:
        name = _RUNNERS[idx]
        left = total_budget - (time.monotonic() - t_start)
        if left < 60:
            configs[name] = None
            stats[f"{name}_error"] = "skipped: total bench budget exhausted"
            continue
        print(f"# config {idx} ({name})...", file=sys.stderr, flush=True)
        # subprocess isolation: a hung compile RPC cannot be interrupted by
        # signals inside the process (blocked in native code), but a child
        # can always be killed on timeout
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--config", str(idx)],
                capture_output=True, text=True,
                timeout=min(budget, left), env=dict(os.environ))
            if proc.returncode != 0:
                raise RuntimeError(
                    f"exit {proc.returncode}: {proc.stderr[-200:]}")
            res = json.loads(proc.stdout.strip().splitlines()[-1])
            configs[name] = res["value"]
            stats.update(res.get("stats", {}))
            print(f"#   -> {configs[name]} rounds/s", file=sys.stderr,
                  flush=True)
        except subprocess.TimeoutExpired:
            configs[name] = None
            stats[f"{name}_error"] = f"timeout after {min(budget, left):.0f}s"
        except Exception as e:  # one failed config must not hide the others
            configs[name] = None
            stats[f"{name}_error"] = f"{type(e).__name__}: {e}"[:200]

    headline, headline_config = 0.0, None
    for name in ("streamed_store", "unchained_resident"):
        if configs.get(name):
            headline, headline_config = configs[name], name
            break
    else:
        for name, v in configs.items():
            if v:
                headline, headline_config = v, name
                break
    out = {
        "metric": "beacon_verify_rounds_per_sec",
        "value": headline,
        "headline_config": headline_config,
        "unit": "rounds/s",
        "vs_baseline": round(headline / BASELINE_RPS, 3),
        "configs": configs,
        "n": {"streamed_store": N_STREAM, "unchained_resident": N_RESIDENT,
              "chained_catchup": N_CHAINED,
              "partials_recover": N_PARTIAL_ROUNDS,
              "mixed_4chains": N_CHAINED + 3 * N_MIXED,
              **stats},
    }
    print(json.dumps(out))
    if headline == 0.0:
        sys.exit(1)


if __name__ == "__main__":
    if len(sys.argv) == 3 and sys.argv[1] == "--config":
        _run_one(int(sys.argv[2]))
    else:
        main()
