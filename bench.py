#!/usr/bin/env python
"""Round benchmark: BASELINE config 2 — batch-verify unchained beacon rounds
on one chip with the `bls-unchained-on-g1` scheme.

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The baseline is the serial-CPU anchor from BASELINE.md: a single pairing-based
verification is milliseconds-scale on one core, i.e. ~10^2-10^3 rounds/sec.
We pin the anchor at 500 rounds/sec (midpoint, reference
crypto/schemes_test.go:15-45 harness order-of-magnitude).

The measured op is `BatchBeaconVerifier.verify_batch` end-to-end (host packing
+ device RLC pipeline), on signatures produced by the device signer — the
same path a sync catch-up or client chain-replay takes.
"""

import json
import os
import sys
import time

# Persistent compile cache: the pairing/ladder programs are compile-heavy.
# Under axon, jax is already imported (sitecustomize) before this file runs
# and has snapshotted its env-derived config — set the config directly.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/drand_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir", "/tmp/drand_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)

N = int(os.environ.get("DRAND_TPU_BENCH_N", "4096"))
BASELINE_RPS = 500.0  # serial kyber CPU anchor (BASELINE.md)


def main():
    from drand_tpu.crypto import batch, schemes

    sch = schemes.scheme_from_name(schemes.SHORT_SIG_SCHEME_ID)
    sec, pub = sch.keypair(seed=b"drand-tpu-bench")
    verifier = batch.BatchBeaconVerifier(sch, sch.public_bytes(pub))

    rounds = list(range(1, N + 1))
    msgs = [sch.digest_beacon(r, None) for r in rounds]
    sigs = batch.sign_batch(sch, sec, msgs)

    def fail():
        print(json.dumps({"metric": "beacon_verify_rounds_per_sec", "value": 0,
                          "unit": "rounds/s", "vs_baseline": 0,
                          "error": "verification failed"}))
        sys.exit(1)

    # Warmup at full shape (compiles once; persistent cache across runs).
    if not verifier.verify_batch(rounds, sigs).all():
        fail()

    t0 = time.perf_counter()
    ok = verifier.verify_batch(rounds, sigs)
    dt = time.perf_counter() - t0
    if not ok.all():
        fail()

    rps = N / dt
    print(json.dumps({
        "metric": "beacon_verify_rounds_per_sec",
        "value": round(rps, 1),
        "unit": "rounds/s",
        "vs_baseline": round(rps / BASELINE_RPS, 3),
    }))


if __name__ == "__main__":
    main()
