#!/usr/bin/env python
"""Local network demo: the subprocess orchestrator (reference:
demo/lib/orchestrator.go:37-615, `make demo`).

Spawns n real daemon processes, runs the networked DKG through the control
plane, waits for genesis, prints live beacons (verifying each), then
demonstrates node kill + catch-up.  Everything over real gRPC on localhost.

    python demo.py [--nodes 3] [--threshold 2] [--period 6] [--rounds 5]
"""

import argparse
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from drand_tpu.net import ControlClient, Peer, ProtocolClient   # noqa: E402
from drand_tpu.net import convert                               # noqa: E402
from drand_tpu.protos import drand_pb2 as pb                    # noqa: E402

SECRET = b"demo-secret"


class Node:
    """One daemon subprocess (demo/node/node_subprocess.go pattern).

    `version` overrides the advertised protocol version — the
    demo/regression/main.go upgrade scenario simulated by version skew
    (one codebase stands in for old/new binaries)."""

    def __init__(self, folder: str, index: int, version: str = "",
                 listen: str = "127.0.0.1:0"):
        self.index = index
        self.folder = folder
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        if version:
            env["DRAND_NODE_VERSION"] = version
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "drand_tpu.cli", "start",
             "--folder", folder, "--control", "0",
             "--private-listen", listen, "--db", "memdb",
             "--no-tpu", "--dkg-timeout", "3"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
            env=env)
        import queue as _q
        lines: "_q.Queue" = _q.Queue()

        def pump():
            for ln in self.proc.stdout:
                lines.put(ln)
            lines.put(None)

        threading.Thread(target=pump, daemon=True).start()
        line = ""
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                got = lines.get(timeout=1)
            except _q.Empty:
                continue
            if got is None:           # daemon exited without the banner
                break
            line = got
            if "private=" in line:
                break
        assert "private=" in line, f"node {index} failed to start: {line!r}"
        part = dict(kv.split("=") for kv in line.split() if "=" in kv)
        self.address = part["private"]
        self.control = int(part["control"])
        print(f"  node {index}: {self.address} (control {self.control})")

    def stop(self):
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()


def run_dkg(nodes, threshold: int, period: int):
    print(f"* running DKG: {len(nodes)} nodes, threshold {threshold}, "
          f"period {period}s")
    results = [None] * len(nodes)

    def share(i):
        cc = ControlClient(nodes[i].control)
        leader = i == 0
        info = pb.SetupInfo(
            leader=leader,
            leader_address="" if leader else nodes[0].address,
            nodes=len(nodes), threshold=threshold, timeout_seconds=60,
            secret=SECRET)
        req = pb.InitDKGPacket(info=info, beacon_period_seconds=period,
                               metadata=convert.metadata("default"))
        results[i] = cc.stub.init_dkg(req, timeout=180)

    threads = [threading.Thread(target=share, args=(i,))
               for i in range(len(nodes))]
    threads[0].start()
    time.sleep(0.5)
    for t in threads[1:]:
        t.start()
    for t in threads:
        t.join(timeout=200)
    missing = [i for i, r in enumerate(results) if r is None]
    assert not missing, f"DKG did not complete on nodes {missing}"
    group = convert.proto_to_group(results[0])
    print(f"* group created; hash {group.hash().hex()[:16]}…, "
          f"genesis in {group.genesis_time - int(time.time())}s")
    return group


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=3)
    ap.add_argument("--threshold", type=int, default=2)
    ap.add_argument("--period", type=int, default=6)
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--regression", action="store_true",
                    help="run the upgrade/version-skew regression after the "
                         "basic demo (demo/regression/main.go analogue)")
    args = ap.parse_args()

    tmp = tempfile.mkdtemp(prefix="drand-demo-")
    print(f"* starting {args.nodes} daemons under {tmp}")
    nodes = [Node(os.path.join(tmp, f"n{i}"), i)
             for i in range(args.nodes)]
    try:
        group = run_dkg(nodes, args.threshold, args.period)
        pc = ProtocolClient()
        # every node must serve the SAME chain info (QUAL-fork guard)
        infos = {pc.chain_info(Peer(n.address), "default").hash
                 for n in nodes}
        assert len(infos) == 1, f"collective key fork across nodes: {infos}"
        info = convert.proto_to_info(
            pc.chain_info(Peer(nodes[0].address), "default"))
        from drand_tpu.client.verify import verify_beacon_with_info

        print(f"* waiting for beacons (chain {info.hash_string()[:16]}…)")
        seen = 0
        killed = False
        while seen < args.rounds:
            time.sleep(1)
            try:
                resp = pc.public_rand(Peer(nodes[-1].address), 0, "default")
            except Exception:
                continue
            if resp.round > seen:
                seen = resp.round
                beacon = convert.rand_to_beacon(resp)
                ok = verify_beacon_with_info(info, beacon)
                print(f"  round {resp.round}: "
                      f"{beacon.randomness().hex()[:32]}… "
                      f"verified={ok}")
                if not ok:
                    print(f"    !! prev={bool(beacon.previous_sig)} "
                          f"sig_len={len(beacon.signature)} "
                          f"scheme={info.scheme} "
                          f"pk={info.public_key.hex()[:16]}…")
                    if os.environ.get("DEMO_DEBUG"):
                        print("    info:", info.to_json().decode())
                        print("    beacon:", beacon.to_json().decode())
                if not killed and seen >= 2 and len(nodes) > args.threshold:
                    print(f"* killing node 1 (threshold {args.threshold} of "
                          f"{args.nodes} still met)")
                    nodes[1].stop()
                    killed = True
        print("* demo complete: chain advanced with a node down; "
              "randomness verified against the collective key")
        if args.regression:
            return regression(nodes, pc, args)
        return 0
    finally:
        for n in nodes:
            n.stop()


def regression(nodes, pc, args) -> int:
    """Upgrade/version-skew regression (demo/regression/main.go:37-90 +
    demo/lib/orchestrator.go:417 UpdateBinary, simulated by version skew):

      1. rolling upgrade: restart a node advertising a newer COMPATIBLE
         minor version; the mixed network must keep producing beacons
      2. an incompatible-major "new binary" restart is locked out by the
         version interceptors (drand_daemon_interceptors.go:19-89): its
         catch-up sync is refused and the rest of the network advances
    """
    victim = next(i for i, n in enumerate(nodes) if n.proc.poll() is None
                  and i != 0)

    def last_round(addr):
        try:
            return pc.public_rand(Peer(addr), 0, "default").round
        except Exception:
            return 0

    print(f"* regression 1: rolling upgrade of node {victim} to v2.9.9")
    old = nodes[victim]
    old.stop()
    nodes[victim] = Node(old.folder, victim, version="2.9.9",
                         listen=old.address)
    base = last_round(nodes[0].address)
    deadline = time.time() + 12 * args.period
    while time.time() < deadline:
        time.sleep(1)
        if last_round(nodes[victim].address) > base:
            break
    upgraded = last_round(nodes[victim].address)
    assert upgraded > base, "mixed-minor network stopped producing"
    print(f"  ok: v2.9.9 node caught up + serving round {upgraded}")

    # restore full strength before the lockout test so the REST of the
    # network still meets the threshold without the victim — otherwise the
    # "network advances while v3 is locked out" claim is vacuous
    for i, n in enumerate(nodes):
        if i != victim and n.proc.poll() is not None:
            nodes[i] = Node(n.folder, i, listen=n.address)
    deadline = time.time() + 12 * args.period
    while time.time() < deadline:
        if last_round(nodes[0].address) > upgraded:
            break
        time.sleep(1)

    print(f"* regression 2: incompatible upgrade of node {victim} to v3.0.0")
    old = nodes[victim]
    old.stop()
    nodes[victim] = Node(old.folder, victim, version="3.0.0",
                         listen=old.address)
    time.sleep(3 * args.period)
    behind1 = last_round(nodes[victim].address)
    ahead1 = last_round(nodes[0].address)
    time.sleep(3 * args.period)
    behind2 = last_round(nodes[victim].address)
    ahead2 = last_round(nodes[0].address)
    assert ahead2 > ahead1, "network stalled without the v3 node"
    assert (ahead2 - behind2) > (ahead1 - behind1) or behind2 == behind1, (
        f"v3 node kept up ({behind1}->{behind2} vs {ahead1}->{ahead2}) — "
        "version gate broken")
    print(f"  ok: v3.0.0 node locked out at round {behind2}; "
          f"network advanced {ahead1}->{ahead2}")
    print("* regression complete")
    return 0


if __name__ == "__main__":
    sys.exit(main())
