"""Disk-layout migration: single-beacon v1 folders -> multibeacon
(reference: core/migration/migration.go:15-119, CLI `util migrate`).

v1 layout:   <folder>/{key,groups,db}
multibeacon: <folder>/multibeacon/<beaconID>/{key,groups,db}
"""

import os
import shutil

from .common import DEFAULT_BEACON_ID, MULTI_BEACON_FOLDER

_V1_DIRS = ("key", "groups", "db")


def needs_migration(folder: str) -> bool:
    """v1 dirs still present — regardless of whether a multibeacon layout
    already exists (a daemon may have created it before the operator ran
    migrate, or a previous run may have moved only some dirs)."""
    return any(os.path.isdir(os.path.join(folder, d)) for d in _V1_DIRS)


def migrate(folder: str, beacon_id: str = DEFAULT_BEACON_ID) -> bool:
    """Move v1 dirs under multibeacon/<id>/; returns True when work was done.
    Safe to re-run (no-op when already migrated); refuses to clobber data
    that already exists at the destination."""
    if not needs_migration(folder):
        return False
    target = os.path.join(folder, MULTI_BEACON_FOLDER, beacon_id)
    os.makedirs(target, mode=0o700, exist_ok=True)
    moves = [(os.path.join(folder, d), os.path.join(target, d))
             for d in _V1_DIRS if os.path.isdir(os.path.join(folder, d))]
    # check every destination BEFORE moving anything: failing halfway
    # would leave a layout neither reader understands
    conflicts = [dst for _, dst in moves if os.path.exists(dst)]
    if conflicts:
        raise RuntimeError(
            f"migration targets already exist: {conflicts}; resolve the "
            f"conflicts manually (v1 data left in place)")
    for src, dst in moves:
        shutil.move(src, dst)
    return True
