"""drand_tpu command-line interface (reference: cmd/drand-cli/cli.go:60-580).

    python -m drand_tpu.cli <command> ...

Daemon-side commands talk to a running daemon over the localhost control
plane (net/control.go); `start` runs the daemon itself.  Flags accept
`DRAND_*` environment fallbacks like the reference's urfave/cli setup.
"""

import argparse
import os
import signal
import sys
import time

from . import log as dlog
from .common import DEFAULT_BEACON_ID
from .core.config import (Config, DEFAULT_CONTROL_PORT,
                          default_config_folder)
from .net import ControlClient, Peer, ProtocolClient
from .net import convert
from .protos import drand_pb2 as pb


def _env(name: str, default):
    return os.environ.get(f"DRAND_{name.upper().replace('-', '_')}", default)


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--folder", default=_env("folder", default_config_folder()),
                   help="config folder (~/.drand)")
    p.add_argument("--control", type=int,
                   default=int(_env("control", DEFAULT_CONTROL_PORT)),
                   help="control port of the local daemon")
    p.add_argument("--id", default=_env("beacon_id", DEFAULT_BEACON_ID),
                   help="beacon id (multi-beacon daemons)")
    p.add_argument("--json", action="store_true", help="JSON log output")
    p.add_argument("--verbose", action="store_true")
    p.add_argument("--identity-dir", default=_env("identity_dir", ""),
                   help="cert dir (node.key/node.crt/ca.crt) for mutual "
                        "TLS — on `start` it arms the identity plane; on "
                        "control commands it authenticates the client "
                        "(DRAND_IDENTITY_DIR)")


def _control(args) -> ControlClient:
    return ControlClient(args.control,
                         identity_dir=getattr(args, "identity_dir", "")
                         or None)


def _md(args):
    return convert.metadata(args.id)


# -- commands ----------------------------------------------------------------

def cmd_generate_keypair(args) -> int:
    from .crypto.schemes import get_scheme_by_id_with_default
    from .key.keys import new_keypair
    from .key.store import FileStore
    scheme = get_scheme_by_id_with_default(args.scheme)
    pair = new_keypair(args.address, scheme, tls=args.tls)
    FileStore(args.folder, args.id).save_keypair(pair)
    print(f"Generated keys for {args.address} (scheme {scheme.id})")
    print(f"Public key: {pair.public.key.hex()}")
    return 0


# service threads that must be gone after a clean stop (mirrors
# tests/harness.SERVICE_THREAD_PREFIXES — the daemon-side copy backs the
# leaked-thread exit code, so fleet runs catch leaks without importing
# test code into the child process)
_SERVICE_THREAD_PREFIXES = ("verify-scheduler", "verify-packer",
                            "verify-watchdog", "verify-probe",
                            "transition-", "handel-")


def _write_ready_file(path: str, daemon, cfg) -> None:
    """Atomically publish this daemon's pid + bound ports (the fleet
    supervisor binds everything ephemeral and reads the roster back from
    here — no port races)."""
    import json
    import tempfile
    info = {
        "pid": os.getpid(),
        "private": daemon.gateway.listen_addr,
        "control": daemon.control.port,
        "metrics": daemon.metrics.port if daemon.metrics is not None
        else None,
        "public": daemon.http_server.port
        if daemon.http_server is not None else None,
        "folder": cfg.folder,
    }
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               prefix=".ready-")
    with os.fdopen(fd, "w") as f:
        json.dump(info, f)
    os.replace(tmp, path)


def _leaked_service_threads() -> list:
    import threading
    return sorted(t.name for t in threading.enumerate()
                  if t.name.startswith(_SERVICE_THREAD_PREFIXES))


def _install_dump_handler() -> None:
    """SIGUSR1 -> all-thread stack dump on stderr, the operator's answer
    to "what is this daemon doing right now".  When the lock sanitizer
    is live (DRAND_TSAN=1) the dump is followed by the held-lock table,
    so a wedged daemon shows not just where each thread sits but which
    locks it sits on.  No-op on platforms without SIGUSR1."""
    if not hasattr(signal, "SIGUSR1"):
        return

    def _dump(_s, _f):
        import faulthandler
        faulthandler.dump_traceback(file=sys.stderr, all_threads=True)
        if os.environ.get("DRAND_TSAN", "") not in ("", "0"):
            from .analysis import tsan
            sys.stderr.write(tsan.render_held_table())
        sys.stderr.flush()

    signal.signal(signal.SIGUSR1, _dump)


def cmd_start(args) -> int:
    identity_dir = getattr(args, "identity_dir", "") or None
    cfg = Config(
        folder=args.folder,
        private_listen=args.private_listen,
        public_listen=args.public_listen or "",
        control_port=args.control,
        metrics_port=args.metrics,
        db_engine=args.db,
        pg_dsn=getattr(args, "pg_dsn", ""),
        insecure=not (identity_dir or (args.tls_cert and args.tls_key)),
        tls_cert=args.tls_cert, tls_key=args.tls_key,
        identity_dir=identity_dir,
        dkg_timeout=args.dkg_timeout,
        use_device_verifier=not args.no_tpu)
    from .core.daemon import DrandDaemon
    daemon = DrandDaemon(cfg)
    daemon.start()
    if cfg.public_listen:
        from .http_server import RestServer
        daemon.http_server = RestServer(daemon, cfg.public_listen,
                                        admission=daemon.admission)
        daemon.http_server.start()
    daemon.load_beacons_from_disk()
    if args.ready_file:
        _write_ready_file(args.ready_file, daemon, cfg)
    import threading
    stopping = []
    stoppers = []
    drain_ok = []

    def _graceful():
        drain_ok.append(daemon.graceful_stop(grace=args.grace))

    def _sig(s, _f):
        if stopping:
            return
        stopping.append(1)
        if s == signal.SIGTERM:
            # drain off the signal frame: the handler runs on the main
            # thread mid-wait_exit, and graceful_stop blocks on condvars
            t = threading.Thread(target=_graceful, daemon=True,
                                 name="stop-graceful")
            stoppers.append(t)
            t.start()
        else:
            daemon.stop()
    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    _install_dump_handler()
    print(f"drand daemon up: private={daemon.gateway.listen_addr} "
          f"control={daemon.control.port}", flush=True)
    try:
        while not daemon.wait_exit(0.5):
            pass
    except KeyboardInterrupt:
        daemon.stop()
    for t in stoppers:
        t.join(timeout=args.grace + 5)
    # teardown verdict: 0 clean; 1 drain timed out; 3 leaked service
    # threads — the fleet invariant checker reads these exit codes
    settle = threading.Event()
    leaked = _leaked_service_threads()
    for _ in range(20):
        if not leaked:
            break
        settle.wait(0.1)
        leaked = _leaked_service_threads()
    if leaked:
        print(f"leaked service threads: {leaked}", file=sys.stderr)
        return 3
    if drain_ok and not drain_ok[0]:
        return 1
    return 0


def cmd_stop(args) -> int:
    cc = _control(args)
    cc.stub.shutdown(pb.ShutdownRequest(metadata=_md(args)))
    print("daemon stopped")
    return 0


def _read_secret(args) -> bytes:
    if args.secret_file:
        with open(args.secret_file, "rb") as f:
            return f.read().strip()
    env = os.environ.get("DRAND_SHARE_SECRET")
    if env:
        return env.encode()
    raise SystemExit("need --secret-file or DRAND_SHARE_SECRET")


def cmd_share(args) -> int:
    """DKG / reshare kickoff (cli.go shareCmd; control.go:877)."""
    cc = _control(args)
    info = pb.SetupInfo(
        leader=args.leader, leader_address=args.connect or "",
        nodes=args.nodes, threshold=args.threshold,
        timeout_seconds=args.setup_timeout, secret=_read_secret(args))
    # Session timeout: setup window + DKG phases + margin.
    rpc_timeout = args.setup_timeout + 120
    if args.transition or args.from_group:
        req = pb.InitResharePacket(info=info,
                                   old_group_path=args.from_group or "",
                                   metadata=_md(args))
        group = cc.stub.init_reshare(req, timeout=rpc_timeout)
    else:
        req = pb.InitDKGPacket(
            info=info, beacon_period_seconds=args.period,
            catchup_period_seconds=args.catchup_period,
            schemeID=args.scheme, metadata=_md(args))
        group = cc.stub.init_dkg(req, timeout=rpc_timeout)
    g = convert.proto_to_group(group)
    print(f"Group created: {len(g)} nodes, threshold {g.threshold}, "
          f"genesis {g.genesis_time}")
    print(f"Group hash: {g.hash().hex()}")
    if g.public_key is not None:
        print(f"Collective key: {g.public_key.key().hex()}")
    return 0


def cmd_get(args) -> int:
    """Fetch + verify randomness from a remote node's public API
    (cmd/client + core/client_public.go)."""
    client = ProtocolClient()
    peer = Peer(args.url, args.tls)
    if args.what == "chain-info":
        info = convert.proto_to_info(client.chain_info(peer, args.id))
        sys.stdout.buffer.write(info.to_json() + b"\n")
        return 0
    resp = client.public_rand(peer, args.round, args.id)
    beacon = convert.rand_to_beacon(resp)
    if args.chain_hash:
        from .client.verify import verify_beacon_with_info
        info = convert.proto_to_info(client.chain_info(peer, args.id))
        if info.hash_string() != args.chain_hash:
            print("chain hash mismatch", file=sys.stderr)
            return 1
        if not verify_beacon_with_info(info, beacon):
            print("beacon verification FAILED", file=sys.stderr)
            return 1
    print(f"round: {beacon.round}")
    print(f"randomness: {beacon.randomness().hex()}")
    print(f"signature: {beacon.signature.hex()}")
    return 0


def cmd_show(args) -> int:
    cc = _control(args)
    if args.what == "group":
        group = cc.stub.group_file(pb.GroupRequest(metadata=_md(args)))
        print(convert.proto_to_group(group).to_toml())
    elif args.what == "chain-info":
        packet = cc.stub.chain_info(pb.ChainInfoRequest(metadata=_md(args)))
        sys.stdout.buffer.write(
            convert.proto_to_info(packet).to_json() + b"\n")
    elif args.what == "public":
        resp = cc.stub.public_key(pb.PublicKeyRequest(metadata=_md(args)))
        print(resp.pub_key.hex())
    return 0


def cmd_sync(args) -> int:
    """Follow (observer) or check/repair the local chain
    (cli.go syncCmd; control.go follow/check)."""
    cc = _control(args)
    req = pb.StartSyncRequest(
        nodes=args.sync_nodes, is_tls=args.tls, up_to=args.up_to,
        beaconID=args.id, chain_hash=args.chain_hash or "",
        metadata=_md(args))
    stream = (cc.stub.start_follow_chain if args.follow
              else cc.stub.start_check_chain)
    for progress in stream(req):
        print(f"\rsync {progress.current}/{progress.target}", end="",
              flush=True)
    print()
    return 0


def cmd_token(args) -> int:
    """Tenant token mint/revoke/list over the Control plane (ISSUE 19).
    The minted token string is printed exactly once — the daemon's ledger
    keeps only its id and caveat metadata."""
    cc = _control(args)
    if args.token_cmd == "mint":
        resp = cc.stub.token_mint(pb.TokenMintRequest(
            tenant=args.tenant, chains=args.chains,
            ttl_seconds=args.ttl, read_only=args.read_only,
            metadata=_md(args)))
        print(f"token-id: {resp.token_id}")
        if resp.expires:
            print(f"expires: {resp.expires:.0f}")
        print(resp.token)
        return 0
    if args.token_cmd == "revoke":
        cc.stub.token_revoke(pb.TokenRequest(token_id=args.token_id,
                                             metadata=_md(args)))
        print(f"revoked {args.token_id}")
        return 0
    for t in cc.stub.token_list(pb.TokenRequest(metadata=_md(args))).tokens:
        state = "revoked" if t.revoked else "active"
        caveats = []
        if t.chains:
            caveats.append("chains=" + ",".join(t.chains))
        if t.expires:
            caveats.append(f"expires={t.expires:.0f}")
        if t.read_only:
            caveats.append("read-only")
        print(f"{t.token_id}  {t.tenant}  {state}"
              + ("  " + " ".join(caveats) if caveats else ""))
    return 0


def cmd_identity(args) -> int:
    """Provision the mTLS identity plane: a CA plus one cert dir per
    roster entry (net/identity.py).  `name=host:port` pairs come from the
    group roster; each node then starts with --identity-dir."""
    from .net.identity import provision_fleet
    roster = {}
    for entry in args.nodes:
        name, _, addr = entry.partition("=")
        if not addr:
            raise SystemExit(f"expected name=host[:port], got {entry!r}")
        host = addr.rsplit(":", 1)[0] if ":" in addr else addr
        roster[name] = [host]
    dirs = provision_fleet(args.out, roster, days=args.days)
    for name, d in sorted(dirs.items()):
        print(f"{name}: {d}")
    return 0


def cmd_util(args) -> int:
    cc_lazy = lambda: _control(args)
    if args.util == "check":
        # connectivity probe of listed addresses (cli.go checkCmd)
        client = ProtocolClient()
        bad = 0
        for addr in args.addresses:
            try:
                client.home(Peer(addr, args.tls))
                print(f"{addr}: ok")
            except Exception as e:
                print(f"{addr}: FAIL ({e})")
                bad += 1
        return 1 if bad else 0
    if args.util == "ping":
        cc_lazy().stub.ping_pong(pb.Ping(metadata=_md(args)))
        print("pong")
        return 0
    if args.util == "list-schemes":
        for s in cc_lazy().stub.list_schemes(
                pb.ListSchemesRequest(metadata=_md(args))).ids:
            print(s)
        return 0
    if args.util == "status":
        st = cc_lazy().stub.status(pb.StatusRequest(metadata=_md(args)))
        print(st)
        return 0
    if args.util == "remote-status":
        req = pb.RemoteStatusRequest(metadata=_md(args))
        for a in args.addresses:
            req.addresses.append(pb.StatusAddress(address=a, tls=args.tls))
        print(cc_lazy().stub.remote_status(req))
        return 0
    if args.util == "self-sign":
        from .key.store import FileStore
        fs = FileStore(args.folder, args.id)
        pair = fs.load_keypair()
        pair.self_sign()
        fs.save_keypair(pair)
        print("keypair self-signed")
        return 0
    if args.util == "backup":
        cc_lazy().stub.backup_database(
            pb.BackupDBRequest(output_file=args.out, metadata=_md(args)))
        print(f"backup written to {args.out}")
        return 0
    if args.util == "migrate":
        from .migration import migrate
        did = migrate(args.folder, args.id or "default")
        print("migrated" if did else "nothing to migrate")
        return 0
    if args.util in ("reset", "del-beacon"):
        from .key.store import FileStore
        import shutil
        fs = FileStore(args.folder, args.id)
        fs.reset()
        db = os.path.join(args.folder, "multibeacon",
                          args.id or DEFAULT_BEACON_ID, "db")
        if args.util == "del-beacon" and os.path.isdir(db):
            shutil.rmtree(db)
        print(f"{args.util}: done for beacon {args.id!r}")
        return 0
    raise SystemExit(f"unknown util command {args.util!r}")


# -- parser ------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    root = argparse.ArgumentParser(
        prog="drand", description="TPU-native drand daemon and tools")
    sub = root.add_subparsers(dest="command", required=True)

    p = sub.add_parser("generate-keypair", help="create a longterm keypair")
    _add_common(p)
    p.add_argument("address", help="public host:port of this node")
    p.add_argument("--scheme", default="", help="scheme id")
    p.add_argument("--tls", action="store_true")
    p.set_defaults(fn=cmd_generate_keypair)

    p = sub.add_parser("start", help="run the daemon")
    _add_common(p)
    p.add_argument("--private-listen", default="127.0.0.1:0",
                   help="node-to-node gRPC bind address")
    p.add_argument("--public-listen", default="",
                   help="REST edge bind address (empty = off)")
    p.add_argument("--metrics", type=int, default=None,
                   help="metrics HTTP port (omit = disabled; 0 = ephemeral)")
    p.add_argument("--db", default="sqlite",
                   choices=["sqlite", "memdb", "postgres"])
    p.add_argument("--pg-dsn", default=_env("pg_dsn", ""),
                   help="postgres connection string (--db postgres)")
    p.add_argument("--tls-cert")
    p.add_argument("--tls-key")
    p.add_argument("--dkg-timeout", type=int, default=10)
    p.add_argument("--no-tpu", action="store_true",
                   help="host-only partial verification")
    p.add_argument("--ready-file", default=_env("ready_file", ""),
                   help="write pid + bound ports here once serving "
                        "(fleet supervisors; DRAND_READY_FILE)")
    p.add_argument("--grace", type=float,
                   default=float(_env("grace", 10.0)),
                   help="SIGTERM drain budget in seconds (DRAND_GRACE)")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("stop", help="shut the daemon down")
    _add_common(p)
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser("share", help="run a DKG or reshare")
    _add_common(p)
    p.add_argument("--leader", action="store_true")
    p.add_argument("--connect", help="leader address (followers)")
    p.add_argument("--nodes", type=int, default=0)
    p.add_argument("--threshold", type=int, default=0)
    p.add_argument("--period", type=int, default=30)
    p.add_argument("--catchup-period", type=int, default=0)
    p.add_argument("--scheme", default="")
    p.add_argument("--secret-file")
    p.add_argument("--setup-timeout", type=int, default=60)
    p.add_argument("--transition", action="store_true",
                   help="reshare from the stored group")
    p.add_argument("--from", dest="from_group",
                   help="reshare from this group TOML (newcomers)")
    p.set_defaults(fn=cmd_share)

    p = sub.add_parser("get", help="fetch randomness from a node")
    _add_common(p)
    p.add_argument("what", choices=["public", "chain-info"])
    p.add_argument("url", help="node gRPC address")
    p.add_argument("--round", type=int, default=0)
    p.add_argument("--tls", action="store_true")
    p.add_argument("--chain-hash", help="verify against this chain hash")
    p.set_defaults(fn=cmd_get)

    p = sub.add_parser("show", help="inspect local daemon state")
    _add_common(p)
    p.add_argument("what", choices=["group", "chain-info", "public"])
    p.set_defaults(fn=cmd_show)

    p = sub.add_parser("sync", help="follow or check a chain")
    _add_common(p)
    p.add_argument("--follow", action="store_true")
    p.add_argument("--sync-nodes", nargs="+", default=[])
    p.add_argument("--up-to", type=int, default=0)
    p.add_argument("--chain-hash")
    p.add_argument("--tls", action="store_true")
    p.set_defaults(fn=cmd_sync)

    p = sub.add_parser("token", help="tenant bearer tokens (mint/revoke/list)")
    _add_common(p)
    p.add_argument("token_cmd", choices=["mint", "revoke", "list"])
    p.add_argument("--tenant", default="", help="tenant the token names")
    p.add_argument("--chains", nargs="*", default=[],
                   help="beacon-id allowlist caveat (empty = any chain)")
    p.add_argument("--ttl", type=float, default=0.0,
                   help="seconds until expiry (0 = no expiry caveat)")
    p.add_argument("--read-only", action="store_true")
    p.add_argument("--token-id", default="", help="id to revoke")
    p.set_defaults(fn=cmd_token)

    p = sub.add_parser("identity",
                       help="provision mTLS certs for a roster")
    _add_common(p)
    p.add_argument("nodes", nargs="+", metavar="name=host[:port]",
                   help="roster entries to issue certs for")
    p.add_argument("--out", default="identity",
                   help="output root (CA + one cert dir per node)")
    p.add_argument("--days", type=int, default=365,
                   help="certificate validity in days")
    p.set_defaults(fn=cmd_identity)

    p = sub.add_parser("util", help="maintenance helpers")
    _add_common(p)
    p.add_argument("util", choices=[
        "check", "ping", "list-schemes", "status", "remote-status",
        "self-sign", "backup", "reset", "del-beacon", "migrate"])
    p.add_argument("addresses", nargs="*", default=[])
    p.add_argument("--tls", action="store_true")
    p.add_argument("--out", default="backup.db")
    p.set_defaults(fn=cmd_util)

    return root


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    dlog.configure(level="debug" if args.verbose else "info",
                   json_output=args.json)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
