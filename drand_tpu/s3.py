"""Minimal S3 REST client — pure stdlib, AWS Signature V4.

The reference ships an S3 relay built on the AWS SDK
(cmd/relay-s3/main.go:43-199).  boto3 is not available in this
environment, so the backend speaks the S3 REST API directly over
urllib with SigV4 request signing: PUT/GET/HEAD object is all the relay
needs.  The endpoint is configurable, so the same code path serves AWS,
any S3-compatible store, and the in-suite fake server.
"""

import datetime
import hashlib
import hmac
import os
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, Optional, Tuple


def _sha256(b: bytes) -> str:
    return hashlib.sha256(b).hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


class SigV4Signer:
    """AWS Signature Version 4 for the S3 service (single-chunk payloads)."""

    def __init__(self, access_key: str, secret_key: str, region: str):
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region

    def sign(self, method: str, url: str, headers: Dict[str, str],
             payload: bytes, now: Optional[datetime.datetime] = None
             ) -> Dict[str, str]:
        """Returns `headers` + Authorization/x-amz-* for the request."""
        u = urllib.parse.urlsplit(url)
        now = now or datetime.datetime.now(datetime.timezone.utc)
        amzdate = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        payload_hash = _sha256(payload)
        hdrs = dict(headers)
        hdrs["host"] = u.netloc
        hdrs["x-amz-date"] = amzdate
        hdrs["x-amz-content-sha256"] = payload_hash

        signed = sorted(k.lower() for k in hdrs)
        canonical_headers = "".join(
            f"{k}:{hdrs[_orig(hdrs, k)].strip()}\n" for k in signed)
        signed_headers = ";".join(signed)
        canonical_query = "&".join(
            f"{k}={urllib.parse.quote(v, safe='~')}"
            for k, v in sorted(urllib.parse.parse_qsl(
                u.query, keep_blank_values=True)))
        # S3 canonical URIs must NOT be double-encoded: u.path is already
        # percent-encoded by _url(), so it goes in verbatim (re-quoting
        # would corrupt keys containing space/%/non-ASCII).
        canonical = "\n".join([
            method, u.path or "/",
            canonical_query, canonical_headers, signed_headers, payload_hash])
        scope = f"{datestamp}/{self.region}/s3/aws4_request"
        to_sign = "\n".join(["AWS4-HMAC-SHA256", amzdate, scope,
                             _sha256(canonical.encode())])
        k = _hmac(("AWS4" + self.secret_key).encode(), datestamp)
        k = _hmac(k, self.region)
        k = _hmac(k, "s3")
        k = _hmac(k, "aws4_request")
        sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
        hdrs["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={sig}")
        return hdrs


def _orig(hdrs: Dict[str, str], lower: str) -> str:
    for k in hdrs:
        if k.lower() == lower:
            return k
    raise KeyError(lower)


class S3Client:
    """PUT/GET/HEAD object against an S3(-compatible) endpoint.

    Credentials default to the standard AWS_* environment variables; the
    endpoint defaults to the AWS virtual-hosted S3 URL for the region."""

    def __init__(self, bucket: str, region: str = "us-east-1",
                 endpoint: Optional[str] = None,
                 access_key: Optional[str] = None,
                 secret_key: Optional[str] = None):
        self.bucket = bucket
        self.region = region
        self.endpoint = (endpoint or
                         f"https://{bucket}.s3.{region}.amazonaws.com")
        self._path_style = endpoint is not None
        self.signer = SigV4Signer(
            access_key or os.environ.get("AWS_ACCESS_KEY_ID", ""),
            secret_key or os.environ.get("AWS_SECRET_ACCESS_KEY", ""),
            region)

    def _url(self, key: str) -> str:
        base = self.endpoint.rstrip("/")
        if self._path_style:
            return f"{base}/{self.bucket}/{urllib.parse.quote(key)}"
        return f"{base}/{urllib.parse.quote(key)}"

    def _request(self, method: str, key: str, payload: bytes = b"",
                 headers: Optional[Dict[str, str]] = None
                 ) -> Tuple[int, bytes]:
        url = self._url(key)
        hdrs = self.signer.sign(method, url, headers or {}, payload)
        req = urllib.request.Request(url, data=payload or None,
                                     headers=hdrs, method=method)
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()

    def put_object(self, key: str, data: bytes, content_type: str,
                   acl: str = "public-read",
                   cache_control: Optional[str] = None) -> None:
        hdrs = {"content-type": content_type, "x-amz-acl": acl}
        if cache_control:
            hdrs["cache-control"] = cache_control
        status, body = self._request("PUT", key, data, hdrs)
        if status not in (200, 201):
            raise IOError(f"S3 PUT {key}: HTTP {status}: {body[:200]!r}")

    def get_object(self, key: str) -> Optional[bytes]:
        status, body = self._request("GET", key)
        if status == 404:
            return None
        if status != 200:
            raise IOError(f"S3 GET {key}: HTTP {status}")
        return body

    def head_object(self, key: str) -> bool:
        status, _ = self._request("HEAD", key)
        if status == 200:
            return True
        if status in (403, 404):
            return False
        raise IOError(f"S3 HEAD {key}: HTTP {status}")
