"""User-supplied entropy sources (reference: entropy/entropy.go:16-67).

`get_random(source, n)` falls back to the OS CSPRNG when the custom source
fails or under-delivers; `ScriptReader` shells out to a user executable and
concatenates its stdout until n bytes are available.
"""

import secrets
import subprocess
from typing import Optional


class ScriptReader:
    """Entropy from a user script's stdout (entropy.go:33-58)."""

    def __init__(self, path: str):
        self.path = path

    def read(self, n: int) -> bytes:
        if not self.path:
            raise ValueError("no reader was provided")
        out = b""
        while len(out) < n:
            proc = subprocess.run([self.path], capture_output=True,
                                  timeout=30)
            if proc.returncode != 0 or not proc.stdout:
                raise OSError(f"entropy script failed: rc={proc.returncode}")
            out += proc.stdout
        return out[:n]


def get_random(source: Optional[object], n: int) -> bytes:
    """n random bytes from `source` (an object with .read(n)->bytes), with
    CSPRNG fallback on any failure (entropy.go:16-30)."""
    if source is None:
        return secrets.token_bytes(n)
    try:
        data = source.read(n)
        if len(data) == n:
            return data
    except Exception:
        pass
    return secrets.token_bytes(n)
