"""Relays: re-serve a drand chain from any client stack.

Reference surface:
  * HTTP relay (cmd/relay/main.go:1-184): standalone REST frontend over a
    client.Client — same routes as the daemon's edge, but backed by remote
    sources.
  * Gossip relay (lp2p/relaynode.go:34-179): watches a source and
    republishes every round over a one-to-many transport with full BLS
    validation before relaying (lp2p/client/validator.go:18-68).  libp2p
    isn't available in this environment, so the fan-out transport is the
    gRPC Public service (`PublicRandStream`) — consumers use the ordinary
    GrpcTransport client against the relay.
  * S3 relay (cmd/relay-s3/main.go:43-199): uploads every round as a
    public JSON object + a `latest` pointer.  The object-store interface is
    pluggable: a local-directory backend ships here (and is what tests
    exercise); an S3 backend slots in where boto3 exists.
"""

import json
import os
import threading

from .common import make_condition, make_lock
from typing import Iterator, Optional

from .beacon.clock import Clock, RealClock
from .chain.beacon import Beacon
from .chain.errors import ErrNoBeaconStored
from .client.interface import Client, Result
from .client.verify import verify_beacon_with_info
from .log import Logger


# ---------------------------------------------------------------------------
# Validating watch: the gossip validator semantic (validator.go:18-68)
# ---------------------------------------------------------------------------

class ValidatingWatch:
    """Wraps a client's watch: drops future rounds, duplicates, and
    anything that fails full BLS verification — the relay never
    republishes junk."""

    def __init__(self, client: Client, log: Logger,
                 clock: Optional[Clock] = None):
        self.client = client
        self.log = log
        self.clock = clock or RealClock()
        self.info = client.info()
        self._seen_max = 0

    def watch(self, stop: Optional[threading.Event] = None
              ) -> Iterator[Result]:
        from .chain.timing import current_round
        for res in self.client.watch(stop):
            now_round = current_round(int(self.clock.now()),
                                      self.info.period,
                                      self.info.genesis_time)
            if res.round > now_round + 1:
                self.log.warn("dropping future round", round=res.round)
                continue
            if res.round <= self._seen_max:
                continue
            if not verify_beacon_with_info(self.info, res.beacon()):
                self.log.warn("dropping invalid beacon", round=res.round)
                continue
            self._seen_max = res.round
            yield res


# ---------------------------------------------------------------------------
# gRPC fan-out relay (the gossipsub-equivalent distribution node)
# ---------------------------------------------------------------------------

class GrpcRelayNode:
    """Watches a source client and re-serves the chain over the Public
    gRPC service with live streaming fan-out (relaynode.go:34-101
    semantics on the gRPC transport)."""

    def __init__(self, client: Optional[Client], listen: str = "127.0.0.1:0",
                 log: Optional[Logger] = None, buffer: int = 256,
                 info=None, extra_services=(),
                 clock: Optional[Clock] = None):
        from .net import Listener, services

        self.log = (log or Logger()).named("relay")
        self.client = client
        self.clock = clock or RealClock()
        self.info = info if info is not None else client.info()
        self.valid = (ValidatingWatch(client, self.log, clock=self.clock)
                      if client is not None else None)
        self._cache = {}                 # round -> Result (bounded)
        self._buffer = buffer
        self._latest = 0
        # Eviction watermark: highest round ever evicted from the serving
        # cache.  Dedup must NOT rely on cache membership alone — a replayed
        # historical round would be inserted, instantly evicted as
        # min(_cache), and re-forwarded forever (self-sustaining packet
        # storm; the lp2p reference keeps a seen-TTL cache independent of
        # delivery state).  Rounds <= the watermark count as already seen.
        self._evicted = 0
        self._lock = make_lock()
        self._new = make_condition(self._lock)
        self._stop = threading.Event()
        self.listener = Listener(
            listen, [(services.PUBLIC, _RelayPublic(self))]
            + list(extra_services))
        host = listen.rsplit(":", 1)[0]
        self.address = f"{host}:{self.listener.port}"
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self.listener.start()
        if self.valid is not None:
            self._thread = threading.Thread(target=self._pump, daemon=True,
                                            name="relay-pump")
            self._thread.start()
        self.log.info("gRPC relay serving", addr=self.address)

    def _deliver(self, res: Result) -> bool:
        """Insert one validated round into the serving cache; returns False
        for duplicates (already delivered).

        Eviction is a pure watermark (latest - buffer), deliberately: any
        round at or below it is treated as seen even if it never arrived,
        so a legitimately late straggler more than `buffer` behind latest
        is dropped without ever being cached or forwarded.  That is the
        anti-replay-storm tradeoff (a fresh node joining a mesh must not
        re-gossip deep history at it); stragglers that recent clients still
        need are served by the HTTP/gRPC catch-up path, not the gossip
        fan-out.  The libp2p reference instead keeps a TTL'd seen-set
        (lp2p/client) — switch to that if first-time delivery of deep
        stragglers ever matters more than storm immunity."""
        with self._lock:
            if res.round in self._cache or res.round <= self._evicted:
                return False
            self._cache[res.round] = res
            self._latest = max(self._latest, res.round)
            # anything at or below latest - buffer counts as seen even
            # before the cache ever overflows (a fresh node must not
            # re-forward replayed historical rounds during warm-up)
            self._evicted = max(self._evicted, self._latest - self._buffer)
            while len(self._cache) > self._buffer:
                mn = min(self._cache)
                self._evicted = max(self._evicted, mn)
                del self._cache[mn]
            self._new.notify_all()
            return True

    def _pump(self) -> None:
        while not self._stop.is_set():
            try:
                for res in self.valid.watch(self._stop):
                    self._deliver(res)
                    if self._stop.is_set():
                        return
            except Exception as e:
                self.log.warn("relay watch failed; retrying", err=str(e))
            self._stop.wait(1.0)

    def get(self, round_: int) -> Result:
        with self._lock:
            if round_ == 0 and self._latest:
                return self._cache[self._latest]
            if round_ in self._cache:
                return self._cache[round_]
        return self.client.get(round_)

    def wait_next(self, after: int, timeout: float = 1.0) -> Optional[Result]:
        """Smallest cached round > `after` (so a stream consumer sees every
        round the relay holds, in order); falls to the latest only when the
        bounded cache already evicted the requested range."""
        def pick():
            if self._latest <= after:
                return None
            nxt = after + 1
            if nxt in self._cache:
                return self._cache[nxt]
            later = [r for r in self._cache if r > after]
            return self._cache[min(later)] if later else None

        with self._lock:
            got = pick()
            if got is None:
                self._new.wait(timeout)
                got = pick()
            return got

    def stop(self) -> None:
        self._stop.set()
        pump, self._thread = self._thread, None
        if pump is not None:
            pump.join(timeout=5)
        self.listener.stop()
        if self.client is not None:
            self.client.close()


class _RelayPublic:
    """drand.Public impl backed by the relay cache/source."""

    def __init__(self, node: GrpcRelayNode):
        self.node = node

    def _rand(self, res: Result):
        from .net import convert
        return convert.beacon_to_rand(res.beacon(),
                                      self.node.info.beacon_id)

    def public_rand(self, req, context):
        import grpc
        try:
            return self._rand(self.node.get(req.round))
        except Exception as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))

    def public_rand_stream(self, req, context):
        stop = threading.Event()
        context.add_callback(stop.set)
        sent = req.round - 1 if req.round else self.node._latest - 1
        while not stop.is_set() and not self.node._stop.is_set():
            res = self.node.wait_next(sent, timeout=0.5)
            if res is not None and res.round > sent:
                sent = res.round
                yield self._rand(res)

    def chain_info(self, req, context):
        from .net import convert
        return convert.info_to_proto(self.node.info)

    def home(self, req, context):
        from .protos import drand_pb2 as pb
        return pb.HomeResponse(status="drand relay up")


# ---------------------------------------------------------------------------
# Gossip mesh relay (lp2p/relaynode.go:34-101 rebuilt over the gRPC plane)
# ---------------------------------------------------------------------------

class GossipRelayNode(GrpcRelayNode):
    """One node of a pubsub MESH: epidemic one-to-many distribution, not
    hub-and-spoke (VERDICT r2 #6).  Semantics per lp2p gossipsub:

      * static peer list (bootstrap graph), per-hop fanout bound
      * seen-cache dedup: each round is validated + forwarded at most once
      * validate-before-relay: full BLS verification against the pinned
        chain info BEFORE forwarding (lp2p/client/validator.go:18-68) —
        a node never amplifies junk
      * origin nodes (with a source `client`) inject their watch stream;
        pure relay nodes need only the chain `info`

    Consumers read any node through the ordinary Public gRPC service."""

    def __init__(self, listen: str = "127.0.0.1:0", peers=(),
                 client: Optional[Client] = None, info=None, fanout: int = 3,
                 log: Optional[Logger] = None, buffer: int = 256,
                 clock: Optional[Clock] = None):
        from .net import services

        self._gossip_impl = _GossipService(self)
        super().__init__(client, listen, log=log, buffer=buffer, info=info,
                         extra_services=[(services.GOSSIP, self._gossip_impl)],
                         clock=clock)
        from concurrent.futures import ThreadPoolExecutor

        self.peers = list(peers)
        self.fanout = fanout
        self._send_pool = ThreadPoolExecutor(
            max_workers=max(4, 2 * fanout), thread_name_prefix="gossip-send")
        self._channels = {}
        self._chan_lock = make_lock()
        self._chain_hash = self.info.hash()
        # mesh observability: delivered (first-seen), dup (suppressed),
        # invalid (failed validation) — tests assert dedup through these
        self.stats = {"delivered": 0, "dup": 0, "invalid": 0}

    def add_peer(self, addr: str) -> None:
        if addr not in self.peers and addr != self.address:
            self.peers.append(addr)

    # -- mesh ingress/egress --------------------------------------------------

    def _pump(self) -> None:
        """Origin: validated source rounds enter the mesh here."""
        while not self._stop.is_set():
            try:
                for res in self.valid.watch(self._stop):
                    if self._deliver(res):
                        self._forward(res, exclude=())
                    if self._stop.is_set():
                        return
            except Exception as e:
                self.log.warn("relay watch failed; retrying", err=str(e))
            self._stop.wait(1.0)

    def on_gossip(self, pkt) -> None:
        """One gossip hop: dedup -> validate -> deliver -> re-forward."""
        if pkt.chain_hash != self._chain_hash:
            raise ValueError("gossip for unknown chain")
        with self._lock:
            if pkt.round in self._cache or pkt.round <= self._evicted:
                self.stats["dup"] += 1
                return                       # seen: suppress re-broadcast
        beacon = Beacon(round=pkt.round, signature=pkt.signature,
                        previous_sig=pkt.previous_signature or None)
        if not verify_beacon_with_info(self.info, beacon):
            self.stats["invalid"] += 1
            self.log.warn("dropping invalid gossip beacon", round=pkt.round)
            return
        res = Result.from_beacon(beacon)
        if self._deliver(res):
            self.stats["delivered"] += 1
            self._forward(res, exclude=(pkt.sender,))
        else:
            self.stats["dup"] += 1

    def _forward(self, res: Result, exclude=()) -> None:
        import random

        targets = [p for p in self.peers if p not in exclude]
        if len(targets) > self.fanout:
            targets = random.sample(targets, self.fanout)
        enq = self.clock.monotonic()
        for addr in targets:
            # bounded sender pool, not thread-per-send: slow peers (5 s
            # timeout each) must queue, not pile up hundreds of threads
            self._send_pool.submit(self._send, addr, res, enq)

    # sends that sat queued longer than this behind slow/blackholed peers
    # are dropped — the round is stale to the mesh by then, and dropping
    # keeps the queue draining.  Gated on QUEUE AGE, not round recency: a
    # catch-up burst delivers many rounds back-to-back and every one of
    # them must still be forwarded when the pool is keeping up.  Age is
    # a DURATION, so it is measured on the injected clock's monotonic
    # source: deterministic under a FakeClock in mesh chaos tests, and
    # immune to wall-clock jumps (NTP step, VM suspend) in production.
    SEND_MAX_QUEUE_AGE = 10.0

    def _send(self, addr: str, res: Result, enq: float = 0.0) -> None:
        from .protos import drand_pb2 as pb

        if enq and self.clock.monotonic() - enq > self.SEND_MAX_QUEUE_AGE:
            return
        pkt = pb.GossipBeaconPacket(
            chain_hash=self._chain_hash, round=res.round,
            signature=res.signature,
            previous_signature=res.previous_signature or b"",
            sender=self.address)
        try:
            self._stub(addr).publish(pkt, timeout=5)
        except Exception as e:
            self.log.warn("gossip send failed", peer=addr, err=str(e))

    def _stub(self, addr: str):
        import grpc

        from .net import services

        with self._chan_lock:
            stub = self._channels.get(addr)
            if stub is None:
                chan = grpc.insecure_channel(addr)
                stub = services.GOSSIP.stub(chan)
                self._channels[addr] = stub
            return stub

    def stop(self) -> None:
        super().stop()
        self._send_pool.shutdown(wait=False, cancel_futures=True)


class _GossipService:
    """drand.Gossip impl: one `Publish` hop."""

    def __init__(self, node: "GossipRelayNode"):
        self.node = node

    def publish(self, req, context):
        import grpc

        from .protos import drand_pb2 as pb

        try:
            self.node.on_gossip(req)
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        return pb.Empty()


# ---------------------------------------------------------------------------
# Object-store relay (the S3 relay shape)
# ---------------------------------------------------------------------------

class ObjectStore:
    """Object-store interface (cmd/relay-s3's S3 usage)."""

    def put(self, key: str, data: bytes, content_type: str) -> None:
        raise NotImplementedError

    def get(self, key: str):
        """Returns bytes or None when absent."""
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        return self.get(key) is not None


class DirObjectStore(ObjectStore):
    """Local-directory backend (any FUSE/rclone-mounted bucket)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def put(self, key: str, data: bytes, content_type: str) -> None:
        path = os.path.join(self.root, key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path + ".tmp", "wb") as f:
            f.write(data)
        os.replace(path + ".tmp", path)

    def get(self, key: str):
        try:
            with open(os.path.join(self.root, key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def exists(self, key: str) -> bool:
        return os.path.exists(os.path.join(self.root, key))


class S3ObjectStore(ObjectStore):
    """S3 backend over the stdlib SigV4 client (drand_tpu/s3.py) — rounds
    are immutable public JSON objects (cmd/relay-s3/main.go:127-146:
    public-read ACL + a week-long immutable cache-control)."""

    IMMUTABLE_CC = "public, max-age=604800, immutable"

    def __init__(self, bucket: str, region: str = "us-east-1",
                 endpoint=None, access_key=None, secret_key=None):
        from .s3 import S3Client
        self.client = S3Client(bucket, region, endpoint=endpoint,
                               access_key=access_key, secret_key=secret_key)

    def put(self, key: str, data: bytes, content_type: str) -> None:
        # `latest`/`info` pointers are mutable; round objects immutable
        cc = None if key.endswith(("/latest", "/info")) else self.IMMUTABLE_CC
        self.client.put_object(key, data, content_type, cache_control=cc)

    def get(self, key: str):
        return self.client.get_object(key)

    def exists(self, key: str) -> bool:
        return self.client.head_object(key)


class ObjectStoreRelay:
    """Uploads every verified round as `<chain-hash>/public/<round>` JSON
    plus a `latest` pointer (cmd/relay-s3/main.go:43-199)."""

    def __init__(self, client: Client, store: ObjectStore,
                 log: Optional[Logger] = None,
                 clock: Optional[Clock] = None):
        self.client = client
        self.store = store
        self.log = (log or Logger()).named("s3-relay")
        self.clock = clock or RealClock()
        self.info = client.info()
        self.prefix = self.info.hash().hex()
        self.valid = ValidatingWatch(client, self.log, clock=self.clock)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _obj(self, res: Result) -> bytes:
        obj = {"round": res.round, "randomness": res.randomness.hex(),
               "signature": res.signature.hex()}
        if res.previous_signature:
            obj["previous_signature"] = res.previous_signature.hex()
        return json.dumps(obj, separators=(",", ":")).encode()

    def upload(self, res: Result, update_latest: bool = True) -> None:
        data = self._obj(res)
        self.store.put(f"{self.prefix}/public/{res.round}", data,
                       "application/json")
        if update_latest:
            self.store.put(f"{self.prefix}/public/latest", data,
                           "application/json")

    def sync(self, from_round: int, to_round: int) -> int:
        """Backfill rounds [from, to] against the bucket, skipping objects
        already uploaded (cmd/relay-s3/main.go:149-199 `sync`; the skip is
        the main.go:181 TODO made real)."""
        n = 0
        for r in range(from_round, to_round + 1):
            if self.store.exists(f"{self.prefix}/public/{r}"):
                continue
            res = self.client.get(r)
            if verify_beacon_with_info(self.info, res.beacon()):
                # backfill must not rewind the `latest` pointer
                self.upload(res, update_latest=False)
                n += 1
        return n

    def start(self) -> None:
        self.store.put(f"{self.prefix}/info", self.info.to_json(),
                       "application/json")

        def run():
            while not self._stop.is_set():
                try:
                    for res in self.valid.watch(self._stop):
                        self.upload(res)
                        if self._stop.is_set():
                            return
                except Exception as e:
                    self.log.warn("relay watch failed; retrying", err=str(e))
                self._stop.wait(1.0)

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="s3-relay")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)


# ---------------------------------------------------------------------------
# HTTP relay (cmd/relay): REST frontend over a client stack
# ---------------------------------------------------------------------------

class HttpRelay:
    """Serves /info /public/{round}|latest /health from a client stack."""

    def __init__(self, client: Client, listen: str = "127.0.0.1:0",
                 log: Optional[Logger] = None):
        from http.server import BaseHTTPRequestHandler

        from .http_server import BoundedHTTPServer

        self.client = client
        self.info = client.info()
        self.log = (log or Logger()).named("http-relay")
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                try:
                    code, body = outer._route(self.path)
                except Exception as e:
                    code, body = 500, json.dumps({"error": str(e)}).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                self.wfile.write(body)

        host, _, port = listen.rpartition(":")
        # bounded worker pool, not thread-per-request: an edge relay is
        # the FIRST thing a read flood hits (net/admission.py doctrine)
        self.httpd = BoundedHTTPServer((host or "127.0.0.1", int(port)),
                                       Handler, workers=8)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def _route(self, path: str):
        parts = [p for p in path.split("/") if p]
        if parts and len(parts[0]) == 64:
            if parts[0] != self.info.hash().hex():
                return 404, b'{"error":"unknown chain"}'
            parts = parts[1:]
        if parts == ["info"]:
            return 200, self.info.to_json()
        if parts == ["health"]:
            return 200, b'{"status":true}'
        if len(parts) == 2 and parts[0] == "public":
            round_ = 0 if parts[1] == "latest" else int(parts[1])
            res = self.client.get(round_)
            obj = {"round": res.round, "randomness": res.randomness.hex(),
                   "signature": res.signature.hex()}
            if res.previous_signature:
                obj["previous_signature"] = res.previous_signature.hex()
            return 200, json.dumps(obj, separators=(",", ":")).encode()
        return 404, b'{"error":"no such route"}'

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="http-relay")
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)
        self.client.close()
