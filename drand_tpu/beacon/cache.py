"""Partial-signature cache with anti-DoS bounds (chain/beacon/cache.go:17-168).

Partials are cached per (round, previous_sig) key — a malicious node cannot
poison a round by sending a partial with a different previous signature than
honest nodes'.  Each signer index may occupy at most MAX_PARTIALS_PER_NODE
cached rounds; its oldest round is evicted beyond that (constants.go:14)."""

import threading

from ..common import make_lock
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..crypto.tbls import index_of

MAX_PARTIALS_PER_NODE = 100
# How many distinct INVALID partials one signer index may submit to a single
# round before that index is banned for the round.  Bounds both the `checked`
# map and the device-verification work an equivocating member can force
# (without it, distinct garbage blobs re-admit forever on a round that never
# reaches threshold).
MAX_BAD_PER_INDEX = 3


class _RoundCache:
    def __init__(self, round_: int, prev_sig: Optional[bytes]):
        self.round = round_
        self.prev_sig = prev_sig
        self.partials: Dict[int, bytes] = {}
        # partial BYTES -> verification outcome, filled at aggregation time.
        # Keyed by the exact bytes (not the signer index) so that dropping an
        # invalid partial and later receiving an honest one from the same
        # index forces re-verification, and an evicted-then-replaced partial
        # can never inherit a stale verdict.
        self.checked: Dict[bytes, bool] = {}
        self.bad_count: Dict[int, int] = {}

    def mark_bad(self, partial: bytes) -> None:
        """Record a failed verification verdict (called by the aggregator)."""
        self.checked[partial] = False
        idx = index_of(partial)
        self.bad_count[idx] = self.bad_count.get(idx, 0) + 1

    def append(self, partial: bytes) -> bool:
        idx = index_of(partial)
        if idx in self.partials:
            return False
        if self.bad_count.get(idx, 0) >= MAX_BAD_PER_INDEX:
            return False  # index banned for this round (anti-DoS)
        if self.checked.get(partial) is False:
            return False  # known-bad bytes; don't re-admit
        self.partials[idx] = partial
        return True

    def __len__(self) -> int:
        return len(self.partials)


class PartialCache:
    def __init__(self, max_per_node: int = MAX_PARTIALS_PER_NODE):
        self._lock = make_lock()
        self._rounds: Dict[Tuple[int, bytes], _RoundCache] = {}
        # per-signer FIFO of cache keys it occupies (eviction order)
        self._per_node: Dict[int, OrderedDict] = {}
        self._max_per_node = max_per_node

    @staticmethod
    def _key(round_: int, prev_sig: Optional[bytes]):
        return (round_, prev_sig or b"")

    def append(self, round_: int, prev_sig: Optional[bytes],
               partial: bytes) -> "_RoundCache":
        """Cache one partial; returns the round cache it landed in."""
        idx = index_of(partial)
        key = self._key(round_, prev_sig)
        with self._lock:
            rc = self._rounds.get(key)
            if rc is None:
                rc = self._rounds[key] = _RoundCache(round_, prev_sig)
            if rc.append(partial):
                self._note_occupancy_locked(idx, key)
            return rc

    def put_verified(self, round_: int, prev_sig: Optional[bytes],
                     partial: bytes) -> "_RoundCache":
        """Insert a partial KNOWN-GOOD for this (round, prev_sig) — the
        Handel overlay batch-verified it against the same digest.  Unlike
        `append`, it may EVICT an occupant of the signer slot whose bytes
        are not themselves verified-good: an ingress forgery (valid index,
        garbage sig — the cheap checks can't tell) must not squat the slot
        of an honestly verified partial, or one packet per node per round
        wedges aggregation at threshold-1.  A verified-good occupant is
        never displaced, and bytes previously marked bad never re-enter."""
        idx = index_of(partial)
        key = self._key(round_, prev_sig)
        with self._lock:
            rc = self._rounds.get(key)
            if rc is None:
                rc = self._rounds[key] = _RoundCache(round_, prev_sig)
            if rc.checked.get(partial) is False:
                return rc       # an explicit bad verdict is final
            rc.checked[partial] = True
            cur = rc.partials.get(idx)
            if cur is None or (cur != partial
                               and rc.checked.get(cur) is not True):
                rc.partials[idx] = partial
                self._note_occupancy_locked(idx, key)
            return rc

    def _note_occupancy_locked(self, idx: int, key) -> None:
        """Per-signer FIFO bookkeeping + eviction.  Caller holds _lock
        (both call sites acquire it around the whole insert)."""
        seen = self._per_node.setdefault(idx, OrderedDict())
        if key not in seen:
            seen[key] = True
            if len(seen) > self._max_per_node:
                evict_key, _ = seen.popitem(last=False)
                evicted = self._rounds.get(evict_key)
                if evicted is not None:
                    evicted.partials.pop(idx, None)
                    if not evicted.partials:
                        del self._rounds[evict_key]  # tpu-vet: disable=lock

    def get(self, round_: int, prev_sig: Optional[bytes]) -> Optional[_RoundCache]:
        with self._lock:
            return self._rounds.get(self._key(round_, prev_sig))

    def get_round_partials(self, round_: int) -> List[bytes]:
        """All partials cached for a round across prev-sig variants."""
        with self._lock:
            out = []
            for (r, _), rc in self._rounds.items():
                if r == round_:
                    out.extend(rc.partials.values())
            return out

    def flush_rounds(self, upto: int) -> None:
        """Drop every cached round <= upto (cache.go:55-70): once a beacon is
        stored, its partials are useless."""
        with self._lock:
            for key in [k for k in self._rounds if k[0] <= upto]:
                del self._rounds[key]
            for seen in self._per_node.values():
                for key in [k for k in seen if k[0] <= upto]:
                    del seen[key]
