"""Aggregator: collect validated partials, recover at threshold, verify,
append (chain/beacon/chainstore.go:24-333).

A single aggregator thread consumes validated partials from a queue (the
reference's `runAggregator` goroutine).  When a (round, prev_sig) cache
reaches the group threshold it Lagrange-recovers the full signature
(tbls.Recover, chainstore.go:202), verifies it against the collective key
(chainstore.go:207) and appends through the decorator chain; the cache is
flushed on every store (partials for stored rounds are dead weight)."""

import queue
import threading

from ..common import make_condition
from typing import Callable, Optional

from ..chain.beacon import Beacon
from ..chain.errors import ErrNoBeaconSaved, ErrNoBeaconStored
from ..crypto import tbls
from ..crypto.vault import Vault
from .cache import PartialCache
from .clock import Clock
from .stores import (AppendStore, CallbackStore, DiscrepancyStore,
                     ErrBeaconAlreadyStored, SchemeStore)


class HostPartialVerifier:
    """Serial host verification (the reference's per-packet path)."""

    def __init__(self, scheme, pub_poly):
        self.scheme = scheme
        self.pub_poly = pub_poly

    def verify(self, msg: bytes, partials):
        return [tbls.verify_partial(self.scheme, self.pub_poly, msg, p)
                for p in partials]


class DevicePartialVerifier:
    """TPU-batched verification (crypto/partials.py) — the design's point:
    partials are validated in one RLC block at aggregation time instead of
    one 2-pairing check per packet (node.go:150)."""

    def __init__(self, scheme, pub_poly, n_nodes: int):
        from .. crypto.partials import BatchPartialVerifier
        self._bv = BatchPartialVerifier(scheme, pub_poly, n_nodes)

    def verify(self, msg: bytes, partials):
        return self._bv.verify_partials([msg], [list(partials)])[0].tolist()


class ChainStore:
    def __init__(self, backend, vault: Vault, clock: Clock, group,
                 on_sync_needed: Optional[Callable[[int], None]] = None,
                 on_discrepancy=None, partial_verifier=None):
        """`backend`: raw chain.Store; `group`: key.Group (threshold, times).

        Decorator assembly mirrors chainstore.go:43-75.  Partials get their
        cryptographic check at aggregation time through `partial_verifier`
        (host serial by default; DevicePartialVerifier for the TPU path)."""
        self.vault = vault
        self.group = group
        self.backend = backend      # raw store: integrity scans + repair
        self.partial_verifier = partial_verifier or HostPartialVerifier(
            vault.scheme, vault.get_pub())
        disc = DiscrepancyStore(backend, clock, group.period,
                                group.genesis_time, on_discrepancy)
        sch = SchemeStore(disc, vault.scheme.chained)
        self._append = AppendStore(sch)
        self.cbstore = CallbackStore(self._append)
        self.cache = PartialCache()
        self.on_sync_needed = on_sync_needed
        self._partials: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._new_beacon = make_condition()
        self._thread = threading.Thread(target=self._run_aggregator,
                                        daemon=True, name="aggregator")
        self._thread.start()

    # -- store facade (reads/writes go through the decorator chain) ---------

    @property
    def store(self):
        return self.cbstore

    def last(self) -> Beacon:
        return self.cbstore.last()

    def put(self, beacon: Beacon) -> None:
        self.cbstore.put(beacon)
        self._on_stored(beacon)

    def _on_stored(self, beacon: Beacon) -> None:
        self.cache.flush_rounds(beacon.round)
        with self._new_beacon:
            self._new_beacon.notify_all()

    def integrity_scan(self, verifier=None, mode: str = "full",
                       upto: Optional[int] = None, progress=None,
                       beacon_id: str = "default", chunk: int = 512,
                       trigger: str = "startup", resume=None):
        """Scan the RAW backend (below the decorators — corruption hides
        underneath them) against this chain's scheme + genesis seed.
        Returns a chain.integrity.ScanReport; pair with
        `SyncManager.heal` to quarantine + re-fetch what it finds.
        `resume` (a chain.integrity.ScanCheckpoint) skips the prefix a
        previous scan already proved clean."""
        from ..chain.integrity import IntegrityScanner
        return IntegrityScanner(
            self.backend, self.vault.scheme, verifier=verifier,
            genesis_seed=self.group.get_genesis_seed(), chunk=chunk,
            beacon_id=beacon_id, trigger=trigger).scan(mode=mode, upto=upto,
                                                       progress=progress,
                                                       resume=resume)

    def wait_for_round(self, round_: int, timeout: float,
                       scheduled_time: bool = False) -> Optional[Beacon]:
        """Block until the chain reaches `round_`.

        With ``scheduled_time=False`` (default) the timeout is plain wall
        time — what an RPC-deadline caller expects.

        ``scheduled_time=True`` (used by the test harness) makes the
        timeout *starvation-aware*: on a loaded box (e.g. sibling test
        workers cold-compiling XLA programs on the one host core) a 0.1 s
        condition wait can take seconds of wall time while this process is
        descheduled.  Charging raw wall time against the deadline makes
        tests flake exactly when the machine is busy — so each iteration
        charges at most 2x the requested wait, i.e. the deadline counts
        (mostly-)scheduled time.  A hard wall cap of 20x the timeout still
        bounds genuine deadlocks."""
        # The monotonic() reads below deliberately bypass the injected
        # clock: this loop measures raw WALL time to detect OS
        # descheduling (charged-vs-elapsed) — a FakeClock would defeat
        # the starvation-awareness that is its whole point.
        import time as _t
        charged = 0.0
        wall_cap = (20 if scheduled_time else 1) * timeout
        # tpu-vet: disable=clock
        wall_deadline = _t.monotonic() + wall_cap
        while True:
            try:
                last = self.last()
                if last.round >= round_:
                    if last.round == round_:
                        return last
                    try:
                        return self.cbstore.get(round_)
                    except ErrNoBeaconSaved:
                        return None  # trimmed/skipped (e.g. memdb ring buffer)
            except ErrNoBeaconStored:
                pass
            # tpu-vet: disable=clock
            if charged >= timeout or _t.monotonic() >= wall_deadline:
                return None
            step = min(timeout - charged, 0.1)
            # tpu-vet: disable=clock
            t0 = _t.monotonic()
            with self._new_beacon:
                self._new_beacon.wait(step)
            # tpu-vet: disable=clock
            charged += min(_t.monotonic() - t0, 2 * step)

    # -- aggregation ---------------------------------------------------------

    def new_valid_partial(self, round_: int, prev_sig: Optional[bytes],
                          partial: bytes) -> None:
        """Feed one ingress-validated partial (chainstore.go:106)."""
        self._partials.put((round_, prev_sig, partial))

    def aggregate_verified(self, round_: int, prev_sig: Optional[bytes],
                           partials) -> None:
        """Handel delivery (beacon/handel.py): the overlay hands over a
        set of partials it ALREADY batch-verified through the verify
        service.  The verdicts are recorded in the round cache keyed by
        exact bytes — the same structure the aggregator consults — so
        recovery proceeds without re-verifying, and a partial the flat
        path would have rejected can never sneak in (a pre-existing False
        verdict for the same bytes is never overwritten).  Insertion uses
        `put_verified`: a known-good partial may displace an UNVERIFIED
        squatter in its signer slot (an ingress forgery with a valid
        index would otherwise hold the slot until threshold-time
        verification pops it — after the overlay's delivery was already
        consumed, wedging the round at threshold-1).  Processing still
        rides the single aggregator thread."""
        for p in partials:
            self.cache.put_verified(round_, prev_sig, p)
            self._partials.put((round_, prev_sig, p))

    def _run_aggregator(self) -> None:
        while not self._stop.is_set():
            try:
                item = self._partials.get(timeout=0.1)
            except queue.Empty:
                continue
            try:
                self._process_partial(*item)
            except Exception:
                pass

    def _process_partial(self, round_: int, prev_sig: Optional[bytes],
                         partial: bytes) -> None:
        try:
            last = self.cbstore.last()
        except ErrNoBeaconStored:
            return
        if round_ <= last.round:
            return  # already have that beacon
        rc = self.cache.append(round_, prev_sig, partial)
        thr = self.group.threshold
        if len(rc) < thr:
            return

        scheme = self.vault.scheme
        msg = scheme.digest_beacon(round_, prev_sig if scheme.chained else None)

        # Verify whatever the cache holds unchecked, in one batch (the
        # TPU-first move of node.go:150's per-packet pairing to aggregation
        # time).  Verdicts are keyed by the exact partial bytes: a dropped
        # invalid partial does not block a later honest partial from the
        # same signer index from being verified and used.
        unchecked = [p for p in rc.partials.values() if p not in rc.checked]
        if unchecked:
            results = self.partial_verifier.verify(msg, unchecked)
            for p, ok in zip(unchecked, results):
                if ok:
                    rc.checked[p] = True
                else:
                    rc.mark_bad(p)
                    # drop the slot only if it still holds THESE bytes —
                    # popping by index alone could evict a good partial
                    # that re-occupied the slot while this one verified
                    if rc.partials.get(tbls.index_of(p)) == p:
                        rc.partials.pop(tbls.index_of(p), None)
        good = [p for p in rc.partials.values() if rc.checked.get(p)]
        if len(good) < thr:
            return

        pub_poly = self.vault.get_pub()
        try:
            sig = tbls.recover(scheme, pub_poly, msg, good[:thr],
                               thr, len(self.group), verify_each=False)
        except ValueError:
            return
        pub = self.vault.public_key_bytes()
        if not scheme.verify_beacon(pub, round_, prev_sig, sig):
            # should be unreachable once partials are verified; drop and wait
            # for more honest partials (chainstore.go:207-218)
            rc.partials.clear()
            rc.checked.clear()
            return
        beacon = Beacon(round=round_, signature=sig, previous_sig=prev_sig)
        self._try_append(last, beacon)

    def _try_append(self, last: Beacon, beacon: Beacon) -> None:
        if last.round + 1 < beacon.round:
            # we aggregated a round ahead of our chain: sync the gap first
            if self.on_sync_needed is not None:
                self.on_sync_needed(beacon.round)
            return
        try:
            self.put(beacon)
        except ErrBeaconAlreadyStored:
            pass  # racing with the sync path is benign (chainstore.go:253-265)
        except ValueError:
            pass

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
        self.cbstore.close()
