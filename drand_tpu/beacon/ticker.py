"""Genesis-anchored round ticker (chain/beacon/ticker.go:13-131).

One thread computes each round boundary from (genesis, period) — never by
accumulating sleeps, so drift cannot build up — and fans (round, time) ticks
out to subscriber queues.  Subscribers registered with a `start_at` time only
see ticks from that time on (ticker.go:42-58)."""

import queue
import threading

from ..common import make_lock
from dataclasses import dataclass
from typing import List, Optional, Tuple

from ..chain.timing import current_round, time_of_round
from .clock import Clock


@dataclass
class Tick:
    round: int
    time: int


class Ticker:
    def __init__(self, clock: Clock, period: int, genesis_time: int):
        self.clock = clock
        self.period = period
        self.genesis = genesis_time
        self._subs: List[Tuple[queue.Queue, int]] = []
        self._lock = make_lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def current_round(self) -> int:
        return current_round(int(self.clock.now()), self.period, self.genesis)

    def channel(self, start_at: int = 0) -> "queue.Queue[Tick]":
        """Queue of future ticks; only ticks at/after `start_at` delivered."""
        q: queue.Queue = queue.Queue()
        with self._lock:
            self._subs.append((q, start_at))
        return q

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ticker")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        last_fired = 0
        while not self._stop.is_set():
            now = int(self.clock.now())
            if now < self.genesis:
                if not self.clock.wait_until(self.genesis, self._stop):
                    return
                continue
            r = current_round(now, self.period, self.genesis)
            if last_fired >= r:
                # current round already fired; wait for the next boundary.
                # A (fake) clock jumping several periods fires only the then-
                # current round — missed rounds are the catchup path's job.
                if not self.clock.wait_until(
                        time_of_round(self.period, self.genesis, last_fired + 1),
                        self._stop):
                    return
                continue
            t = time_of_round(self.period, self.genesis, r)
            tick = Tick(round=r, time=t)
            last_fired = r
            with self._lock:
                subs = list(self._subs)
            for q, start_at in subs:
                if t >= start_at:
                    q.put(tick)
