"""Handel aggregation overlay: committee-scale partial aggregation
(arXiv:1906.05132; ISSUE 13, ROADMAP item 3).

The flat fan-out (`core/beacon_process._broadcast_partial`) is all-to-all:
n² messages per round and one giant verification set at the aggregator.
Handel replaces it above a committee-size threshold with a binomial-tree
overlay — node i's *level l* partners are the ids whose bit (l-1) differs
from i's (the mirror block of size 2^(l-1)) — so each node exchanges
*candidate aggregates* per level and the full aggregate emerges in
O(log n) hops.

Adaptation to threshold BLS: Handel's multisigs add; tBLS partials are
combined by Lagrange interpolation over the FINAL signer set, so an
"aggregate" here is the partial *set* itself (bitmap + partial sigs) and
merging is set union.  Verification cost is therefore per-partial, which
is exactly the shape the batched device verifier is built for:

  * **windowed verification** — each tick, the best-scored pending
    candidates (up to `window`) contribute their unseen partials to ONE
    `verifier.verify(msg, partials)` call.  In the daemon that verifier
    is the verify service's `_PartialLaneVerifier` (`submit_call` on the
    LIVE lane), so every level's scored window coalesces into the same
    RLC device dispatch that flat aggregation uses — candidates ride one
    dispatch, never one check each.
  * **scoring-driven peer selection** — send targets are ranked by the
    `net/resilience.py` score snapshot (the breaker/rank state the sync
    and fan-out planes already maintain — READ-ONLY here; transport
    failures feed it in the client, where they are actually observed)
    plus local demotion state, with one rotating exploration slot per
    level so every non-demoted peer is eventually polled.
  * **Byzantine tolerance** — a candidate carrying an invalid partial,
    out-of-block signers, or an oversized set *demotes* the contributor
    SESSION-LOCALLY (sender_index is self-declared on the wire, so
    content offences are never attributed into the shared transport
    registry — a spoofed packet must not be able to open an honest
    peer's breaker); its valid partials are still adopted and the level
    never wedges.  After `bad_limit` offences the peer stops being
    polled entirely — Handel's "stop paying for unresponsive peers".
    A claimed sender OUTSIDE the level's block is dropped with no
    penalty at all: that is the one violation an attacker can aim at an
    arbitrary victim.

`HandelSession` is a pure lock-guarded state machine (receive()/tick());
`HandelCoordinator` is the daemon wrapper: per-round sessions, a tick
thread on the injected clock, wire codec, and delivery of the verified
set back to the aggregation plane (`ChainStore.aggregate_verified` — the
partials arrive pre-verified, so the aggregator recovers without
re-checking; verdicts are keyed by exact partial bytes, bit-identical to
the flat path's).
"""

import os
import threading

from ..common import make_lock
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ..crypto.tbls import index_of
from ..log import Logger
from .clock import Clock, RealClock

# knobs (COMPONENTS.md "Committee-scale engine"; Config.handel_* pins)
DEFAULT_MIN_GROUP = int(os.environ.get("DRAND_HANDEL_MIN_GROUP", "129"))
DEFAULT_FANOUT = int(os.environ.get("DRAND_HANDEL_FANOUT", "4"))
DEFAULT_WINDOW = int(os.environ.get("DRAND_HANDEL_WINDOW", "16"))
DEFAULT_BAD_LIMIT = int(os.environ.get("DRAND_HANDEL_BAD_LIMIT", "3"))
DEFAULT_LEVEL_TICKS = int(os.environ.get("DRAND_HANDEL_LEVEL_TICKS", "4"))
DEFAULT_SESSION_CAP = 8         # concurrent per-round sessions kept


# ---------------------------------------------------------------------------
# tree layout
# ---------------------------------------------------------------------------

def num_levels(n: int) -> int:
    """Height of the binomial tree over n ids (1 level for n=2)."""
    return (n - 1).bit_length() if n > 1 else 0


def level_block(n: int, me: int, level: int) -> List[int]:
    """The mirror block node `me` exchanges with at `level`: ids agreeing
    with me above bit (level-1), differing at it — size 2^(level-1),
    clipped to the committee."""
    size = 1 << (level - 1)
    base = (me ^ size) & ~(size - 1)
    return [i for i in range(base, base + size) if i < n]


def own_block(n: int, me: int, level: int) -> List[int]:
    """The ids my own candidate for `level` may cover (my side of the
    split: the size-2^(level-1) block containing me)."""
    size = 1 << (level - 1)
    base = me & ~(size - 1)
    return [i for i in range(base, base + size) if i < n]


# ---------------------------------------------------------------------------
# aggregates
# ---------------------------------------------------------------------------

class Aggregate:
    """One candidate: a set of tBLS partials keyed by signer index."""

    __slots__ = ("partials",)

    def __init__(self, partials: Optional[Dict[int, bytes]] = None):
        self.partials: Dict[int, bytes] = dict(partials or {})

    @property
    def weight(self) -> int:
        return len(self.partials)

    def indices(self):
        return self.partials.keys()

    def bitmask(self, n: int) -> bytes:
        """Little-endian signer bitmap (the cheap wire summary)."""
        mask = 0
        for i in self.partials:
            mask |= 1 << i
        return mask.to_bytes((n + 7) // 8, "little")

    @classmethod
    def from_partials(cls, partials) -> "Aggregate":
        out = {}
        for p in partials:
            if len(p) < 2:
                continue
            out.setdefault(index_of(p), p)
        return cls(out)


# ---------------------------------------------------------------------------
# the per-round state machine
# ---------------------------------------------------------------------------

class HandelConfig:
    def __init__(self, min_group: int = 0, fanout: int = 0, window: int = 0,
                 bad_limit: int = 0, level_ticks: int = 0,
                 tick: float = 0.0, session_cap: int = 0):
        self.min_group = min_group or DEFAULT_MIN_GROUP
        self.fanout = fanout or DEFAULT_FANOUT
        self.window = window or DEFAULT_WINDOW
        self.bad_limit = bad_limit or DEFAULT_BAD_LIMIT
        self.level_ticks = level_ticks or DEFAULT_LEVEL_TICKS
        self.tick = tick            # 0 = derive from the beacon period
        self.session_cap = session_cap or DEFAULT_SESSION_CAP

    def level_budget(self, n: int) -> int:
        """Ticks a healthy committee gets to complete every level (the
        chaos scenario's convergence bar)."""
        return max(1, num_levels(n)) * self.level_ticks


class HandelSession:
    """One node's aggregation state for one (round, prev_sig).

    Deterministic: all progress happens inside `receive()` (ingress) and
    `tick()` (the verification window + the scored send pass), so a
    FakeClock harness can single-step a thousand-signer committee."""

    def __init__(self, cfg: HandelConfig, n: int, me: int, threshold: int,
                 round_: int, prev_sig: Optional[bytes], msg: bytes,
                 verifier, send: Callable[[int, int, Aggregate], None],
                 scorer=None, score_key: Optional[Callable[[int], str]] = None,
                 on_complete: Optional[Callable[[Dict[int, bytes]], None]]
                 = None,
                 on_demote: Optional[Callable[[int], None]] = None):
        self.cfg = cfg
        self.n = n
        self.me = me
        self.threshold = threshold
        self.round = round_
        self.prev_sig = prev_sig
        self.msg = msg
        self.verifier = verifier
        self.send = send
        self.scorer = scorer                 # BreakerRegistry (or None)
        self.score_key = score_key or (lambda idx: f"handel-{idx}")
        self.on_complete = on_complete
        self.on_demote = on_demote
        self.levels = num_levels(n)
        self._lock = make_lock()
        self.verified: Dict[int, bytes] = {}     # signer -> good partial
        self.checked: Dict[bytes, bool] = {}     # exact bytes -> verdict
        # latest candidate per (level, sender): equivocation costs a
        # Byzantine sender its own slot, never extra memory
        self._pending: Dict[Tuple[int, int], Aggregate] = {}
        self._bad: Dict[int, int] = {}
        self._rotate: Dict[int, int] = {}
        # (tick, peer) audit log for the demotion assertions — BOUNDED:
        # a session for a stuck round (halted chain) keeps ticking until
        # flush, and an append-only log would grow for the outage's whole
        # duration in exactly the degraded state that must stay stable
        self._sends: deque = deque(maxlen=4096)
        self._ticks = 0
        self.complete = False
        self.completed_at: Optional[int] = None
        self.own_seeded = False     # add_own ran: this is OUR live round

    # -- ingress -------------------------------------------------------------

    def add_own(self, partial: bytes) -> None:
        """Our own partial enters like any contribution (it is verified in
        the next window — verdict parity with the flat path, which also
        batch-checks its own partial at aggregation time)."""
        with self._lock:
            self._pending[(0, self.me)] = Aggregate({self.me: partial})
            self.own_seeded = True

    def receive(self, level: int, sender: int, agg: Aggregate) -> bool:
        """One candidate from `sender` for our `level`.  Cheap structural
        checks here; cryptographic verification waits for the window.
        Returns False when the candidate was rejected outright.

        A sender OUTSIDE the level's mirror block is dropped with no
        penalty at all: `sender_index` is self-declared on the wire, so
        a single forged packet could otherwise demote any honest peer of
        the attacker's choosing (the one violation an attacker can aim
        at an arbitrary victim).  In-block offences still demote — the
        spoof there is confined to ids the level would accept anyway."""
        if not (1 <= level <= self.levels) or not (0 <= sender < self.n) \
                or sender == self.me:
            return False
        block = set(level_block(self.n, self.me, level))
        if sender not in block:
            return False
        with self._lock:
            if self._bad.get(sender, 0) >= self.cfg.bad_limit:
                return False        # demoted: stop paying for this peer
            structurally_ok = (0 < agg.weight <= len(block)
                               and set(agg.indices()) <= block)
        if not structurally_ok:
            self._note_bad(sender)
            return False
        with self._lock:
            self._pending[(level, sender)] = agg
        return True

    # -- scoring -------------------------------------------------------------

    def _peer_score(self, idx: int) -> float:
        """READ-ONLY view of the shared breaker/rank state
        (net/resilience.py score_snapshot): the overlay ranks by the
        transport evidence the client and sync planes already maintain.
        Deliberately never WRITTEN from candidate content — sender_index
        is self-declared, so a content offence attributed into the
        shared registry would let a spoofed packet open an honest peer's
        transport breaker (cutting its partial/sync traffic mesh-wide).
        Content offences stay session-local (`_bad`/demotion); transport
        failures feed the registry where they are observed — in the
        CLIENT, per real dial."""
        if self.scorer is None:
            return 0.0
        return self.scorer.score(self.score_key(idx))

    def _note_bad(self, idx: int) -> None:
        """One more session-local offence; fires the demotion hook on
        the crossing."""
        with self._lock:
            before = self._bad.get(idx, 0)
            self._bad[idx] = before + 1
            crossed = before < self.cfg.bad_limit <= before + 1
        if crossed and self.on_demote is not None:
            self.on_demote(idx)

    def demoted(self) -> List[int]:
        with self._lock:
            return sorted(i for i, c in self._bad.items()
                          if c >= self.cfg.bad_limit)

    # -- the tick ------------------------------------------------------------

    def tick(self) -> None:
        self._verify_window()
        self._maybe_complete()
        self._send_pass()
        with self._lock:
            self._ticks += 1

    def _verify_window(self) -> None:
        """Scored window: the best pending candidates contribute their
        unseen partials to ONE batched verify call."""
        with self._lock:
            pending = list(self._pending.items())
            known = dict(self.checked)
        if not pending:
            return

        def novelty(item):
            (_, _), agg = item
            return sum(1 for i, p in agg.partials.items()
                       if i not in self.verified and p not in known)

        # most new information first, peer reliability as the tiebreak
        pending.sort(key=lambda it: (novelty(it),
                                     self._peer_score(it[0][1])),
                     reverse=True)
        window = pending[:self.cfg.window]
        to_check: List[bytes] = []
        seen = set()
        for (_, _), agg in window:
            for p in agg.partials.values():
                if p not in known and p not in seen:
                    seen.add(p)
                    to_check.append(p)
        if to_check:
            # ONE call for the whole window — in the daemon this is the
            # verify service's LIVE lane (submit_call), so candidates
            # coalesce into a single RLC dispatch
            verdicts = self.verifier.verify(self.msg, to_check)
            with self._lock:
                for p, ok in zip(to_check, verdicts):
                    self.checked[p] = bool(ok)
        offenders = set()
        with self._lock:
            for (level, sender), agg in window:
                # consume the slot only if it still holds the snapshotted
                # candidate: a FRESHER one that receive() stored while the
                # (blocking) verify call ran must wait for its own window,
                # not be silently discarded unverified
                if self._pending.get((level, sender)) is agg:
                    self._pending.pop((level, sender), None)
                any_bad = False
                for i, p in agg.partials.items():
                    if self.checked.get(p):
                        self.verified.setdefault(i, p)
                    elif self.checked.get(p) is False:
                        any_bad = True
                if any_bad and sender != self.me:
                    offenders.add(sender)
        for s in offenders:
            self._note_bad(s)

    def _maybe_complete(self) -> None:
        fire = False
        with self._lock:
            if not self.complete and len(self.verified) >= self.threshold:
                self.complete = True
                self.completed_at = self._ticks
                fire = True
            snapshot = dict(self.verified)
        if fire and self.on_complete is not None:
            self.on_complete(snapshot)

    def _send_pass(self) -> None:
        """Fast-start Handel: every level is live from tick 0; per level,
        up to `fanout` targets ranked by score (demoted peers are never
        polled), rotated each tick so the block is eventually covered."""
        for level in range(1, self.levels + 1):
            payload = self._payload(level)
            if payload.weight == 0:
                continue
            targets = self._targets(level)
            for peer in targets:
                with self._lock:
                    self._sends.append((self._ticks, peer))
                self.send(peer, level, payload)

    def _payload(self, level: int) -> Aggregate:
        mine = set(own_block(self.n, self.me, level))
        with self._lock:
            out = {i: p for i, p in self.verified.items() if i in mine}
            own = self._pending.get((0, self.me))
        if own is not None and self.me in mine:
            # our own partial travels before its first window verdict —
            # receivers verify it like anything else
            out.setdefault(self.me, own.partials[self.me])
        return Aggregate(out)

    def _targets(self, level: int) -> List[int]:
        with self._lock:
            bad = {i for i, c in self._bad.items()
                   if c >= self.cfg.bad_limit}
            rot = self._rotate.get(level, 0)
            self._rotate[level] = rot + 1
        block = [i for i in level_block(self.n, self.me, level)
                 if i not in bad]
        if not block:
            return []
        # top scorers lead, but the LAST fanout slot rotates through the
        # remainder — once scores diverge a pure score sort would pin the
        # same winners forever and never cover the rest of the block
        # (the reachable-but-never-contacted tail); the exploration slot
        # guarantees every non-demoted peer is eventually polled
        ranked = sorted(block, key=self._peer_score, reverse=True)
        if len(ranked) <= self.cfg.fanout:
            return ranked
        head = ranked[:self.cfg.fanout - 1]
        rest = ranked[self.cfg.fanout - 1:]
        return head + [rest[rot % len(rest)]]

    # -- introspection ---------------------------------------------------------

    def sends_to(self, peer: int) -> List[int]:
        """Ticks at which we sent to `peer` (chaos assertions: a demoted
        peer stops appearing here)."""
        with self._lock:
            return [t for t, p in self._sends if p == peer]

    def stats(self) -> dict:
        with self._lock:
            return {"round": self.round, "verified": len(self.verified),
                    "threshold": self.threshold, "complete": self.complete,
                    "completed_at_tick": self.completed_at,
                    "ticks": self._ticks, "pending": len(self._pending),
                    "demoted": sorted(
                        i for i, c in self._bad.items()
                        if c >= self.cfg.bad_limit)}


# ---------------------------------------------------------------------------
# wire codec
# ---------------------------------------------------------------------------

def to_packet(round_: int, prev_sig: Optional[bytes], level: int,
              sender_index: int, agg: Aggregate, n: int, beacon_id: str):
    from ..net import convert
    from ..protos import drand_pb2 as pb
    return pb.HandelAggregatePacket(
        round=round_, previous_signature=prev_sig or b"", level=level,
        bitmask=agg.bitmask(n),
        partial_sigs=list(agg.partials.values()),
        sender_index=sender_index,
        metadata=convert.metadata(beacon_id))


def from_packet(pkt) -> Tuple[int, Optional[bytes], int, int, Aggregate]:
    """-> (round, prev_sig, level, sender_index, Aggregate).  The bitmap
    is advisory (weight preview); the partial bytes are authoritative."""
    agg = Aggregate.from_partials(list(pkt.partial_sigs))
    return (pkt.round, pkt.previous_signature or None, pkt.level,
            pkt.sender_index, agg)


def peer_host(addr: str) -> str:
    """Host component of either a gRPC transport peer string
    ('ipv4:10.0.0.1:52644', 'ipv6:[::1]:52644') or a drand node address
    ('10.0.0.1:8080', 'node-a:443', '[::1]:8080').  The sender-binding
    check compares hosts: the client connects from an ephemeral port, so
    the port component carries no identity."""
    a = addr
    if a.startswith(("ipv4:", "ipv6:")):
        a = a.split(":", 1)[1]
    if a.startswith("[") and "]" in a:      # bracketed ipv6 literal
        return a[:a.index("]") + 1]
    return a.rsplit(":", 1)[0] if ":" in a else a


def _ip_literal(host: str) -> bool:
    """True iff `host` is an IPv4/IPv6 literal (brackets tolerated)."""
    import ipaddress
    try:
        ipaddress.ip_address(host.strip("[]"))
        return True
    except ValueError:
        return False


def sender_binding_enforceable(claimed_addr: str) -> bool:
    """The binding check compares the ROSTER address host against the
    transport peer host — but gRPC's `context.peer()` is always a
    numeric IP, so a roster registered under DNS names (the common
    production form) would fail the comparison for every honest packet.
    Enforce only when the roster host is itself an IP literal; DNS-named
    rosters (and NAT'd deployments) keep the pre-binding trust model and
    should bind identity with mTLS instead (the COMPONENTS.md note)."""
    return _ip_literal(peer_host(claimed_addr))


class ChainVerifier:
    """Late-bound view of a ChainStore's partial verifier: a reshare
    transition swaps `chain.partial_verifier` for the new group's, and
    the overlay must follow the swap instead of pinning the old one."""

    def __init__(self, chain):
        self._chain = chain

    def verify(self, msg: bytes, partials):
        return self._chain.partial_verifier.verify(msg, partials)


# ---------------------------------------------------------------------------
# the daemon coordinator
# ---------------------------------------------------------------------------

class HandelCoordinator:
    """Per-chain overlay driver: owns the per-round sessions, the tick
    thread on the injected clock, and the transport/aggregation glue.

    `transport(node_index, pb_packet)` delivers one wire packet (the
    daemon binds it to `ProtocolClient.handel_aggregate`; tests to a
    loopback).  `on_complete(round, prev_sig, partials)` hands the
    verified set to the aggregation plane."""

    def __init__(self, group_n: int, me: int, threshold: int, scheme,
                 verifier, transport: Callable[[int, object], None],
                 on_complete: Callable[[int, Optional[bytes],
                                        Dict[int, bytes]], None],
                 clock: Optional[Clock] = None, scorer=None,
                 score_key: Optional[Callable[[int], str]] = None,
                 cfg: Optional[HandelConfig] = None, period: float = 30.0,
                 beacon_id: str = "default",
                 log: Optional[Logger] = None):
        self.n = group_n
        self.me = me
        self.threshold = threshold
        self.scheme = scheme
        self.verifier = verifier
        self.transport = transport
        self.on_complete = on_complete
        self.clock = clock or RealClock()
        self.scorer = scorer
        self.score_key = score_key
        self.cfg = cfg or HandelConfig()
        self.beacon_id = beacon_id
        self.log = (log or Logger()).named(f"handel-{beacon_id}")
        # tick cadence: a handful of hops must fit well inside one round
        self.tick_s = self.cfg.tick or max(0.05, min(1.0, period / 20.0))
        self._sessions: Dict[Tuple[int, bytes], HandelSession] = {}
        self._flushed = 0
        self._lock = make_lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._completed = 0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"handel-{self.beacon_id}")
            self._thread.start()

    def stop(self) -> None:
        # shutdown promptness is governed by the _stop event alone: the
        # run loop parks in clock.wait_until(..., self._stop)
        self._stop.set()
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.is_set():
            if not self.clock.wait_until(self.clock.now() + self.tick_s,
                                         self._stop):
                return
            try:
                self.tick()
            except Exception as e:      # a bad candidate must never stop
                self.log.warn("handel tick failed", err=str(e))

    # -- session plumbing ----------------------------------------------------

    def _key(self, round_: int, prev_sig: Optional[bytes]):
        return (round_, prev_sig or b"")

    def _session(self, round_: int, prev_sig: Optional[bytes]
                 ) -> Optional[HandelSession]:
        from ..metrics import (handel_active_sessions, handel_demotions,
                               handel_sessions)
        key = self._key(round_, prev_sig)
        with self._lock:
            if round_ <= self._flushed:
                return None
            sess = self._sessions.get(key)
        if sess is not None:
            return sess
        # build outside the coordinator lock (digest + closure wiring);
        # the insert below re-checks under the lock, losers are discarded
        msg = self.scheme.digest_beacon(
            round_, prev_sig if self.scheme.chained else None)
        fresh = HandelSession(
            self.cfg, self.n, self.me, self.threshold, round_,
            prev_sig, msg, self.verifier,
            send=self._make_sender(round_, prev_sig),
            scorer=self.scorer, score_key=self.score_key,
            on_complete=self._make_completer(round_, prev_sig),
            on_demote=lambda idx: handel_demotions.labels(
                self.beacon_id).inc())
        flushed_evictions = 0
        with self._lock:
            if round_ <= self._flushed:
                return None
            sess = self._sessions.get(key)
            if sess is None:
                if len(self._sessions) >= self.cfg.session_cap:
                    # Bound memory WITHOUT sacrificing live aggregation:
                    # prefer evicting a session we never seeded with our
                    # own partial — those only exist because of ingress
                    # (e.g. a flood of bogus prev_sig variants for the
                    # current round, which would otherwise churn out the
                    # REAL session's verified state); among candidates,
                    # the oldest round goes (likeliest already served by
                    # catch-up sync).  If every session is own-seeded,
                    # evict the oldest of those.
                    unseeded = [k for k, s in self._sessions.items()
                                if not s.own_seeded]
                    victim = min(unseeded) if unseeded \
                        else min(self._sessions)
                    self._sessions.pop(victim, None)
                    flushed_evictions += 1
                sess = self._sessions[key] = fresh
            n_active = len(self._sessions)
        handel_active_sessions.labels(self.beacon_id).set(n_active)
        if flushed_evictions:
            handel_sessions.labels(self.beacon_id, "flushed").inc(
                flushed_evictions)
        return sess

    def _make_sender(self, round_: int, prev_sig: Optional[bytes]):
        def send(peer: int, level: int, agg: Aggregate):
            from ..metrics import handel_sends
            pkt = to_packet(round_, prev_sig, level, self.me, agg,
                            self.n, self.beacon_id)
            handel_sends.labels(self.beacon_id).inc()
            try:
                self.transport(peer, pkt)
            except Exception:
                # transport failures feed the breaker through the shared
                # registry (the client's policy does it per peer); the
                # overlay itself just moves on to the next target
                pass
        return send

    def _make_completer(self, round_: int, prev_sig: Optional[bytes]):
        def complete(partials: Dict[int, bytes]):
            from ..metrics import handel_sessions
            with self._lock:
                self._completed += 1
            handel_sessions.labels(self.beacon_id, "complete").inc()
            self.on_complete(round_, prev_sig, partials)
        return complete

    # -- ingress/egress ------------------------------------------------------

    def submit_own(self, round_: int, prev_sig: Optional[bytes],
                   partial: bytes) -> None:
        """Our partial for a round: seeds the session and runs an
        immediate SEND pass so level sends leave this round-trip, not a
        tick later.  Verification deliberately waits for the next tick's
        window — our lone partial must not burn a one-lane dispatch on
        the handler thread when the window will batch it with incoming
        candidates anyway."""
        sess = self._session(round_, prev_sig)
        if sess is None:
            return
        sess.add_own(partial)
        sess._send_pass()

    def receive(self, pkt, peer: Optional[str] = None, auth=None) -> None:
        """One wire candidate (daemon ingress).  Raises ValueError on
        protocol violations (mapped to INVALID_ARGUMENT upstream).

        `peer` is the TRANSPORT-level sender (gRPC `context.peer()`):
        when given, the claimed `sender_index` must map — via the group
        roster the coordinator was built with — to the same host the
        packet physically arrived from (ROADMAP 3d).  Without this,
        sender_index is pure self-declaration: any member could claim a
        victim's index on forged candidates and farm the victim's
        session-local score demotion (the one per-peer state content
        offences feed).  Host-granular by design — the client dials from
        an ephemeral port.

        `auth` (net/identity.py PeerIdentity) is the mTLS-authenticated
        sender: when present it REPLACES the IP heuristic — the roster
        host of the claimed index must appear in the sender cert's SAN
        set, which holds for DNS-named rosters too (the PR 15
        `sender_binding_enforceable` carve-out, now enforced; ISSUE 19).
        Either way a mismatch is rejected at ingress, metered, and never
        reaches the session — the honest owner of the claimed index is
        not demoted by someone else's forgery."""
        from ..metrics import handel_candidates
        round_, prev_sig, level, sender, agg = from_packet(pkt)
        if not (0 <= sender < self.n):
            raise ValueError(f"handel sender index {sender} out of range")
        if auth is not None and self.score_key is not None:
            claimed = self.score_key(sender)
            if not auth.matches(peer_host(claimed)):
                from ..metrics import identity_rejections
                handel_candidates.labels(self.beacon_id,
                                         "impersonation").inc()
                identity_rejections.labels("handel", "impersonation").inc()
                raise ValueError(
                    f"handel sender index {sender} is registered at "
                    f"{claimed}, but the packet was authenticated as "
                    f"{auth.label}")
        elif peer is not None and self.score_key is not None:
            claimed = self.score_key(sender)
            # enforce only for IP-literal rosters: the transport peer is
            # always numeric, so a DNS-named roster entry can never
            # match and enforcing would reject every honest packet
            # (sender_binding_enforceable; DNS rosters bind with mTLS
            # via `auth` above)
            if sender_binding_enforceable(claimed) \
                    and peer_host(claimed) != peer_host(peer):
                handel_candidates.labels(self.beacon_id,
                                         "impersonation").inc()
                raise ValueError(
                    f"handel sender index {sender} is registered at "
                    f"{claimed}, but the packet arrived from {peer}")
        sess = self._session(round_, prev_sig)
        if sess is None:
            return                      # stale round: already aggregated
        ok = sess.receive(level, sender, agg)
        handel_candidates.labels(
            self.beacon_id, "accepted" if ok else "rejected").inc()

    def tick(self) -> None:
        with self._lock:
            sessions = list(self._sessions.values())
        for sess in sessions:
            sess.tick()

    def flush(self, upto: int) -> None:
        """Retire sessions for stored rounds (mirror of the partial
        cache's flush_rounds)."""
        from ..metrics import handel_active_sessions
        with self._lock:
            self._flushed = max(self._flushed, upto)
            for key in [k for k in self._sessions if k[0] <= upto]:
                del self._sessions[key]
            handel_active_sessions.labels(self.beacon_id).set(
                len(self._sessions))

    # -- introspection -------------------------------------------------------

    def summary(self) -> dict:
        with self._lock:
            sessions = {str(k[0]): s.stats()
                        for k, s in sorted(self._sessions.items())}
            return {"n": self.n, "levels": num_levels(self.n),
                    "threshold": self.threshold,
                    "tick_s": self.tick_s,
                    "active_sessions": len(sessions),
                    "completed": self._completed,
                    "sessions": sessions}
