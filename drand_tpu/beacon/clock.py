"""Injectable clocks (the clockwork pattern the reference tests lean on,
core/util_test.go:43-78): the engine never calls time.time() directly, so
tests can step time deterministically."""

import threading

from ..common import make_condition
import time
from abc import ABC, abstractmethod


class Clock(ABC):
    @abstractmethod
    def now(self) -> float: ...

    def monotonic(self) -> float:
        """Elapsed-time source for measuring DURATIONS (queue age,
        timeouts) as opposed to reading the schedule.  Defaults to now()
        — fake clocks only move forward, so their one timeline serves
        both — but RealClock overrides it with time.monotonic() so an
        NTP step or VM suspend/resume can't corrupt a duration."""
        return self.now()

    @abstractmethod
    def wait_until(self, deadline: float, stop: threading.Event) -> bool:
        """Block until now() >= deadline or `stop` is set.  Returns True if
        the deadline was reached (False = stopped)."""


class RealClock(Clock):
    def now(self) -> float:
        return time.time()

    def monotonic(self) -> float:
        return time.monotonic()

    def wait_until(self, deadline: float, stop: threading.Event) -> bool:
        while not stop.is_set():
            delta = deadline - self.now()
            if delta <= 0:
                return True
            stop.wait(min(delta, 0.5))
        return False


class FakeClock(Clock):
    """Manually advanced clock; all waiters share one condition variable."""

    def __init__(self, start: float = 0.0):
        self._now = start
        self._cond = make_condition()

    def now(self) -> float:
        with self._cond:
            return self._now

    def set_time(self, t: float) -> None:
        with self._cond:
            if t < self._now:
                raise ValueError("fake clock cannot go backwards")
            self._now = t
            self._cond.notify_all()

    def advance(self, dt: float) -> None:
        with self._cond:
            self._now += dt
            self._cond.notify_all()

    def wait_until(self, deadline: float, stop: threading.Event) -> bool:
        with self._cond:
            while self._now < deadline:
                if stop.is_set():
                    return False
                # Poll stop with a real-time bound so shutdown can't hang a
                # waiter whose fake deadline never arrives.
                self._cond.wait(0.05)
            return not stop.is_set() or self._now >= deadline
