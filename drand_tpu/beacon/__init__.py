"""Beacon protocol engine (reference chain/beacon/, SURVEY.md §2.5):
ticker, partial cache, aggregator, store decorators, round-loop handler,
sync manager."""

from .clock import Clock, FakeClock, RealClock
from .ticker import Ticker
from .cache import PartialCache
from .chainstore import ChainStore
from .node import Handler, HandlerConfig

__all__ = ["Clock", "RealClock", "FakeClock", "Ticker", "PartialCache",
           "ChainStore", "Handler", "HandlerConfig"]
