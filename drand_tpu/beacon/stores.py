"""Store decorator chain (chain/beacon/store.go:35-279).

Assembled bottom-up as
    backend -> discrepancy (timing metrics) -> scheme (linkage rules)
            -> append (strict monotonic rounds) -> callback (subscribers)
exactly like chainstore.go:43-75.  Each decorator is itself a chain.Store.
"""

import queue
import threading

from ..common import make_lock
from typing import Callable, Dict, Optional

from ..chain.beacon import Beacon
from ..chain.store import Cursor, Store
from ..chain.timing import time_of_round
from ..chain.errors import ErrNoBeaconStored
from .clock import Clock


class ErrBeaconAlreadyStored(Exception):
    """Duplicate round put (store.go:53); racing writers treat it as benign."""


class _Decorator(Store):
    def __init__(self, inner: Store):
        self.inner = inner

    @property
    def DURABILITY(self):  # noqa: N802 — contract attribute (chain/store.py)
        """Decorators add semantics, not persistence: durability is
        whatever the wrapped backend provides."""
        return self.inner.DURABILITY

    def __len__(self):
        return len(self.inner)

    def put(self, beacon: Beacon) -> None:
        self.inner.put(beacon)

    def last(self) -> Beacon:
        return self.inner.last()

    def get(self, round_: int) -> Beacon:
        return self.inner.get(round_)

    def cursor(self) -> Cursor:
        return self.inner.cursor()

    def close(self) -> None:
        self.inner.close()

    def delete(self, round_: int) -> None:
        self.inner.delete(round_)

    # two-phase quarantine (chain/store.py contract): delegate so the
    # side table lives with the BACKEND, not per decorator layer
    def tombstone(self, round_: int) -> bool:
        return self.inner.tombstone(round_)

    def tombstoned(self, round_: int):
        return self.inner.tombstoned(round_)

    def drop_tombstone(self, round_: int) -> None:
        self.inner.drop_tombstone(round_)

    def save_to(self, fileobj) -> None:
        self.inner.save_to(fileobj)


class AppendStore(_Decorator):
    """Strict `round == last+1` appends; duplicates raise
    ErrBeaconAlreadyStored (store.go:35-77)."""

    def __init__(self, inner: Store):
        super().__init__(inner)
        self._lock = make_lock()
        try:
            self._last: Optional[Beacon] = inner.last()
        except ErrNoBeaconStored:
            self._last = None

    def put(self, beacon: Beacon) -> None:
        with self._lock:
            last = self._last
            if last is not None:
                if beacon.round <= last.round:
                    raise ErrBeaconAlreadyStored(
                        f"round {beacon.round} already stored (last {last.round})")
                if beacon.round != last.round + 1:
                    raise ValueError(
                        f"invalid round inserted: last {last.round}, new {beacon.round}")
            elif beacon.round != 0 and len(self.inner) > 0:
                raise ValueError("store not empty but last unknown")
            self.inner.put(beacon)
            self._last = beacon

    def delete(self, round_: int) -> None:
        """Deleting (e.g. a rolled-back head) must invalidate the cached
        last beacon or the round stays unwritable forever."""
        with self._lock:
            self.inner.delete(round_)
            try:
                self._last = self.inner.last()
            except ErrNoBeaconStored:
                self._last = None


class SchemeStore(_Decorator):
    """Linkage rules by scheme (store.go:80-124): chained beacons must carry
    previous_sig == last.signature; unchained beacons store no previous_sig."""

    def __init__(self, inner: Store, chained: bool):
        super().__init__(inner)
        self.chained = chained
        self._lock = make_lock()

    def put(self, beacon: Beacon) -> None:
        with self._lock:
            if self.chained:
                try:
                    last = self.inner.last()
                except ErrNoBeaconStored:
                    last = None
                if last is not None and beacon.round == last.round + 1 \
                        and beacon.previous_sig != last.signature:
                    raise ValueError(
                        f"invalid previous signature for round {beacon.round}")
            elif beacon.previous_sig is not None:
                beacon = Beacon(round=beacon.round, signature=beacon.signature)
            self.inner.put(beacon)


class DiscrepancyStore(_Decorator):
    """Records wall-clock discrepancy vs the expected round time
    (store.go:127-173; feeds beacon_discrepancy_latency)."""

    def __init__(self, inner: Store, clock: Clock, period: int, genesis: int,
                 on_discrepancy: Optional[Callable[[int, float], None]] = None):
        super().__init__(inner)
        self.clock = clock
        self.period = period
        self.genesis = genesis
        self.on_discrepancy = on_discrepancy
        self.last_discrepancy_ms: Optional[float] = None

    def put(self, beacon: Beacon) -> None:
        self.inner.put(beacon)
        expected = time_of_round(self.period, self.genesis, beacon.round)
        disc_ms = (self.clock.now() - expected) * 1000.0
        self.last_discrepancy_ms = disc_ms
        if self.on_discrepancy is not None:
            self.on_discrepancy(beacon.round, disc_ms)


class CallbackStore(_Decorator):
    """Fan-out of stored beacons to named subscribers, each served by its own
    worker thread with a bounded queue (store.go:176-279) — a slow consumer
    (HTTP watcher, sync stream) cannot stall the aggregator."""

    QUEUE_SIZE = 100

    def __init__(self, inner: Store):
        super().__init__(inner)
        self._lock = make_lock()
        self._subs: Dict[str, queue.Queue] = {}
        self._threads: Dict[str, threading.Thread] = {}

    def put(self, beacon: Beacon) -> None:
        self.inner.put(beacon)
        with self._lock:
            qs = list(self._subs.values())
        for q in qs:
            try:
                q.put_nowait(beacon)
            except queue.Full:
                pass  # slow subscriber drops ticks; sync repairs later

    def add_callback(self, id_: str, fn: Callable[[Beacon], None]) -> None:
        """Replaces any existing subscriber with the same id
        (sync_manager.go:542-560 re-request behavior)."""
        self.remove_callback(id_)
        q: queue.Queue = queue.Queue(maxsize=self.QUEUE_SIZE)

        def worker():
            while True:
                b = q.get()
                if b is None:
                    return
                try:
                    fn(b)
                except Exception:
                    pass

        t = threading.Thread(target=worker, daemon=True,
                             name=f"callback-{id_}")
        with self._lock:
            self._subs[id_] = q
            self._threads[id_] = t
        t.start()

    def remove_callback(self, id_: str) -> None:
        with self._lock:
            q = self._subs.pop(id_, None)
            t = self._threads.pop(id_, None)
        if q is not None:
            q.put(None)
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2)

    def close(self) -> None:
        with self._lock:
            ids = list(self._subs)
        for id_ in ids:
            self.remove_callback(id_)
        self.inner.close()
