"""SyncManager + SyncChain server (chain/beacon/sync_manager.go:28-590).

The TPU-first redesign of the reference's sync path: where the Go code
verifies each streamed beacon with one CPU pairing (sync_manager.go:406 —
the designated batch hook per SURVEY.md §2.5), beacons here are buffered
into chunks and verified in ONE device RLC pass per chunk through
`BatchBeaconVerifier`, with the chained-linkage check done as the cheap
host-side prefix pass.

Components:
  * `SyncManager.run` — serializes sync requests (queue 3), restarts a sync
    idle for > 2·period (sync_manager.go:52-53,154-162), shuffles peers for
    failover (sync_manager.go:302).
  * `check_past_beacons` / `correct_past_beacons` — full-chain validation
    and repair (sync_manager.go:170-268); repair writes through the RAW
    store, bypassing the append decorator (the "insecure store" ReSync path,
    sync_manager.go:411-416).
  * `SyncChainServer` — the serving side of a sync stream: cursor replay
    from `from_round`, then live-follow via a store callback registered
    under the remote address (replaced on re-request, sync_manager.go:542-560).
"""

import queue
import threading
from typing import Callable, Iterable, Iterator, List, Optional, Sequence

from ..chain.beacon import Beacon
from ..chain.errors import ErrNoBeaconStored
from ..net.resilience import (DEFAULT_SYNC_BUDGET, BreakerOpen, Deadline,
                              ResiliencePolicy, peer_key)
from .stores import ErrBeaconAlreadyStored

DEFAULT_CHUNK = 512
SYNC_QUEUE = 3


class ErrFailedAll(Exception):
    """Every candidate peer failed to advance the sync (sync_manager.go:59)."""


class SyncManager:
    """Pulls missing rounds from peers with batched device verification.

    `fetch(peer, from_round)` must return an iterator of Beacons streamed by
    the peer (the net layer's SyncChain client; tests wire SyncChainServer
    generators directly)."""

    def __init__(self, chain, scheme, public_key_bytes: bytes, period: int,
                 clock, fetch: Callable[[object, int], Iterable[Beacon]],
                 peers: Sequence[object] = (), chunk: int = DEFAULT_CHUNK,
                 verifier=None, resilience: Optional[ResiliencePolicy] = None,
                 sync_budget: Optional[float] = None):
        self.chain = chain                  # ChainStore facade (decorators)
        self.scheme = scheme
        self.period = period
        self.clock = clock
        self.fetch = fetch
        self.peers = list(peers)
        self.chunk = chunk
        if verifier is None:                # lazy: keep jax out of host-only
            # all device dispatch goes through the resident verify
            # service (one owner, coalesced batches, priority lanes) —
            # sync/heal work rides the BACKGROUND lane so live-round
            # partial aggregation preempts it at chunk boundaries
            from ..crypto.verify_service import get_service
            verifier = get_service().handle(scheme, public_key_bytes)
        self.verifier = verifier
        # shared policy: the daemon passes the one its ProtocolClient uses,
        # so partial-send failures steer sync peer selection and vice versa
        self.resilience = resilience or ResiliencePolicy(clock=clock,
                                                         scope="sync")
        self.sync_budget = sync_budget or DEFAULT_SYNC_BUDGET
        self._requests: queue.Queue = queue.Queue(maxsize=SYNC_QUEUE)
        self._stop = threading.Event()
        self._last_progress = None
        self._thread: Optional[threading.Thread] = None

    # -- request plane -------------------------------------------------------

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self.run, daemon=True,
                                            name="sync-manager")
            self._thread.start()

    def send_sync_request(self, target_round: int,
                          peers: Optional[Sequence[object]] = None) -> None:
        """Non-blocking enqueue; a full queue drops the request — the next
        gap detection re-issues it (sync_manager.go:121-142)."""
        try:
            self._requests.put_nowait((target_round, list(peers or self.peers)))
        except queue.Full:
            pass

    def run(self) -> None:
        while not self._stop.is_set():
            try:
                target, peers = self._requests.get(timeout=0.1)
            except queue.Empty:
                continue
            # collapse queued requests to the farthest target
            try:
                while True:
                    t2, p2 = self._requests.get_nowait()
                    if t2 > target:
                        target, peers = t2, p2
            except queue.Empty:
                pass
            if target <= self._head_round():
                continue
            try:
                self.sync(target, peers)
            except ErrFailedAll:
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- the sync itself -----------------------------------------------------

    def _head_round(self) -> int:
        head = self._head_beacon()
        return head.round if head is not None else 0

    def _head_beacon(self) -> Optional[Beacon]:
        try:
            return self.chain.last()
        except ErrNoBeaconStored:
            return None   # fresh store (follow-mode bootstrap)

    def sync(self, target_round: int, peers: Sequence[object]) -> None:
        """Stream from peers until the chain reaches target_round, under ONE
        overall budget (`sync_budget`) instead of per-call timeouts.

        Peer order is breaker-aware (closed-breaker peers first, quarantined
        ones last, shuffled within each health bucket for load spreading —
        the Handel-style de-prioritization of unresponsive peers).
        Quarantined peers are skipped while any healthier candidate exists,
        but when EVERY peer is quarantined they are dialed anyway (last
        resort — a healed partition must not idle out a full cooldown); a
        pass that makes no progress backs off with jitter, and
        `ErrFailedAll` is raised only once the budget is spent."""
        peers = list(peers)
        if not peers:
            raise ErrFailedAll("no peers to sync from")
        deadline = Deadline.after(self.clock, self.sync_budget)
        strikes = 0
        while True:
            progressed = False
            # ONE preference snapshot per pass drives both the ranking and
            # the quarantine skip — querying the registry twice would let a
            # cooldown that elapses mid-pass make the two disagree
            prefs = {peer_key(p): self.resilience.breakers.preference(
                peer_key(p)) for p in peers}
            all_quarantined = all(v == 2 for v in prefs.values())
            order = list(peers)
            self.resilience.rng.shuffle(order)
            order.sort(key=lambda p: prefs[peer_key(p)])
            for peer in order:
                if self._stop.is_set():
                    return
                if deadline.expired:
                    raise ErrFailedAll(
                        f"no peer could sync us to round {target_round} "
                        f"within the {self.sync_budget}s budget")
                key = peer_key(peer)
                br = self.resilience.breaker(key)
                if prefs[key] == 2:
                    if not all_quarantined:
                        continue    # quarantined: cooldown not yet elapsed
                    # last resort: every peer is quarantined — admit a
                    # probe NOW (OPEN → HALF_OPEN before the cooldown
                    # elapses), or the production fetch path would raise
                    # BreakerOpen at the client and the dial-anyway promise
                    # above would be dead code
                    br.force_probe()
                before = self._head_round()
                try:
                    reached, aborted = self._try_peer(peer, target_round,
                                                      deadline)
                except BreakerOpen:
                    continue        # client-side rejection, not a failure
                except Exception:
                    br.record_failure()
                    continue
                if self._head_round() > before:
                    progressed = True
                    br.record_success()
                elif not reached and not aborted:
                    # transport was fine but the content didn't advance us
                    # (empty, stale, or Byzantine stream); an `aborted` try
                    # (stop() or budget expiry mid-stream) is OUR exit, not
                    # the peer's fault — no strike
                    br.record_failure()
                if reached:
                    return
            if self._stop.is_set():
                return
            if deadline.expired:
                raise ErrFailedAll(
                    f"no peer could sync us to round {target_round} "
                    f"within the {self.sync_budget}s budget")
            strikes = 0 if progressed else strikes + 1
            # back off before the next pass (full jitter, never past the
            # deadline); a fruitless pass also waits for the earliest
            # breaker probe so a fully-quarantined peer set isn't hot-looped
            delay = max(self.resilience.backoff.delay(strikes,
                                                      self.resilience.rng),
                        0.05)
            wake = min(self.clock.now() + delay, deadline.expires)
            if not progressed:
                probe_at = self.resilience.breakers.next_probe_at(
                    [peer_key(p) for p in peers])
                wake = min(max(wake, probe_at), deadline.expires)
            self.clock.wait_until(wake, self._stop)

    def _try_peer(self, peer, target_round: int,
                  deadline: Optional[Deadline] = None) -> tuple:
        """One streaming attempt against `peer`.  Returns (reached,
        aborted): `aborted` means WE bailed (stop() or budget expiry), so
        the caller must not blame the peer for the lack of progress."""
        head = self._head_beacon()
        buf: List[Beacon] = []
        aborted = False
        # Idle watchdog: a peer that stops producing for > 2·period is
        # abandoned so sync() can fail over (sync_manager.go:52-53,154-162);
        # without it a black-holed TCP stream stalls the manager forever.
        stream = _IdleTimeoutIter(
            self.fetch(peer, (head.round + 1) if head else 1),
            idle=max(2 * self.period, 10), stop=self._stop)
        try:
            for b in stream:
                if self._stop.is_set():
                    return False, True
                if deadline is not None and deadline.expired:
                    aborted = True
                    break       # budget spent mid-stream: flush what we have
                buf.append(b)
                # flush on a full chunk OR once the target is covered: the
                # serving side live-follows forever (sync_manager.go:468),
                # so waiting for a full chunk would buffer one round per
                # period indefinitely and never store anything
                if len(buf) >= self.chunk or b.round >= target_round:
                    head = self._verify_and_store(head, buf)
                    buf = []
                    if head is None:
                        return False, False
                    if head.round >= target_round:
                        return True, False
            if buf:
                head = self._verify_and_store(head, buf)
            reached = head is not None and head.round >= target_round
            return reached, aborted
        finally:
            # every exit path must tear the stream down, or the pump thread
            # keeps draining the peer's live-follow stream forever
            stream.close()

    def _verify_and_store(self, head: Optional[Beacon], chunk: List[Beacon]
                          ) -> Optional[Beacon]:
        """One device pass for the whole chunk; store on full success.

        Returns the new head, or None if the peer's stream is invalid
        (caller fails over to the next peer)."""
        # The aggregator may have stored rounds while we streamed
        # (chainstore.go:253-265): advance to the freshest head and drop the
        # now-stale prefix BEFORE the linkage check, or an honest peer would
        # be blamed for the overlap.
        cur = self._head_beacon()
        if cur is not None and (head is None or cur.round > head.round):
            head = cur
            chunk = [b for b in chunk if b.round > head.round]
            if not chunk:
                return head
        if not self._chunk_links(head, chunk):
            return None
        ok = self.verifier.verify_batch(
            [b.round for b in chunk],
            [b.signature for b in chunk],
            [b.previous_sig for b in chunk])
        if not ok.all():
            return None
        for b in chunk:
            try:
                self.chain.put(b)
            except (ErrBeaconAlreadyStored, ValueError):
                # racing the aggregator is benign (chainstore.go:253-265)
                pass
        self._last_progress = self.clock.now()
        return chunk[-1]

    def _chunk_links(self, head: Optional[Beacon], chunk: List[Beacon]) -> bool:
        """Host-side linkage prefix pass (SURVEY.md §5.7).

        With no local head (fresh store) the first streamed beacon anchors
        the walk; its own validity is established by the signature check."""
        prev = head
        for b in chunk:
            if prev is not None:
                if b.round != prev.round + 1:
                    return False
                if self.scheme.chained and prev.round > 0 \
                        and b.previous_sig != prev.signature:
                    return False
            prev = b
        return True

    # -- chain validation & repair (sync_manager.go:170-268) -----------------

    def check_past_beacons(self, upto: int,
                           progress: Optional[Callable[[int, int], None]] = None
                           ) -> List[int]:
        """Validate rounds 1..upto of our own store in device chunks;
        returns the faulty round numbers (missing, failing signature
        verification, or breaking the chained linkage).

        Facade over `chain.integrity.IntegrityScanner` (ROADMAP storage
        follow-up): the pre-scanner implementation verified against the
        STORE-RETURNED `previous_sig`, which a raw trimmed store (the
        daemon default, `require_previous=False`) materializes as None —
        so a chained-scheme check flagged every round.  The scanner
        carries the linkage anchor itself (the previous row's stored
        signature, seeded from a stored genesis row or the configured
        genesis seed), so trimmed and full-beacon stores validate alike.
        Prefer `ChainStore.integrity_scan` for new callers — it returns
        the full ScanReport that `heal` consumes."""
        from ..chain.integrity import MODE_FULL
        report = self._scanner().scan(mode=MODE_FULL, upto=upto,
                                      progress=progress)
        return report.faulty_rounds

    def _scanner(self):
        from ..chain.integrity import IntegrityScanner
        # scan the RAW backend when the chain exposes one — corruption
        # hides underneath the decorators (same choice as
        # ChainStore.integrity_scan) — and recover the genesis anchor
        # from whichever facade we were handed: FollowFacade carries
        # genesis_seed directly, ChainStore derives it from the group.
        store = getattr(self.chain, "backend", None) or self.chain.store
        seed = getattr(self.chain, "genesis_seed", None)
        if seed is None:
            group = getattr(self.chain, "group", None)
            if group is not None:
                seed = group.get_genesis_seed()
        return IntegrityScanner(
            store, self.scheme, verifier=self.verifier,
            genesis_seed=seed, chunk=self.chunk)

    def correct_past_beacons(self, raw_store, faulty: Sequence[int],
                             peers: Optional[Sequence[object]] = None) -> List[int]:
        """Re-fetch faulty rounds from peers, verify, and overwrite through
        the RAW store (the append decorator would reject non-head writes).

        Returns the rounds that could not be repaired."""
        peers = self.resilience.rank(list(peers or self.peers))
        remaining = sorted(set(faulty))
        for peer in peers:
            if not remaining:
                break
            br = self.resilience.breaker(peer_key(peer))
            dialed = False
            fetched = []
            for r in remaining:
                try:
                    b = self._fetch_one(peer, r)
                    dialed = True
                except BreakerOpen:
                    # client-side rejection: nothing was dialed, and every
                    # further round would be rejected too — next peer
                    break
                except Exception:
                    dialed = True
                    b = None
                fetched.append((r, b))
            got = [(r, b) for r, b in fetched if b is not None]
            repaired = set()
            if got:
                # one device pass for everything this peer produced
                ok = self.verifier.verify_batch(
                    [b.round for _, b in got],
                    [b.signature for _, b in got],
                    [b.previous_sig for _, b in got])
                goods = [(r, b) for (r, b), good in zip(got, ok) if good]
                for r, _ in goods:
                    raw_store.delete(r)
                try:
                    # one transaction for the whole batch on engines that
                    # support it (chain/store.py put_many contract)
                    raw_store.put_many([b for _, b in goods])
                    repaired = {r for r, _ in goods}
                except Exception:
                    # the rows are already deleted — salvage row by row so
                    # a batch-level failure (e.g. SQLITE_BUSY past the
                    # timeout) loses at most the rows that individually
                    # fail, not every verified replacement in hand
                    for r, b in goods:
                        try:
                            raw_store.put(b)
                            repaired.add(r)
                        except Exception:
                            pass
                remaining = [r for r in remaining if r not in repaired]
            # repair-path breaker accounting: a peer that produced nothing
            # usable (unreachable, or only forged rounds) trips towards
            # open — but only an ACTUAL dial outcome counts; a BreakerOpen
            # fast-fail is not new evidence against the peer
            if repaired:
                br.record_success()
            elif dialed:
                br.record_failure()
        return remaining

    def heal(self, raw_store, report_or_rounds, peers=None,
             beacon_id: str = "default") -> List[int]:
        """Quarantine + repair rounds flagged by an integrity scan
        (chain/integrity.py): corrupt rows are tombstoned to the
        quarantine side table first so this node stops serving them, then
        repair runs in two phases:

          1. provably-bad rounds (invalid signature, malformed, missing)
             are re-fetched from breaker-ranked peers
             (correct_past_beacons — the existing repair machinery with
             its peer accounting), verified in device batches, and
             written back through the RAW store;
          2. rounds that were merely UNPROVABLE (their anchor rotted, not
             their own bytes) get a PROMOTE pass: the tombstoned bytes
             are re-verified against the now-restored anchor and put back
             without touching the network (ROADMAP item 6 two-phase
             quarantine).  Only the rounds promotion cannot prove fall
             through to a peer fetch.

        Accepts a ScanReport or a plain round list (list = no kind
        information, everything is treated as provably bad).  Returns the
        rounds that could not be repaired (every peer failed or served
        forgeries); those stay quarantined rather than corrupt."""
        from ..chain.integrity import (UNLINKED, IntegrityScanner,
                                       ScanReport)
        from ..metrics import integrity_repaired
        unprovable: set = set()
        if isinstance(report_or_rounds, ScanReport):
            bad_rows = report_or_rounds.quarantinable_rounds
            faulty = report_or_rounds.faulty_rounds
            # promotable = rounds whose EVERY finding is UNLINKED: their
            # own bytes were never proven bad, only unprovable
            kinds: dict = {}
            for f in report_or_rounds.findings:
                kinds.setdefault(f.round, set()).add(f.kind)
            unprovable = {r for r, ks in kinds.items() if ks == {UNLINKED}}
        else:
            faulty = sorted(set(report_or_rounds))
            bad_rows = faulty
        if not faulty:
            return []
        IntegrityScanner(raw_store, self.scheme,
                         beacon_id=beacon_id).quarantine(bad_rows)
        fetch_first = [r for r in faulty if r not in unprovable]
        remaining = self.correct_past_beacons(raw_store, fetch_first, peers) \
            if fetch_first else []
        if unprovable:
            promoted = self._promote_tombstoned(raw_store,
                                                sorted(unprovable),
                                                beacon_id=beacon_id)
            leftover = [r for r in sorted(unprovable) if r not in promoted]
            if leftover:
                remaining += self.correct_past_beacons(raw_store, leftover,
                                                       peers)
        remaining = sorted(set(remaining))
        # a repaired round's stale tombstone must not linger (a later
        # promote pass could resurrect pre-repair bytes)
        drop = getattr(raw_store, "drop_tombstone", None)
        if drop is not None:
            for r in faulty:
                if r not in remaining:
                    try:
                        drop(r)
                    except Exception:
                        pass
        healed = len(faulty) - len(remaining)
        if healed > 0:
            integrity_repaired.labels(beacon_id).inc(healed)
        return remaining

    def _promote_tombstoned(self, raw_store, rounds: List[int],
                            beacon_id: str = "default") -> set:
        """Phase-2 repair: re-verify each tombstoned row against its (now
        hopefully restored) anchor and promote it back into the chain.
        Ascending order on purpose — a promoted round is the anchor of
        the next one, so a whole unprovable RUN above one corrupt row
        heals from a single peer-fetched anchor."""
        from ..metrics import integrity_promoted
        promoted: set = set()
        tombstoned = getattr(raw_store, "tombstoned", None)
        if tombstoned is None:
            return promoted
        for r in rounds:
            try:
                row = tombstoned(r)
            except Exception:
                row = None
            if row is None:
                continue
            prev = None
            if self.scheme.chained:
                try:
                    prev = raw_store.get(r - 1).signature
                except Exception:
                    continue        # anchor still missing: cannot prove
            try:
                ok = self.verifier.verify_batch([r], [row.signature], [prev])
            except Exception:
                continue
            if not bool(ok[0]):
                continue
            raw_store.put(Beacon(round=r, signature=row.signature,
                                 previous_sig=prev))
            raw_store.drop_tombstone(r)
            promoted.add(r)
        if promoted:
            integrity_promoted.labels(beacon_id).inc(len(promoted))
        return promoted

    def _fetch_one(self, peer, round_: int) -> Optional[Beacon]:
        """Single-round fetch.  Lets `BreakerOpen` propagate (client-side
        rejection — no dial happened) and tears the stream down on every
        exit: the production fetch is a SyncChain stream that live-follows
        forever after the replay, so returning mid-iteration without
        cancel() would leak one server-side stream per repaired round."""
        stream = self.fetch(peer, round_)
        try:
            for b in stream:
                if b.round == round_:
                    return b
                if b.round > round_:
                    return None
            return None
        finally:
            for name in ("cancel", "close"):
                fn = getattr(stream, name, None)
                if callable(fn):
                    try:
                        fn()
                    except Exception:
                        pass
                    break


class SyncChainServer:
    """Serving side of a sync stream (sync_manager.go:468-570)."""

    def __init__(self, chain):
        self.chain = chain                  # ChainStore facade

    def stream(self, remote_addr: str, from_round: int,
               stop: Optional[threading.Event] = None) -> Iterator[Beacon]:
        """Replay from `from_round` via cursor, then live-follow stored
        beacons through a callback keyed by the remote address — a
        re-request from the same address replaces the old stream's callback
        (sync_manager.go:542-560)."""
        stop = stop or threading.Event()
        q: queue.Queue = queue.Queue(maxsize=100)
        cb_id = f"sync-{remote_addr}"
        self.chain.cbstore.add_callback(cb_id, lambda b: _offer(q, b))
        sent = from_round - 1
        last = [None]       # previous STORE row yielded (the walk anchor)
        try:
            sent = yield from self._replay(from_round, sent, last)
            while not stop.is_set():
                try:
                    b = q.get(timeout=0.1)
                except queue.Empty:
                    continue
                if b is None:
                    return
                if b.round > sent + 1:
                    # the bounded queue dropped beacons (slow consumer):
                    # re-replay the hole from the store before following on
                    sent = yield from self._replay(sent + 1, sent, last)
                if b.round > sent:
                    yield self._fill_prev(b, last[0])
                    last[0] = b
                    sent = b.round
        finally:
            self.chain.cbstore.remove_callback(cb_id)

    def _replay(self, from_round: int, sent: int, last: list):
        """Cursor replay of stored rounds >= from_round; returns new `sent`."""
        cur = self.chain.store.cursor()
        b = cur.seek(from_round) if from_round > 0 else cur.first()
        while b is not None:
            if b.round > sent:
                yield self._fill_prev(b, last[0])
                last[0] = b
                sent = b.round
            b = cur.next()
        return sent

    def _fill_prev(self, b: Beacon, last: Optional[Beacon]) -> Beacon:
        """Trimmed stores (sqlite/postgres) materialize rows WITHOUT
        previous_sig, but a chained-scheme peer cannot link or verify a
        stream that omits it — fill it on the serving side from the walk
        itself (or one point read at the stream head).  Rounds whose
        anchor genuinely isn't stored (round 1, a hole) stream as-is and
        the peer anchors on its own head."""
        scheme = getattr(getattr(self.chain, "group", None), "scheme", None)
        if scheme is None or not scheme.chained \
                or b.previous_sig is not None:
            return b
        if last is not None and last.round == b.round - 1:
            prev_sig = last.signature
        else:
            try:
                prev_sig = self.chain.store.get(b.round - 1).signature
            except Exception:
                return b
        return Beacon(round=b.round, signature=b.signature,
                      previous_sig=prev_sig)


def _offer(q: queue.Queue, item) -> None:
    try:
        q.put_nowait(item)
    except queue.Full:
        pass  # slow stream consumer; the live loop's gap replay repairs


class _IdleTimeoutIter:
    """Iterator wrapper that gives up when the source is idle too long.

    The source is drained on a daemon thread into a queue; `__next__`
    raises StopIteration after `idle` seconds without an item, and the
    underlying gRPC call is cancelled if it exposes `cancel()`."""

    _END = object()

    def __init__(self, source, idle: float, stop: threading.Event):
        self._source = source
        self._idle = idle
        self._stop = stop
        self._dead = False          # consumer gave up; pump must exit
        self._q: queue.Queue = queue.Queue(maxsize=64)
        self._thread = threading.Thread(target=self._pump, daemon=True,
                                        name="sync-stream-pump")
        self._thread.start()

    def _pump(self):
        try:
            for item in self._source:
                while not self._stop.is_set() and not self._dead:
                    try:
                        self._q.put(item, timeout=1)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set() or self._dead:
                    self._cancel()
                    return
        except Exception:
            pass
        finally:
            # the END sentinel must be delivered even through a full queue,
            # or the consumer only notices stream end after the idle timeout
            while not self._stop.is_set() and not self._dead:
                try:
                    self._q.put(self._END, timeout=1)
                    break
                except queue.Full:
                    continue

    def __iter__(self):
        return self

    def __next__(self):
        try:
            item = self._q.get(timeout=self._idle)
        except queue.Empty:
            self._dead = True
            self._cancel()
            raise StopIteration
        if item is self._END:
            raise StopIteration
        return item

    def close(self):
        """Consumer is done with the stream: stop the pump + cancel the RPC."""
        self._dead = True
        self._cancel()
        # the pump exits within one queue-put timeout of _dead flipping;
        # bounded join so a close() during teardown reaps it
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2)

    def _cancel(self):
        cancel = getattr(self._source, "cancel", None)
        if callable(cancel):
            try:
                cancel()
            except Exception:
                pass
