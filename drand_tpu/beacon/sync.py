"""SyncManager + SyncChain server (chain/beacon/sync_manager.go:28-590).

The TPU-first redesign of the reference's sync path: where the Go code
verifies each streamed beacon with one CPU pairing (sync_manager.go:406 —
the designated batch hook per SURVEY.md §2.5), beacons here are buffered
into chunks and verified in ONE device RLC pass per chunk through
`BatchBeaconVerifier`, with the chained-linkage check done as the cheap
host-side prefix pass.

Components:
  * `SyncManager.run` — serializes sync requests (queue 3), restarts a sync
    idle for > 2·period (sync_manager.go:52-53,154-162), shuffles peers for
    failover (sync_manager.go:302).
  * `check_past_beacons` / `correct_past_beacons` — full-chain validation
    and repair (sync_manager.go:170-268); repair writes through the RAW
    store, bypassing the append decorator (the "insecure store" ReSync path,
    sync_manager.go:411-416).
  * `SyncChainServer` — the serving side of a sync stream: cursor replay
    from `from_round`, then live-follow via a store callback registered
    under the remote address (replaced on re-request, sync_manager.go:542-560).
"""

import queue
import random
import threading
from typing import Callable, Iterable, Iterator, List, Optional, Sequence

from ..chain.beacon import Beacon
from ..chain.errors import ErrNoBeaconSaved, ErrNoBeaconStored
from .stores import ErrBeaconAlreadyStored

DEFAULT_CHUNK = 512
SYNC_QUEUE = 3


class ErrFailedAll(Exception):
    """Every candidate peer failed to advance the sync (sync_manager.go:59)."""


class SyncManager:
    """Pulls missing rounds from peers with batched device verification.

    `fetch(peer, from_round)` must return an iterator of Beacons streamed by
    the peer (the net layer's SyncChain client; tests wire SyncChainServer
    generators directly)."""

    def __init__(self, chain, scheme, public_key_bytes: bytes, period: int,
                 clock, fetch: Callable[[object, int], Iterable[Beacon]],
                 peers: Sequence[object] = (), chunk: int = DEFAULT_CHUNK,
                 verifier=None):
        from ..crypto.batch import BatchBeaconVerifier
        self.chain = chain                  # ChainStore facade (decorators)
        self.scheme = scheme
        self.period = period
        self.clock = clock
        self.fetch = fetch
        self.peers = list(peers)
        self.chunk = chunk
        self.verifier = verifier or BatchBeaconVerifier(scheme,
                                                        public_key_bytes)
        self._requests: queue.Queue = queue.Queue(maxsize=SYNC_QUEUE)
        self._stop = threading.Event()
        self._last_progress = None
        self._thread: Optional[threading.Thread] = None

    # -- request plane -------------------------------------------------------

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self.run, daemon=True,
                                            name="sync-manager")
            self._thread.start()

    def send_sync_request(self, target_round: int,
                          peers: Optional[Sequence[object]] = None) -> None:
        """Non-blocking enqueue; a full queue drops the request — the next
        gap detection re-issues it (sync_manager.go:121-142)."""
        try:
            self._requests.put_nowait((target_round, list(peers or self.peers)))
        except queue.Full:
            pass

    def run(self) -> None:
        while not self._stop.is_set():
            try:
                target, peers = self._requests.get(timeout=0.1)
            except queue.Empty:
                continue
            # collapse queued requests to the farthest target
            try:
                while True:
                    t2, p2 = self._requests.get_nowait()
                    if t2 > target:
                        target, peers = t2, p2
            except queue.Empty:
                pass
            if target <= self._head_round():
                continue
            try:
                self.sync(target, peers)
            except ErrFailedAll:
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- the sync itself -----------------------------------------------------

    def _head_round(self) -> int:
        head = self._head_beacon()
        return head.round if head is not None else 0

    def _head_beacon(self) -> Optional[Beacon]:
        try:
            return self.chain.last()
        except ErrNoBeaconStored:
            return None   # fresh store (follow-mode bootstrap)

    def sync(self, target_round: int, peers: Sequence[object]) -> None:
        """Stream from shuffled peers until the chain reaches target_round."""
        peers = list(peers)
        random.shuffle(peers)
        for peer in peers:
            if self._stop.is_set():
                return
            try:
                if self._try_peer(peer, target_round):
                    return
            except Exception:
                continue
        raise ErrFailedAll(f"no peer could sync us to round {target_round}")

    def _try_peer(self, peer, target_round: int) -> bool:
        head = self._head_beacon()
        buf: List[Beacon] = []
        # Idle watchdog: a peer that stops producing for > 2·period is
        # abandoned so sync() can fail over (sync_manager.go:52-53,154-162);
        # without it a black-holed TCP stream stalls the manager forever.
        stream = _IdleTimeoutIter(
            self.fetch(peer, (head.round + 1) if head else 1),
            idle=max(2 * self.period, 10), stop=self._stop)
        try:
            for b in stream:
                if self._stop.is_set():
                    return False
                buf.append(b)
                # flush on a full chunk OR once the target is covered: the
                # serving side live-follows forever (sync_manager.go:468),
                # so waiting for a full chunk would buffer one round per
                # period indefinitely and never store anything
                if len(buf) >= self.chunk or b.round >= target_round:
                    head = self._verify_and_store(head, buf)
                    buf = []
                    if head is None:
                        return False
                    if head.round >= target_round:
                        return True
            if buf:
                head = self._verify_and_store(head, buf)
            return head is not None and head.round >= target_round
        finally:
            # every exit path must tear the stream down, or the pump thread
            # keeps draining the peer's live-follow stream forever
            stream.close()

    def _verify_and_store(self, head: Optional[Beacon], chunk: List[Beacon]
                          ) -> Optional[Beacon]:
        """One device pass for the whole chunk; store on full success.

        Returns the new head, or None if the peer's stream is invalid
        (caller fails over to the next peer)."""
        # The aggregator may have stored rounds while we streamed
        # (chainstore.go:253-265): advance to the freshest head and drop the
        # now-stale prefix BEFORE the linkage check, or an honest peer would
        # be blamed for the overlap.
        cur = self._head_beacon()
        if cur is not None and (head is None or cur.round > head.round):
            head = cur
            chunk = [b for b in chunk if b.round > head.round]
            if not chunk:
                return head
        if not self._chunk_links(head, chunk):
            return None
        ok = self.verifier.verify_batch(
            [b.round for b in chunk],
            [b.signature for b in chunk],
            [b.previous_sig for b in chunk])
        if not ok.all():
            return None
        for b in chunk:
            try:
                self.chain.put(b)
            except (ErrBeaconAlreadyStored, ValueError):
                # racing the aggregator is benign (chainstore.go:253-265)
                pass
        self._last_progress = self.clock.now()
        return chunk[-1]

    def _chunk_links(self, head: Optional[Beacon], chunk: List[Beacon]) -> bool:
        """Host-side linkage prefix pass (SURVEY.md §5.7).

        With no local head (fresh store) the first streamed beacon anchors
        the walk; its own validity is established by the signature check."""
        prev = head
        for b in chunk:
            if prev is not None:
                if b.round != prev.round + 1:
                    return False
                if self.scheme.chained and prev.round > 0 \
                        and b.previous_sig != prev.signature:
                    return False
            prev = b
        return True

    # -- chain validation & repair (sync_manager.go:170-268) -----------------

    def check_past_beacons(self, upto: int,
                           progress: Optional[Callable[[int, int], None]] = None
                           ) -> List[int]:
        """Validate rounds 1..upto of our own store in device chunks.

        Returns the faulty round numbers: missing from the store, failing
        signature verification, or breaking the chained linkage."""
        faulty: List[int] = []
        store = self.chain.store
        buf: List[Beacon] = []
        prev: Optional[Beacon] = None       # linkage carried across chunks
        for r in range(1, upto + 1):
            try:
                b = store.get(r)
            except ErrNoBeaconSaved:
                faulty.append(r)
                continue
            buf.append(b)
            if len(buf) >= self.chunk:
                faulty.extend(self._check_chunk(buf, prev))
                prev = buf[-1]
                if progress:
                    progress(r, upto)
                buf = []
        if buf:
            faulty.extend(self._check_chunk(buf, prev))
            if progress:
                progress(upto, upto)
        return sorted(set(faulty))

    def _check_chunk(self, chunk: List[Beacon],
                     prev: Optional[Beacon]) -> List[int]:
        ok = self.verifier.verify_batch(
            [b.round for b in chunk],
            [b.signature for b in chunk],
            [b.previous_sig for b in chunk])
        bad = [b.round for b, good in zip(chunk, ok) if not good]
        if self.scheme.chained:
            pairs = zip(([prev] if prev else []) + chunk, chunk if prev else chunk[1:])
            for a, b in pairs:
                if b.round == a.round + 1 and b.previous_sig != a.signature:
                    bad.append(b.round)
        return bad

    def correct_past_beacons(self, raw_store, faulty: Sequence[int],
                             peers: Optional[Sequence[object]] = None) -> List[int]:
        """Re-fetch faulty rounds from peers, verify, and overwrite through
        the RAW store (the append decorator would reject non-head writes).

        Returns the rounds that could not be repaired."""
        peers = list(peers or self.peers)
        random.shuffle(peers)
        remaining = sorted(set(faulty))
        for peer in peers:
            if not remaining:
                break
            fetched = [(r, self._fetch_one(peer, r)) for r in remaining]
            got = [(r, b) for r, b in fetched if b is not None]
            if got:
                # one device pass for everything this peer produced
                ok = self.verifier.verify_batch(
                    [b.round for _, b in got],
                    [b.signature for _, b in got],
                    [b.previous_sig for _, b in got])
                repaired = set()
                for (r, b), good in zip(got, ok):
                    if good:
                        raw_store.delete(r)
                        raw_store.put(b)
                        repaired.add(r)
                remaining = [r for r in remaining if r not in repaired]
        return remaining

    def _fetch_one(self, peer, round_: int) -> Optional[Beacon]:
        try:
            for b in self.fetch(peer, round_):
                if b.round == round_:
                    return b
                if b.round > round_:
                    return None
        except Exception:
            return None
        return None


class SyncChainServer:
    """Serving side of a sync stream (sync_manager.go:468-570)."""

    def __init__(self, chain):
        self.chain = chain                  # ChainStore facade

    def stream(self, remote_addr: str, from_round: int,
               stop: Optional[threading.Event] = None) -> Iterator[Beacon]:
        """Replay from `from_round` via cursor, then live-follow stored
        beacons through a callback keyed by the remote address — a
        re-request from the same address replaces the old stream's callback
        (sync_manager.go:542-560)."""
        stop = stop or threading.Event()
        q: queue.Queue = queue.Queue(maxsize=100)
        cb_id = f"sync-{remote_addr}"
        self.chain.cbstore.add_callback(cb_id, lambda b: _offer(q, b))
        sent = from_round - 1
        try:
            sent = yield from self._replay(from_round, sent)
            while not stop.is_set():
                try:
                    b = q.get(timeout=0.1)
                except queue.Empty:
                    continue
                if b is None:
                    return
                if b.round > sent + 1:
                    # the bounded queue dropped beacons (slow consumer):
                    # re-replay the hole from the store before following on
                    sent = yield from self._replay(sent + 1, sent)
                if b.round > sent:
                    yield b
                    sent = b.round
        finally:
            self.chain.cbstore.remove_callback(cb_id)

    def _replay(self, from_round: int, sent: int):
        """Cursor replay of stored rounds >= from_round; returns new `sent`."""
        cur = self.chain.store.cursor()
        b = cur.seek(from_round) if from_round > 0 else cur.first()
        while b is not None:
            if b.round > sent:
                yield b
                sent = b.round
            b = cur.next()
        return sent


def _offer(q: queue.Queue, item) -> None:
    try:
        q.put_nowait(item)
    except queue.Full:
        pass  # slow stream consumer; the live loop's gap replay repairs


class _IdleTimeoutIter:
    """Iterator wrapper that gives up when the source is idle too long.

    The source is drained on a daemon thread into a queue; `__next__`
    raises StopIteration after `idle` seconds without an item, and the
    underlying gRPC call is cancelled if it exposes `cancel()`."""

    _END = object()

    def __init__(self, source, idle: float, stop: threading.Event):
        self._source = source
        self._idle = idle
        self._stop = stop
        self._dead = False          # consumer gave up; pump must exit
        self._q: queue.Queue = queue.Queue(maxsize=64)
        self._thread = threading.Thread(target=self._pump, daemon=True,
                                        name="sync-stream-pump")
        self._thread.start()

    def _pump(self):
        try:
            for item in self._source:
                while not self._stop.is_set() and not self._dead:
                    try:
                        self._q.put(item, timeout=1)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set() or self._dead:
                    self._cancel()
                    return
        except Exception:
            pass
        finally:
            # the END sentinel must be delivered even through a full queue,
            # or the consumer only notices stream end after the idle timeout
            while not self._stop.is_set() and not self._dead:
                try:
                    self._q.put(self._END, timeout=1)
                    break
                except queue.Full:
                    continue

    def __iter__(self):
        return self

    def __next__(self):
        try:
            item = self._q.get(timeout=self._idle)
        except queue.Empty:
            self._dead = True
            self._cancel()
            raise StopIteration
        if item is self._END:
            raise StopIteration
        return item

    def close(self):
        """Consumer is done with the stream: stop the pump + cancel the RPC."""
        self._dead = True
        self._cancel()

    def _cancel(self):
        cancel = getattr(self._source, "cancel", None)
        if callable(cancel):
            try:
                cancel()
            except Exception:
                pass
