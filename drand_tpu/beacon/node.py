"""Beacon Handler: the per-chain round loop (chain/beacon/node.go:41-473).

Owns ticker + aggregator + vault.  Every tick: read the chain head, sign a
partial for head.round+1, broadcast it to all peers (and feed it to the own
aggregator).  When the head lags the wall-clock round, trigger sync and run
catchup rebroadcasts at the (faster) catchup period so a halted network can
fast-forward as soon as beacons appear (node.go:368-403).

Ingress (`process_partial_beacon`, node.go:109-181) performs the cheap
checks — round window, signer membership, not-self — and feeds the
aggregator, which performs the cryptographic verification in batch at
threshold time (the TPU-first redesign of node.go:150's per-packet pairing).
"""

import threading

from ..common import make_lock
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from ..chain.beacon import Beacon, genesis_beacon
from ..chain.errors import ErrNoBeaconStored
from ..chain.timing import current_round, time_of_round
from ..crypto.tbls import index_of
from ..crypto.vault import Vault
from .chainstore import ChainStore
from .clock import Clock, RealClock
from .ticker import Ticker


@dataclass
class PartialBeaconPacket:
    """Wire form of one partial (protobuf/drand/protocol.proto:83)."""
    round: int
    previous_signature: Optional[bytes]
    partial_sig: bytes            # be16(index) || sig
    beacon_id: str = "default"

    def signer_index(self) -> int:
        return index_of(self.partial_sig)


def _host_verifier_factory(scheme, pub_poly, n_nodes):
    from .chainstore import HostPartialVerifier
    return HostPartialVerifier(scheme, pub_poly)


def device_verifier_factory(scheme, pub_poly, n_nodes):
    """Factory for the TPU-batched aggregation-time verifier."""
    from .chainstore import DevicePartialVerifier
    return DevicePartialVerifier(scheme, pub_poly, n_nodes)


@dataclass
class HandlerConfig:
    group: object                  # key.Group
    share: object                  # key.Share
    index: int                     # our node index in the group
    store: object                  # raw chain.Store backend
    clock: Clock = field(default_factory=RealClock)
    # builds the aggregation-time partial verifier; swap in
    # device_verifier_factory for the TPU path
    verifier_factory: Callable = _host_verifier_factory
    # broadcast(packet) must deliver to every OTHER group member
    broadcast: Optional[Callable[[PartialBeaconPacket], None]] = None
    # called with the target round when the chain lags; sync fills the gap
    on_sync_needed: Optional[Callable[[int], None]] = None
    beacon_id: str = "default"


class Handler:
    def __init__(self, cfg: HandlerConfig):
        self.cfg = cfg
        self.group = cfg.group
        self.scheme = cfg.group.scheme
        self.vault = Vault(self.scheme, cfg.group, cfg.share)
        self.clock = cfg.clock
        self.index = cfg.index
        self.catchup_period = cfg.group.catchup_period or cfg.group.period

        # a fresh chain starts from the genesis beacon (node.go:79); must
        # happen before the decorator chain snapshots the chain head
        try:
            cfg.store.last()
        except ErrNoBeaconStored:
            cfg.store.put(genesis_beacon(cfg.group.get_genesis_seed()))

        self.chain = ChainStore(
            cfg.store, self.vault, cfg.clock, cfg.group,
            on_sync_needed=self._sync_needed,
            partial_verifier=cfg.verifier_factory(
                self.scheme, self.vault.get_pub(), len(cfg.group)))
        self.ticker = Ticker(cfg.clock, cfg.group.period, cfg.group.genesis_time)
        # Fast-forward on each stored beacon (node.go:368-403): while the
        # chain lags the wall-clock round, every new beacon immediately
        # triggers the next partial — catching up must not wait for the
        # (possibly frozen fake-clock) catchup timer.  Without this, a node
        # that consumes a tick while still aggregating the previous round
        # never signs the ticked round and a thr-sized network deadlocks.
        self.chain.cbstore.add_callback(
            f"fastforward-{self.index}", self._on_beacon_stored)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._catchup_thread: Optional[threading.Thread] = None
        self._lock = make_lock()
        self._transition_group = None      # (group, share) armed by reshare
        self.running = False

    # -- ingress (node.go:109-181) ------------------------------------------

    def process_partial_beacon(self, packet: PartialBeaconPacket) -> None:
        """Validate window/membership and feed the aggregator.  Raises
        ValueError on protocol violations (mapped to RPC errors upstream)."""
        current = self.ticker.current_round()
        next_round = current + 1
        if packet.round > next_round:
            raise ValueError(
                f"partial for future round {packet.round} (next {next_round})")
        try:
            last = self.chain.last()
            if packet.round <= last.round:
                return  # stale; already have this beacon
        except ErrNoBeaconStored:
            pass
        idx = packet.signer_index()
        node = self.group.node(idx)
        if node is None:
            raise ValueError(f"unknown signer index {idx}")
        if idx == self.index:
            return  # our own partial comes through broadcast_next_partial
        self.chain.new_valid_partial(packet.round, packet.previous_signature,
                                     packet.partial_sig)

    # -- round loop (node.go:322-473) ---------------------------------------

    def start(self) -> None:
        """Start at genesis (DKG fresh-start path, node.go:195)."""
        self._launch()

    def catchup(self) -> None:
        """Start after a restart: sync first, rejoin at the next tick
        (node.go:219-228)."""
        self._sync_needed(self.ticker.current_round())
        self._launch()

    def transition(self, new_group, new_share, on_commit=None) -> None:
        """Arm a reshare transition: at the group's transition time the vault
        swaps to the new share/group atomically (node.go:257-281).

        `on_commit` is the durability hook (core/dkg_journal.py): invoked
        exactly once, at the moment the swap commits, so the staged
        group/share files are promoted over the active ones only when the
        chain no longer needs the old share.  A crash before this point
        restarts with the old state + the pending ledger; a crash after
        it restarts already transitioned."""
        with self._lock:
            self._transition_group = (new_group, new_share, on_commit)

    def _launch(self) -> None:
        if self._thread is not None:
            return
        self.running = True
        self.ticker.start()
        self._ticks = self.ticker.channel()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"handler-{self.index}")
        self._thread.start()
        self._catchup_thread = threading.Thread(
            target=self._run_catchup, daemon=True,
            name=f"catchup-{self.index}")
        self._catchup_thread.start()

    def _run(self) -> None:
        import queue as _q
        while not self._stop.is_set():
            try:
                tick = self._ticks.get(timeout=0.1)
            except _q.Empty:
                continue
            self._maybe_transition()
            try:
                last = self.chain.last()
            except ErrNoBeaconStored:
                continue
            if last.round + 1 < tick.round:
                # gap: we're late — sync, and let catchup rebroadcasts
                # fast-forward us (node.go:358-367)
                self._sync_needed(tick.round)
            self.broadcast_next_partial(last)

    def _on_beacon_stored(self, beacon: Beacon) -> None:
        """Store-driven catchup (node.go:368-403 fast-forward): if we are
        still behind the wall clock after storing `beacon`, sign and
        broadcast the next round's partial right away."""
        if self._stop.is_set() or not self.running:
            return
        try:
            last = self.chain.last()
        except ErrNoBeaconStored:
            return
        if beacon.round != last.round:
            return  # mid-sync backlog: only the head triggers a partial
        self._maybe_transition()
        if beacon.round < self.ticker.current_round():
            self.broadcast_next_partial(beacon)

    def _run_catchup(self) -> None:
        """While behind the wall clock, rebroadcast the next partial every
        catchup period; each stored beacon advances the target immediately
        (node.go:368-403)."""
        while not self._stop.is_set():
            if not self.clock.wait_until(self.clock.now() + self.catchup_period,
                                         self._stop):
                return
            self._maybe_transition()
            try:
                last = self.chain.last()
            except ErrNoBeaconStored:
                continue
            if last.round + 1 < self.ticker.current_round():
                self.broadcast_next_partial(last)

    def _maybe_transition(self) -> None:
        """Share swap at the transition ROUND boundary in chain space
        (node.go:257-281): rounds below the transition round must be signed
        with the OLD share even if the wall clock is already past the
        transition time (a lagging chain first catches its old-key segment
        up; swapping early would sign that segment with the new key and
        stall the chain forever)."""
        with self._lock:
            pending = self._transition_group
            if pending is None:
                return
            new_group, new_share, on_commit = pending
            transition_round = current_round(
                new_group.transition_time, new_group.period,
                new_group.genesis_time)
            try:
                next_to_sign = self.chain.last().round + 1
            except ErrNoBeaconStored:
                next_to_sign = 1
            if int(self.clock.now()) < new_group.transition_time \
                    or next_to_sign < transition_round:
                return
            self._transition_group = None
            # The swap happens INSIDE the lock: every signing path calls
            # _maybe_transition before signing, so a concurrent caller
            # blocks here until the vault/verifier swap is complete
            # instead of seeing `pending is None` mid-swap and signing
            # the transition round with the OLD share (a stray old-share
            # partial does not just fail — it poisons the partial cache's
            # slot for this index, and the rebroadcast-once transport
            # never re-delivers the good one).
            # Promote the staged on-disk state BEFORE the in-memory
            # swap: if the commit lands and we crash, the restart is
            # simply already transitioned; disk failures must not block
            # the live swap.
            if on_commit is not None:
                try:
                    on_commit()
                except Exception:
                    pass        # reported by the owner's own logging
            if new_share is not None:
                self.vault.set_info(new_group, new_share)
                self.group = new_group
                self.chain.group = new_group
                self.chain.partial_verifier = self.cfg.verifier_factory(
                    self.scheme, self.vault.get_pub(), len(new_group))
                self.index = new_share.private.index
                self.catchup_period = new_group.catchup_period \
                    or new_group.period
                return
        # we are not part of the new group: leave the network (outside
        # the lock — stop() joins the very threads that may be parked on
        # _maybe_transition's lock right now)
        # intentional fire-and-forget: the trampoline's whole job is to
        # run stop() outside this lock, and stop() joins every owned thread
        # tpu-vet: disable=threadlife
        threading.Thread(target=self.stop, daemon=True,
                         name="stop-async-node").start()

    def broadcast_next_partial(self, last: Beacon) -> None:
        """Sign our partial for last.round+1 and fan it out
        (node.go:408-473)."""
        round_ = last.round + 1
        prev = last.signature if self.scheme.chained else None
        msg = self.scheme.digest_beacon(round_, prev)
        try:
            partial = self.vault.sign_partial(msg)
        except RuntimeError:
            return  # no share yet (waiting on DKG)
        packet = PartialBeaconPacket(
            round=round_, previous_signature=prev, partial_sig=partial,
            beacon_id=self.cfg.beacon_id)
        # our own partial goes straight to the aggregator (node.go:444)
        self.chain.new_valid_partial(round_, prev, partial)
        if self.cfg.broadcast is not None:
            self.cfg.broadcast(packet)

    def _sync_needed(self, target_round: int) -> None:
        if self.cfg.on_sync_needed is not None:
            self.cfg.on_sync_needed(target_round)

    # -- lifecycle -----------------------------------------------------------

    def stop(self) -> None:
        self.running = False
        self._stop.set()
        self.ticker.stop()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._catchup_thread is not None:
            self._catchup_thread.join(timeout=5)
            self._catchup_thread = None
        self.chain.stop()
