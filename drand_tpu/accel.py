"""Accelerator pre-flight probe (shared by bench.py and __graft_entry__).

Why a SUBPROCESS: against a wedged axon tunnel, backend initialization
(`jax.devices()`) blocks indefinitely in native code while holding jax's
global backend lock — a probe thread therefore poisons its own process
(anything else that later touches the backend deadlocks on that lock),
and an in-process probe with no timeout eats the whole caller budget
(the round-4 driver bench spent its entire window inside backend init).
A subprocess can simply be killed at a deadline; the caller's process
never initializes a backend the probe didn't prove healthy.

Why the config-level platform pin: the axon sitecustomize force-sets
`jax_platforms` at interpreter start, overriding any JAX_PLATFORMS env
var — pinning must happen via `jax.config.update` + `clear_backends`
inside the probe interpreter itself.
"""

import json
import os
import subprocess
import sys

_PROBE_CODE = (
    "import os, json\n"
    "plat = os.environ.get('DRAND_TPU_PROBE_PLATFORM')\n"
    "import jax\n"
    "if plat:\n"
    "    from jax.extend.backend import clear_backends\n"
    "    jax.config.update('jax_platforms', plat)\n"
    "    clear_backends()\n"
    "print('PROBE ' + json.dumps({'backend': jax.default_backend(),"
    " 'devices': len(jax.devices())}), flush=True)\n"
)


def probe_backend(env=None, timeout=90, platform=None):
    """Initialize a JAX backend in a throwaway subprocess.

    Returns ``(info, detail)``: ``info`` is ``{"backend": str, "devices":
    int}`` on success, else ``None``; ``detail`` is a short human-readable
    string for logs/records (the probe JSON, the timeout notice, or the
    last line of the failing probe's stderr).
    """
    env = dict(os.environ if env is None else env)
    if platform:
        env["DRAND_TPU_PROBE_PLATFORM"] = platform
    try:
        pr = subprocess.run([sys.executable, "-c", _PROBE_CODE], env=env,
                            capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return None, f"backend init hung >{timeout}s (tunnel wedged?)"
    for line in pr.stdout.splitlines():
        if line.startswith("PROBE "):
            try:
                return json.loads(line[6:]), line[6:]
            except ValueError:
                break
    tail = (pr.stderr or pr.stdout).strip().splitlines()
    return None, (tail[-1] if tail else f"probe exit {pr.returncode}")[:200]
