"""Metrics + observability (reference: metrics/metrics.go, 535 LoC).

Prometheus series matching the reference's names so existing dashboards
work unchanged: `beacon_discrepancy_latency` (ms between the expected round
time and storage, metrics.go:83-88 / chain/beacon/store.go:156-163),
`last_beacon_round`, `group_size`, `group_threshold`, `dkg_state` /
`reshare_state` (+ timestamps), `drand_node_db`, `error_sending_partial`.

The metrics HTTP server also exposes pprof-equivalent profiling and the
cross-node federation route `/peer/<addr>/metrics` that proxies a group
member's metrics through the gRPC connection we already hold
(metrics.go:408-492) — operators scrape the whole group via one node.

`ThresholdMonitor` (metrics/threshold_monitor.go:12-105): counts distinct
peers with failed partial sends in a sliding one-minute window and
escalates log severity when failures cross threshold/2 and threshold.
"""

import threading
from typing import Callable, Dict, Optional

from prometheus_client import (CollectorRegistry, Counter, Gauge, Histogram,
                               generate_latest)

from .log import Logger

# Four registries, per the reference split (metrics.go:45-51).
PRIVATE = CollectorRegistry()
HTTP = CollectorRegistry()
GROUP = CollectorRegistry()
CLIENT = CollectorRegistry()

# -- label cardinality control ----------------------------------------------
# Prometheus allocates one time series per label combination, so every
# label value must come from a bounded set (the metriclabel lint rule).
# Naturally-unbounded values (peer addresses, tenant names, request-path
# leaves) pass through registered_label(), which caps distinct values per
# namespace and folds the tail into a fallback bucket — a scrape sees the
# first `limit` real values and one "other" series, never an explosion.

_label_sets: Dict[str, set] = {}
_label_lock = threading.Lock()


def registered_label(value, known=None, ns: str = "default",
                     limit: int = 64, fallback: str = "other") -> str:
    """Bound a metric label value.

    With `known`, membership decides: values outside the set collapse to
    `fallback`.  Without it, a first-come registry per `ns` admits up to
    `limit` distinct values; later unseen values collapse to `fallback`.
    """
    v = str(value)
    if known is not None:
        return v if v in known else fallback
    with _label_lock:
        seen = _label_sets.setdefault(ns, set())
        if v in seen:
            return v
        if len(seen) < limit:
            seen.add(v)
            return v
    return fallback

beacon_discrepancy_latency = Gauge(
    "beacon_discrepancy_latency",
    "Difference between the expected round time and the storage time (ms)",
    ["beacon_id"], registry=GROUP)
last_beacon_round = Gauge(
    "last_beacon_round", "Last locally stored beacon round",
    ["beacon_id"], registry=GROUP)
group_size = Gauge(
    "group_size", "Number of nodes in the group", ["beacon_id"],
    registry=GROUP)
group_threshold = Gauge(
    "group_threshold", "Threshold of the group", ["beacon_id"],
    registry=GROUP)
dkg_state = Gauge(
    "dkg_state", "DKG state (0 not started .. 4 done)", ["beacon_id"],
    registry=GROUP)
dkg_state_timestamp = Gauge(
    "dkg_state_timestamp", "When the DKG state last changed", ["beacon_id"],
    registry=GROUP)
reshare_state = Gauge(
    "reshare_state", "Reshare state", ["beacon_id"], registry=GROUP)
reshare_state_timestamp = Gauge(
    "reshare_state_timestamp", "When the reshare state last changed",
    ["beacon_id"], registry=GROUP)
drand_node_db = Gauge(
    "drand_node_db", "Storage engine in use", ["db"], registry=PRIVATE)
# restart observability (fleet harness, ISSUE 18): the gauge is this
# process's start stamp; the counter is seeded from the persisted
# restarts.json in the beacon folder so fleet runs assert restart counts
# from a metrics scrape instead of scraping logs
daemon_start_time_seconds = Gauge(
    "daemon_start_time_seconds", "Unix time this daemon process started",
    registry=PRIVATE)
daemon_restarts_total = Counter(
    "daemon_restarts_total",
    "Daemon starts beyond the first against this beacon folder "
    "(persisted across processes in <folder>/restarts.json)",
    registry=PRIVATE)
error_sending_partial = Counter(
    "error_sending_partial", "Failed partial beacon sends",
    ["beacon_id", "address"], registry=GROUP)
api_call_counter = Counter(
    "api_call_counter", "Public API calls", ["api_method"], registry=HTTP)
http_latency = Histogram(
    "http_response_latency_seconds", "REST edge latency", ["route"],
    registry=HTTP)
client_http_heartbeat = Counter(
    "client_http_heartbeat", "HTTP client watch liveness", ["url"],
    registry=CLIENT)
# Resilience layer (net/resilience.py): per-peer circuit breakers and the
# retry/deadline executor.  `resilience_breaker_state` is 0 closed / 1 open /
# 2 half-open; transitions carry the target state as a label so a scrape
# shows a peer getting quarantined and later probed back in.
breaker_state = Gauge(
    "resilience_breaker_state",
    "Per-peer circuit breaker state (0 closed, 1 open, 2 half-open)",
    ["scope", "address"], registry=GROUP)
breaker_transitions = Counter(
    "resilience_breaker_transitions_total",
    "Circuit breaker state transitions", ["scope", "address", "state"],
    registry=GROUP)
retries_total = Counter(
    "resilience_retries_total", "Retry attempts after a failed call",
    ["scope", "op"], registry=GROUP)
deadline_exceeded_total = Counter(
    "resilience_deadline_exceeded_total",
    "Operations abandoned because their overall budget was spent",
    ["scope", "op"], registry=GROUP)
# Chain-integrity subsystem (chain/integrity.py + tools/chain_doctor.py):
# the scan/quarantine/repair counters live next to the breaker metrics so
# one scrape answers both "is the network healthy" and "is the disk
# healthy".  `verifier` is host|device — the acceptance check that a scan
# really ran through the batched device path reads this label.
integrity_beacons_scanned = Counter(
    "chain_integrity_beacons_scanned_total",
    "Beacon rounds examined by integrity scans",
    ["beacon_id", "verifier", "trigger"], registry=GROUP)
integrity_corrupt_found = Counter(
    "chain_integrity_corrupt_found_total",
    "Corrupt/missing rounds flagged by integrity scans",
    ["beacon_id", "kind", "trigger"], registry=GROUP)
integrity_quarantined = Counter(
    "chain_integrity_quarantined_total",
    "Corrupt rounds deleted (quarantined) pending re-fetch",
    ["beacon_id"], registry=GROUP)
integrity_repaired = Counter(
    "chain_integrity_repaired_total",
    "Quarantined/missing rounds re-fetched, re-verified and restored",
    ["beacon_id"], registry=GROUP)
# TPU-specific: the device batch-verification pipeline.
batch_verify_rounds = Counter(
    "tpu_batch_verify_rounds_total", "Beacon rounds verified on device",
    ["scheme"], registry=PRIVATE)
batch_verify_seconds = Histogram(
    "tpu_batch_verify_seconds", "Device batch-verify wall time",
    ["scheme"], registry=PRIVATE)
# Resident verify service (crypto/verify_service.py): every verify
# consumer submits through one daemon-owned pipeline; these series answer
# "is coalescing working" (fill ratio up, dispatches well below requests)
# and "are live rounds starved" (live queue depth, preemption count).
verify_requests = Counter(
    "verify_service_requests_total",
    "Verification submissions accepted by the verify service",
    ["lane"], registry=PRIVATE)
verify_dispatches = Counter(
    "verify_service_dispatches_total",
    "Device/host dispatches issued by the verify service "
    "(group = the device group whose stream dispatched)",
    ["lane", "group"], registry=PRIVATE)
verify_queue_depth = Gauge(
    "verify_service_queue_depth",
    "Requests waiting in a verify-service lane", ["lane"],
    registry=PRIVATE)
verify_fill_ratio = Histogram(
    "verify_service_batch_fill_ratio",
    "Real lanes / padded width per coalesced dispatch",
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0),
    registry=PRIVATE)
verify_dispatch_latency = Histogram(
    "verify_service_dispatch_latency_seconds",
    "Verify-service latency split: phase=pack is host chunk-packing wall "
    "time (numpy wire parse + message packing; the term device "
    "hash-to-field removes the hashing from), phase=queue is "
    "submit-to-gather wait (coalescing window + lane contention, per "
    "batch), phase=device is dispatch-to-verdict wall time (per coalesced "
    "chunk) — occupancy regressions show up as device-time growth, "
    "overload as queue growth, host-bound packing as pack growth",
    ["lane", "phase"], registry=PRIVATE)
verify_inflight = Gauge(
    "verify_service_inflight_depth",
    "Dispatches currently enqueued ahead of the resolve point in the "
    "depth-k pipelined executor (0 when idle)",
    registry=PRIVATE)
verify_preemptions = Counter(
    "verify_service_preemptions_total",
    "Background batches preempted at a chunk boundary by live work",
    registry=PRIVATE)
# Device failure domain (crypto/verify_service.py watchdog/failover):
# `chain` is "<scheme>:<pk hex prefix>" — one series per backend handle.
# backend_state encodes the failover state machine (0 healthy, 1 suspect,
# 2 degraded, 3 probing); failovers count device→host swaps AND host→device
# re-promotions (the `direction` label tells them apart).
verify_failovers = Counter(
    "verify_service_failovers_total",
    "Verify-service backend swaps (device->host and re-promotions)",
    ["chain", "direction"], registry=PRIVATE)
verify_backend_state = Gauge(
    "verify_service_backend_state",
    "Verify backend failover state (0 healthy, 1 suspect, 2 degraded, "
    "3 probing); group = the chain's device-group affinity",
    ["chain", "group"], registry=PRIVATE)
# Multi-device scale-out (crypto/device_pool.py): one series per device
# group — how many devices it owns.  Group membership is static for a
# process; the gauge going to a new label set means the pool was rebuilt.
verify_group_devices = Gauge(
    "verify_service_group_devices",
    "Devices owned by each verify-service device group",
    ["group"], registry=PRIVATE)
verify_watchdog_trips = Counter(
    "verify_service_watchdog_trips_total",
    "Device dispatches abandoned after blowing their watchdog deadline",
    ["chain"], registry=PRIVATE)
verify_probe_latency = Histogram(
    "verify_service_probe_latency_seconds",
    "Canary probe dispatch latency on a degraded device backend",
    ["chain"], registry=PRIVATE)
# Serving-plane admission control (net/admission.py): every inbound
# surface (gRPC listener, REST edge, SyncChain streams) consults one
# controller.  `class` is critical|normal|sheddable, `decision` is
# admitted|shed; `admission_level` is the degradation-ladder rung
# (0 nominal, 1 shed-public, 2 pause-background, 3 shed-normal).
admission_requests = Counter(
    "admission_requests_total",
    "Serving-plane admission decisions",
    ["cls", "decision"], registry=PRIVATE)
admission_wait_seconds = Histogram(
    "admission_wait_seconds",
    "Admission queue wait per admitted request (the ladder's p99 signal)",
    ["cls"],
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 5.0),
    registry=PRIVATE)
admission_level = Gauge(
    "admission_level",
    "Degradation-ladder level (0 nominal .. 3 shed-normal)",
    registry=PRIVATE)
admission_inflight = Gauge(
    "admission_inflight",
    "Requests currently holding an admission token", ["cls"],
    registry=PRIVATE)
admission_background_paused = Gauge(
    "admission_background_paused",
    "1 while the ladder has paused the verify service's background lane",
    registry=PRIVATE)
# Integrity-scan resumability (chain/integrity.py ScanCheckpoint): where
# the latest scheduled scan resumed from (0 = scanned from genesis).
integrity_scan_resumed_from = Gauge(
    "chain_integrity_scan_resumed_from",
    "Round the latest integrity scan resumed from (0 = full rescan)",
    ["beacon_id"], registry=GROUP)
# Two-phase quarantine (chain/store.py tombstones): rows whose corrupt
# anchor was restored and whose own bytes then re-verified — promoted
# back from the quarantine side table instead of re-downloaded.
integrity_promoted = Counter(
    "chain_integrity_promoted_total",
    "Tombstoned rows re-verified against a restored anchor and promoted "
    "back without a peer re-fetch",
    ["beacon_id"], registry=GROUP)
# DKG/reshare lifecycle (core/dkg_journal.py): session outcomes, the
# live session's phase, and whether a reshare output sits staged on disk
# awaiting its transition round.  `result` is success|failed|aborted
# (aborted = a crash-restart found the session mid-flight).
dkg_sessions = Counter(
    "dkg_sessions_total",
    "DKG/reshare sessions by outcome",
    ["beacon_id", "kind", "result"], registry=GROUP)
dkg_phase_gauge = Gauge(
    "dkg_phase",
    "Live DKG session phase (0 idle, 1 setup, 2 deal, 3 response, "
    "4 justification, 5 adopt)",
    ["beacon_id"], registry=GROUP)
reshare_transition_pending = Gauge(
    "reshare_transition_pending",
    "1 while a reshare output is staged on disk awaiting its transition "
    "round (the pending-transition ledger is non-empty)",
    ["beacon_id"], registry=GROUP)

# Committee-scale engine (beacon/handel.py + crypto/dkg_device.py): the
# Handel overlay's session lifecycle, candidate verdicts, send volume and
# demotions — the observable difference between a converging tree and a
# wedged level.
handel_sessions = Counter(
    "handel_sessions_total",
    "Handel per-round sessions by outcome (complete | flushed)",
    ["beacon_id", "result"], registry=GROUP)
handel_candidates = Counter(
    "handel_candidates_total",
    "Incoming candidate aggregates by admission verdict",
    ["beacon_id", "verdict"], registry=GROUP)
handel_sends = Counter(
    "handel_sends_total", "Candidate aggregates sent to level peers",
    ["beacon_id"], registry=GROUP)
handel_demotions = Counter(
    "handel_demotions_total",
    "Peers demoted by the overlay (bad candidates past the limit)",
    ["beacon_id"], registry=GROUP)
handel_active_sessions = Gauge(
    "handel_active_sessions", "Live per-round Handel sessions",
    ["beacon_id"], registry=GROUP)

# Multi-tenant serving (core/tenancy.py, ISSUE 15): per-tenant admission
# decisions, measured device occupancy, and the quota level the
# enforcement planes act on (>= 1 means the tenant is over its
# device-time budget and sheds one degradation-ladder rung early).
tenant_requests = Counter(
    "tenant_requests_total",
    "Admission decisions attributed to a tenant",
    ["tenant", "decision"], registry=PRIVATE)
tenant_device_seconds = Counter(
    "tenant_device_seconds_total",
    "Verify-service device seconds attributed to a tenant (measured off "
    "the pack|queue|device latency split)",
    ["tenant"], registry=PRIVATE)
tenant_quota_level = Gauge(
    "tenant_quota_level",
    "Device-time quota level per tenant (used/allowed over the rolling "
    "window; >= 1 is over quota)",
    ["tenant"], registry=PRIVATE)

# Identity plane (net/identity.py + core/authz.py, ISSUE 19): mTLS cert
# lifecycle on the node-to-node planes and tenant-token verdicts on the
# admission edge.  Every rejected theft attempt lands here with a bounded
# reason label; `identity_rejections` is the series the StolenIdentity
# chaos scenario asserts on.
identity_cert_state = Gauge(
    "identity_cert_state",
    "Local mTLS cert expiry state (0 fresh, 1 grace, 2 expired; grace "
    "and expired both keep serving — rotation is overdue, not fatal)",
    registry=PRIVATE)
identity_cert_reloads = Counter(
    "identity_cert_reloads_total",
    "Cert-dir hot reloads by result (ok | error)",
    ["result"], registry=PRIVATE)
identity_rejections = Counter(
    "identity_rejections_total",
    "Authentication rejections by surface (grpc | rest | handel) and "
    "reason (token REASON_* values, or impersonation)",
    ["surface", "reason"], registry=PRIVATE)
authz_tokens = Counter(
    "authz_tokens_total",
    "Tenant-token lifecycle events (minted | revoked)",
    ["event"], registry=PRIVATE)


def scrape(which: str = "group") -> bytes:
    reg = {"private": PRIVATE, "http": HTTP, "group": GROUP,
           "client": CLIENT}[which]
    return generate_latest(reg)


def scrape_all() -> bytes:
    return b"".join(generate_latest(r)
                    for r in (PRIVATE, HTTP, GROUP, CLIENT))


class ThresholdMonitor:
    """Escalating alerts when partial-send failures approach the threshold
    (metrics/threshold_monitor.go:12-105)."""

    def __init__(self, beacon_id: str, log: Logger, threshold: int,
                 period: float = 60.0):
        self.beacon_id = beacon_id
        self.log = log
        self.threshold = threshold
        self.period = period
        self._failed: Dict[str, bool] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name=f"thr-mon-{self.beacon_id}")
            self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.period):
            with self._lock:
                failing = sorted(self._failed)
                self._failed = {}
                thr = self.threshold
            if len(failing) >= thr:
                self.log.error("failed connections crossed threshold in the "
                               "last minute", threshold=thr,
                               failures=len(failing), nodes=",".join(failing))
            elif len(failing) >= thr // 2:
                self.log.warn("failed connections crossed half threshold in "
                              "the last minute", threshold=thr,
                              failures=len(failing), nodes=",".join(failing))

    def report_failure(self, addr: str) -> None:
        # committee peers are bounded by the group file, but addresses
        # churn across reshares — cap the series set regardless
        error_sending_partial.labels(
            self.beacon_id,
            registered_label(addr, ns="peer-address", limit=256)).inc()
        with self._lock:
            self._failed[addr] = True

    def update_threshold(self, new_threshold: int) -> None:
        with self._lock:
            self.threshold = new_threshold

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None


class MetricsServer:
    """Plain-HTTP metrics endpoint with profiling and peer federation
    (metrics.go:365-399).

    Routes: `/metrics` (all registries), `/metrics/<registry>`,
    `/debug/gc` (manual GC trigger, metrics.go:390-393), `/debug/pprof`
    (thread stack dump — Python's nearest pprof analogue), and
    `/peer/<addr>/metrics` when a peer-handler is installed."""

    def __init__(self, port: int, host: str = "127.0.0.1",
                 peer_metrics: Optional[Callable[[str], bytes]] = None):
        import http.server

        self.peer_metrics = peer_metrics
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                try:
                    body, ctype = outer._route(self.path)
                except KeyError:
                    self.send_error(404)
                    return
                except Exception as e:   # peer unreachable etc.
                    self.send_error(502, explain=str(e))
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.end_headers()
                self.wfile.write(body)

        self.httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def _route(self, path: str):
        text = "text/plain; version=0.0.4"
        if path == "/metrics":
            return scrape_all(), text
        if path.startswith("/metrics/"):
            return scrape(path.split("/", 2)[2]), text
        if path == "/debug/gc":
            import gc
            gc.collect()
            return b"GC run\n", "text/plain"
        if path == "/debug/pprof":
            import sys
            import traceback
            frames = sys._current_frames()
            out = []
            for tid, frame in frames.items():
                out.append(f"Thread {tid}:\n"
                           + "".join(traceback.format_stack(frame)))
            return "\n".join(out).encode(), "text/plain"
        if path.startswith("/peer/") and path.endswith("/metrics") \
                and self.peer_metrics is not None:
            addr = path[len("/peer/"):-len("/metrics")]
            return self.peer_metrics(addr), text
        raise KeyError(path)

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="metrics-http")
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
