"""L7 client library (reference: client/, SURVEY.md §2.8).

Decorator pipeline: watch aggregator -> cache -> optimizing ->
verifying(per source) -> transport (gRPC / HTTP / relay)."""

from .aggregator import PollingWatcher, WatchAggregator
from .cache import CachingClient
from .client import (From, insecurely, new_client, with_auto_watch,
                     with_cache_size, with_chain_hash, with_chain_info,
                     with_full_chain_verification)
from .interface import Client, Result
from .optimizing import OptimizingClient
from .transports import GrpcTransport, HttpTransport
from .verify import VerifyingClient, verify_beacon_with_info

__all__ = [
    "Client", "Result", "new_client", "From", "with_chain_info",
    "with_chain_hash", "with_full_chain_verification", "with_cache_size",
    "with_auto_watch", "insecurely", "VerifyingClient", "CachingClient",
    "OptimizingClient", "WatchAggregator", "PollingWatcher",
    "GrpcTransport", "HttpTransport", "verify_beacon_with_info",
]
