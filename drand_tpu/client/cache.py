"""LRU caching client (reference: client/cache.go:13-119; LRU size 32)."""

import threading

from ..common import make_lock
from collections import OrderedDict
from typing import Iterator, Optional

from ..chain.info import Info
from .interface import Client, Result

CACHE_SIZE = 32


class CachingClient(Client):
    def __init__(self, inner: Client, size: int = CACHE_SIZE):
        self.inner = inner
        self.size = size
        self._cache: "OrderedDict[int, Result]" = OrderedDict()
        self._lock = make_lock()

    def get(self, round_: int = 0) -> Result:
        if round_ != 0:
            with self._lock:
                hit = self._cache.get(round_)
                if hit is not None:
                    self._cache.move_to_end(round_)
                    return hit
        result = self.inner.get(round_)
        self._remember(result)
        return result

    def _remember(self, result: Result) -> None:
        with self._lock:
            self._cache[result.round] = result
            self._cache.move_to_end(result.round)
            while len(self._cache) > self.size:
                self._cache.popitem(last=False)

    def watch(self, stop: Optional[threading.Event] = None
              ) -> Iterator[Result]:
        for result in self.inner.watch(stop):
            self._remember(result)
            yield result

    def info(self) -> Info:
        return self.inner.info()

    def close(self) -> None:
        self.inner.close()
