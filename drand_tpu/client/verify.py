"""Verifying client (reference: client/verify.go:13-199 — north-star loop #2).

Every result is verified against the chain info before it is returned.  In
strict chained mode the client walks from its last point of trust to the
requested round; where the reference verifies that walk one beacon at a
time on the CPU (verify.go:139-160), this client submits the span to the
resident verify service (`crypto/verify_service.py`), which coalesces it
with every other caller's work into device-sized batches — the
chain-catchup case BASELINE config 1 measures.
"""

import threading

from ..common import make_lock
from typing import Iterator, Optional

from ..chain.beacon import Beacon
from ..chain.info import Info
from ..crypto.schemes import scheme_from_name
from ..log import Logger
from .interface import Client, Result


def verify_beacon_with_info(info: Info, beacon: Beacon) -> bool:
    scheme = scheme_from_name(info.scheme)
    return scheme.verify_beacon(info.public_key, beacon.round,
                                beacon.previous_sig, beacon.signature)


class VerifyingClient(Client):
    def __init__(self, inner: Client, info: Optional[Info] = None,
                 strict: bool = False, log: Optional[Logger] = None):
        """`strict`: full chained-walk verification from the last verified
        point of trust (client.WithFullChainVerification)."""
        self.inner = inner
        self._info = info
        self.strict = strict
        self.log = (log or Logger()).named("verify")
        self._lock = make_lock()
        self._trusted: Optional[Beacon] = None   # last verified beacon
        self._scheme = None
        self._verifier = None

    # -- plumbing ------------------------------------------------------------

    def info(self) -> Info:
        if self._info is None:
            self._info = self.inner.info()
        return self._info

    # batches below this size verify on the host (native C path) — the
    # device pipeline's compile+dispatch only pays off on real sweeps
    DEVICE_MIN_BATCH = 64

    def _ensure_crypto(self):
        if self._verifier is None:
            # jax-free fallback handle behind the verify service's submit
            # API: single interactive gets ride the LIVE-priority host
            # path (a device round-trip and the jax import itself are
            # wrong for a one-beacon check)
            from ..crypto.verify_service import get_service
            info = self.info()
            self._scheme = scheme_from_name(info.scheme)
            self._verifier = get_service().handle(self._scheme,
                                                  info.public_key,
                                                  device=False)
            self._device_verifier = None
        return self._scheme, self._verifier

    def _sweep_verifier(self, n: int):
        """Device verify-service handle for large spans, host handle
        otherwise (the service coalesces sweep chunks from all clients
        into canonical padded batches)."""
        if n < self.DEVICE_MIN_BATCH:
            return self._verifier
        if self._device_verifier is None:
            from ..crypto.verify_service import get_service
            info = self.info()
            self._device_verifier = get_service().handle(self._scheme,
                                                         info.public_key)
        return self._device_verifier

    # -- Client --------------------------------------------------------------

    def get(self, round_: int = 0) -> Result:
        result = self.inner.get(round_)
        return self._verified(result)

    def watch(self, stop: Optional[threading.Event] = None
              ) -> Iterator[Result]:
        for result in self.inner.watch(stop):
            try:
                yield self._verified(result)
            except ValueError as e:
                self.log.warn("dropping unverifiable watch result",
                              round=result.round, err=str(e))

    def close(self) -> None:
        self.inner.close()

    # -- verification --------------------------------------------------------

    def _verified(self, result: Result) -> Result:
        scheme, verifier = self._ensure_crypto()
        beacon = result.beacon()
        if self.strict and scheme.chained:
            self._walk_to(beacon)
        elif not verifier.verify_batch(
                [beacon.round], [beacon.signature],
                [beacon.previous_sig], lane="live").all():
            raise ValueError(f"round {beacon.round}: invalid signature")
        with self._lock:
            if self._trusted is None or beacon.round > self._trusted.round:
                self._trusted = beacon
        # randomness is recomputed locally, never trusted from the wire
        # (verify.go:197)
        return Result.from_beacon(beacon)

    def _walk_to(self, target: Beacon) -> None:
        """Chained catch-up from the last point of trust, batch-verified
        (getTrustedPreviousSignature verify.go:109-171, redesigned as a
        device sweep)."""
        scheme, verifier = self._ensure_crypto()
        with self._lock:
            trusted = self._trusted
        if trusted is not None and target.round <= trusted.round:
            # historical round at or before the trust point: the chain walk
            # doesn't apply (it only extends the frontier); verify the
            # signature directly
            if not verifier.verify_batch([target.round], [target.signature],
                                         [target.previous_sig],
                                         lane="live").all():
                raise ValueError(
                    f"round {target.round}: invalid signature")
            return
        start = trusted.round + 1 if trusted is not None else 1
        span: list = []
        for r in range(start, target.round):
            span.append(self.inner.get(r).beacon())
        span.append(target)
        # host linkage prefix pass, then device signature sweep in chunks
        prev = trusted
        for b in span:
            if prev is not None and b.previous_sig != prev.signature:
                raise ValueError(f"round {b.round}: chain linkage broken")
            prev = b
        # ONE submission for the whole span: the verify service splits it
        # into canonical padded chunks itself (and overlaps host packing
        # with device compute), so the client no longer hand-rolls a
        # BATCH-sized dispatch loop
        # live lane like the sibling point checks: the walk serves an
        # interactive get(), so it preempts background scans rather than
        # queueing behind them
        sweeper = self._sweep_verifier(len(span))
        ok = sweeper.verify_batch(
            [b.round for b in span],
            [b.signature for b in span],
            [b.previous_sig for b in span], lane="live")
        if not ok.all():
            bad = [b.round for b, good in zip(span, ok) if not good]
            raise ValueError(f"invalid signatures at rounds {bad}")
