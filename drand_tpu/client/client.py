"""Client assembly (reference: client/client.go:21-345, makeClient :48-111).

    client = new_client(
        From(GrpcTransport("127.0.0.1:4444")),
        with_chain_info(info),          # or with_chain_hash("...")
        with_full_chain_verification(),
        with_cache_size(32),
        with_auto_watch(),
    )

Decorator order (outermost first): watch aggregator -> cache ->
optimizing -> verifying(per source) -> transport, exactly the reference
pipeline."""

from typing import List, Optional

from ..chain.info import Info
from .aggregator import WatchAggregator
from .cache import CachingClient
from .interface import Client, Result
from .optimizing import OptimizingClient
from .verify import VerifyingClient


class _Options:
    def __init__(self):
        self.sources: List[Client] = []
        self.info: Optional[Info] = None
        self.chain_hash: str = ""
        self.strict: bool = False
        self.cache_size: int = 32
        self.auto_watch: bool = False
        self.skip_verify: bool = False


def From(*sources: Client):
    def opt(o: _Options):
        o.sources.extend(sources)
    return opt


def with_chain_info(info: Info):
    def opt(o: _Options):
        o.info = info
    return opt


def with_chain_hash(hash_hex: str):
    def opt(o: _Options):
        o.chain_hash = hash_hex
    return opt


def with_full_chain_verification():
    def opt(o: _Options):
        o.strict = True
    return opt


def with_cache_size(n: int):
    def opt(o: _Options):
        o.cache_size = n
    return opt


def with_auto_watch():
    def opt(o: _Options):
        o.auto_watch = True
    return opt


def insecurely():
    """Skip verification (reference: client.Insecurely) — test/dev only."""
    def opt(o: _Options):
        o.skip_verify = True
    return opt


def new_client(*options) -> Client:
    o = _Options()
    for opt in options:
        opt(o)
    if not o.sources:
        raise ValueError("client needs at least one source (From(...))")

    # pin the root of trust: explicit info wins; else a chain hash is
    # checked against whatever the sources serve (client.go:279-316)
    info = o.info
    if info is None and o.chain_hash:
        for src in o.sources:
            try:
                candidate = src.info()
            except Exception:
                continue
            if candidate.hash_string() == o.chain_hash:
                info = candidate
                break
        if info is None:
            raise ValueError("no source matched the pinned chain hash")

    sources = o.sources
    if not o.skip_verify:
        sources = [VerifyingClient(s, info=info, strict=o.strict)
                   for s in sources]
    inner: Client = (sources[0] if len(sources) == 1
                     else OptimizingClient(sources))
    if isinstance(inner, OptimizingClient):
        inner.start_speed_tests()
    inner = CachingClient(inner, o.cache_size)
    return WatchAggregator(inner, auto_watch=o.auto_watch)


__all__ = ["new_client", "From", "with_chain_info", "with_chain_hash",
           "with_full_chain_verification", "with_cache_size",
           "with_auto_watch", "insecurely", "Client", "Result"]
