"""Optimizing client (reference: client/optimizing.go:36-638).

Tracks per-source latency, re-probing every `speed_test_interval`; `get`
races the top-2 fastest sources with a stagger and returns the first
success; `watch` follows the fastest source and fails over on error.

Ranking is breaker-aware (net/resilience.py): a source that keeps failing
trips its circuit breaker and sinks to the back of the ranking until its
cooldown elapses, regardless of how fast it was when it last answered —
latency measures the happy path, the breaker remembers the sad one.
"""

import threading

from ..common import make_lock
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from typing import Iterator, List, Optional

from ..chain.info import Info
from ..log import Logger
from ..net.resilience import ResiliencePolicy
from .interface import Client, Result

SPEED_TEST_INTERVAL = 300.0     # optimizing.go: 5 min
RACE_STAGGER = 0.5              # head start for the fastest source (s)
DEFAULT_TIMEOUT = 5.0


class _Source:
    def __init__(self, client: Client, key: str):
        self.client = client
        self.key = key              # breaker key for this transport
        self.latency = float("inf")

    def probe(self) -> None:
        t0 = time.perf_counter()
        try:
            self.client.get(0)
            self.latency = time.perf_counter() - t0
        except Exception:
            self.latency = float("inf")


class OptimizingClient(Client):
    def __init__(self, sources: List[Client],
                 speed_test_interval: float = SPEED_TEST_INTERVAL,
                 log: Optional[Logger] = None,
                 resilience: Optional[ResiliencePolicy] = None):
        if not sources:
            raise ValueError("optimizing client needs at least one source")
        self.sources = [_Source(c, f"source-{i}")
                        for i, c in enumerate(sources)]
        self.log = (log or Logger()).named("optimizing")
        self.resilience = resilience or ResiliencePolicy(scope="client")
        self._stop = threading.Event()
        self._lock = make_lock()
        self._interval = speed_test_interval
        self._prober: Optional[threading.Thread] = None

    def start_speed_tests(self) -> None:
        """Periodic latency ranking (optimizing.go testSpeed)."""
        if self._prober is None:
            self._prober = threading.Thread(target=self._probe_loop,
                                            daemon=True, name="speed-test")
            self._prober.start()

    def _probe_loop(self) -> None:
        while not self._stop.is_set():
            for s in self.sources:
                if self._stop.is_set():
                    return
                s.probe()
            self._stop.wait(self._interval)

    def _ranked(self) -> List[_Source]:
        """Closed-breaker sources first (then by latency); quarantined ones
        last, but never dropped — they are the fallback of last resort."""
        pref = self.resilience.breakers.preference
        with self._lock:
            return sorted(self.sources,
                          key=lambda s: (pref(s.key), s.latency))

    def _record(self, src: _Source, ok: bool) -> None:
        br = self.resilience.breaker(src.key)
        br.record_success() if ok else br.record_failure()

    # -- Client --------------------------------------------------------------

    def get(self, round_: int = 0) -> Result:
        """Race the two fastest sources with a stagger
        (optimizing.go:233-266,287-350)."""
        ranked = self._ranked()
        racers = ranked[:2]
        errors: List[Exception] = []
        with ThreadPoolExecutor(max_workers=len(racers)) as pool:
            futures = {}
            for i, src in enumerate(racers):
                if i > 0:
                    done, _ = wait(futures, timeout=RACE_STAGGER,
                                   return_when=FIRST_COMPLETED)
                    for f in done:
                        # pop: a failure resolved here must not be counted
                        # against the breaker again by the final loop below
                        f_src = futures.pop(f)
                        try:
                            # f is in the `done` set of wait() above —
                            # result() cannot block
                            # tpu-vet: disable=wait
                            result = f.result()
                            self._record(f_src, ok=True)
                            return result
                        except Exception as e:
                            self._record(f_src, ok=False)
                            errors.append(e)
                futures[pool.submit(src.client.get, round_)] = src
            for f, src in futures.items():
                try:
                    result = f.result(timeout=DEFAULT_TIMEOUT)
                    src.latency = min(src.latency, DEFAULT_TIMEOUT)
                    self._record(src, ok=True)
                    return result
                except Exception as e:
                    src.latency = float("inf")
                    self._record(src, ok=False)
                    errors.append(e)
        raise errors[-1] if errors else RuntimeError("no source succeeded")

    def watch(self, stop: Optional[threading.Event] = None
              ) -> Iterator[Result]:
        """Follow the fastest source; on stream failure fail over to the
        next (optimizing.go watch failover)."""
        stop = stop or self._stop
        last_round = 0
        while not stop.is_set():
            progressed = False
            for src in self._ranked():
                src_progressed = False
                try:
                    for result in src.client.watch(stop):
                        if result.round > last_round:
                            last_round = result.round
                            if not src_progressed:
                                src_progressed = True
                                self._record(src, ok=True)
                            progressed = True
                            yield result
                        if stop.is_set():
                            return
                except Exception as e:
                    self._record(src, ok=False)
                    self.log.warn("watch source failed; failing over",
                                  err=str(e))
                    continue
            if not progressed:
                # every source failed without yielding: back off briefly
                if stop.wait(1.0):
                    return

    def info(self) -> Info:
        err: Optional[Exception] = None
        for src in self._ranked():
            try:
                return src.client.info()
            except Exception as e:
                err = e
        raise err or RuntimeError("no source for info")

    def close(self) -> None:
        self._stop.set()
        prober, self._prober = self._prober, None
        if prober is not None:
            prober.join(timeout=2)
        for s in self.sources:
            s.client.close()
