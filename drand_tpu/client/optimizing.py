"""Optimizing client (reference: client/optimizing.go:36-638).

Tracks per-source latency, re-probing every `speed_test_interval`; `get`
races the top-2 fastest sources with a stagger and returns the first
success; `watch` follows the fastest source and fails over on error.
"""

import threading
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from typing import Iterator, List, Optional

from ..chain.info import Info
from ..log import Logger
from .interface import Client, Result

SPEED_TEST_INTERVAL = 300.0     # optimizing.go: 5 min
RACE_STAGGER = 0.5              # head start for the fastest source (s)
DEFAULT_TIMEOUT = 5.0


class _Source:
    def __init__(self, client: Client):
        self.client = client
        self.latency = float("inf")

    def probe(self) -> None:
        t0 = time.perf_counter()
        try:
            self.client.get(0)
            self.latency = time.perf_counter() - t0
        except Exception:
            self.latency = float("inf")


class OptimizingClient(Client):
    def __init__(self, sources: List[Client],
                 speed_test_interval: float = SPEED_TEST_INTERVAL,
                 log: Optional[Logger] = None):
        if not sources:
            raise ValueError("optimizing client needs at least one source")
        self.sources = [_Source(c) for c in sources]
        self.log = (log or Logger()).named("optimizing")
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._interval = speed_test_interval
        self._prober: Optional[threading.Thread] = None

    def start_speed_tests(self) -> None:
        """Periodic latency ranking (optimizing.go testSpeed)."""
        if self._prober is None:
            self._prober = threading.Thread(target=self._probe_loop,
                                            daemon=True, name="speed-test")
            self._prober.start()

    def _probe_loop(self) -> None:
        while not self._stop.is_set():
            for s in self.sources:
                if self._stop.is_set():
                    return
                s.probe()
            self._stop.wait(self._interval)

    def _ranked(self) -> List[_Source]:
        with self._lock:
            return sorted(self.sources, key=lambda s: s.latency)

    # -- Client --------------------------------------------------------------

    def get(self, round_: int = 0) -> Result:
        """Race the two fastest sources with a stagger
        (optimizing.go:233-266,287-350)."""
        ranked = self._ranked()
        racers = ranked[:2]
        errors: List[Exception] = []
        with ThreadPoolExecutor(max_workers=len(racers)) as pool:
            futures = {}
            for i, src in enumerate(racers):
                if i > 0:
                    done, _ = wait(futures, timeout=RACE_STAGGER,
                                   return_when=FIRST_COMPLETED)
                    for f in done:
                        try:
                            return f.result()
                        except Exception as e:
                            errors.append(e)
                futures[pool.submit(src.client.get, round_)] = src
            for f, src in futures.items():
                try:
                    result = f.result(timeout=DEFAULT_TIMEOUT)
                    src.latency = min(src.latency, DEFAULT_TIMEOUT)
                    return result
                except Exception as e:
                    src.latency = float("inf")
                    errors.append(e)
        raise errors[-1] if errors else RuntimeError("no source succeeded")

    def watch(self, stop: Optional[threading.Event] = None
              ) -> Iterator[Result]:
        """Follow the fastest source; on stream failure fail over to the
        next (optimizing.go watch failover)."""
        stop = stop or self._stop
        last_round = 0
        while not stop.is_set():
            progressed = False
            for src in self._ranked():
                try:
                    for result in src.client.watch(stop):
                        if result.round > last_round:
                            last_round = result.round
                            progressed = True
                            yield result
                        if stop.is_set():
                            return
                except Exception as e:
                    self.log.warn("watch source failed; failing over",
                                  err=str(e))
                    continue
            if not progressed:
                # every source failed without yielding: back off briefly
                if stop.wait(1.0):
                    return

    def info(self) -> Info:
        err: Optional[Exception] = None
        for src in self._ranked():
            try:
                return src.client.info()
            except Exception as e:
                err = e
        raise err or RuntimeError("no source for info")

    def close(self) -> None:
        self._stop.set()
        for s in self.sources:
            s.client.close()
