"""Watch aggregator + polling watcher.

`WatchAggregator` (client/aggregator.go:26-219): fans ONE upstream watch
out to any number of subscribers, with auto-restart when the upstream
stream dies.  `PollingWatcher` (client/poll.go:17-62): synthesizes a watch
for transports with no streaming (plain HTTP) by polling `get` aligned to
the round schedule.
"""

import queue
import random
import threading

from ..common import make_lock
from typing import Iterator, List, Optional

from ..beacon.clock import Clock, RealClock
from ..chain.info import Info
from ..chain.timing import time_of_round
from ..net.resilience import BackoffPolicy
from .interface import Client, Result


class WatchAggregator(Client):
    def __init__(self, inner: Client, auto_watch: bool = False,
                 backoff: Optional[BackoffPolicy] = None,
                 rng: Optional[random.Random] = None):
        self.inner = inner
        # reconnect schedule for a dying upstream: exponential backoff with
        # full jitter (was a fixed 1s — a flapping upstream got hammered at
        # 1 Hz by every aggregator in the fleet simultaneously)
        self.backoff = backoff or BackoffPolicy(base=0.5, cap=15.0)
        self.rng = rng or random.Random()
        self._consecutive_failures = 0
        self._subs: List[queue.Queue] = []
        self._lock = make_lock()
        self._stop = threading.Event()
        self._pump: Optional[threading.Thread] = None
        if auto_watch:
            self._ensure_pump()

    def _ensure_pump(self) -> None:
        with self._lock:
            if self._pump is None:
                self._pump = threading.Thread(target=self._run, daemon=True,
                                              name="watch-aggregator")
                self._pump.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                for result in self.inner.watch(self._stop):
                    self._consecutive_failures = 0   # stream is live again
                    with self._lock:
                        subs = list(self._subs)
                    for q in subs:
                        try:
                            q.put_nowait(result)
                        except queue.Full:
                            pass
                    if self._stop.is_set():
                        return
            except Exception:
                pass
            # upstream died: retry with jittered backoff (aggregator.go
            # restarts the watch; the schedule grows while it keeps dying)
            delay = max(self.backoff.delay(self._consecutive_failures,
                                           self.rng), 0.2)
            self._consecutive_failures += 1
            self._stop.wait(delay)

    def get(self, round_: int = 0) -> Result:
        return self.inner.get(round_)

    def watch(self, stop: Optional[threading.Event] = None
              ) -> Iterator[Result]:
        self._ensure_pump()
        q: queue.Queue = queue.Queue(maxsize=32)
        with self._lock:
            self._subs.append(q)
        try:
            while not self._stop.is_set() \
                    and not (stop is not None and stop.is_set()):
                try:
                    yield q.get(timeout=0.2)
                except queue.Empty:
                    continue
        finally:
            with self._lock:
                self._subs.remove(q)

    def info(self) -> Info:
        return self.inner.info()

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            pump, self._pump = self._pump, None
        if pump is not None:
            # the pump wakes from inner.watch/backoff on the stop event;
            # bounded join so a wedged upstream can't hang close()
            pump.join(timeout=2)
        self.inner.close()


class PollingWatcher(Client):
    """Wraps a get-only transport; watch polls once per round, aligned to
    the round schedule (client/poll.go:17-62)."""

    def __init__(self, inner: Client, clock: Optional[Clock] = None):
        self.inner = inner
        self.clock = clock or RealClock()

    def get(self, round_: int = 0) -> Result:
        return self.inner.get(round_)

    def info(self) -> Info:
        return self.inner.info()

    def watch(self, stop: Optional[threading.Event] = None
              ) -> Iterator[Result]:
        stop = stop or threading.Event()
        info = self.info()
        last = 0
        while not stop.is_set():
            try:
                result = self.inner.get(0)
                if result.round > last:
                    last = result.round
                    yield result
            except Exception:
                pass
            # sleep to just after the next round boundary ON the injected
            # clock — wait_until, not stop.wait(delay), so a FakeClock
            # test steps the schedule without real sleeps.  The floor of
            # now()+0.1 keeps a lagging watcher from busy-polling when
            # the boundary is already behind us; the one-period cap keeps
            # a bogus future round from the server (inflated `last`) from
            # parking the watcher past the next boundary it must re-check.
            nxt = time_of_round(info.period, info.genesis_time, last + 1)
            now = self.clock.now()
            deadline = min(max(nxt, now) + 0.1, now + info.period)
            if not self.clock.wait_until(deadline, stop):
                return

    def close(self) -> None:
        self.inner.close()
