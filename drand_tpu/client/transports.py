"""Client transports: gRPC (client/grpc/client.go:24-146) and REST/HTTP
(client/http/http.go:35-396)."""

import json
import threading
import urllib.request
from typing import Iterator, Optional

from ..chain.beacon import Beacon
from ..chain.info import Info
from ..metrics import client_http_heartbeat, registered_label
from ..net import Peer, ProtocolClient
from ..net import convert
from .interface import Client, Result


class GrpcTransport(Client):
    """`client.Client` over the Public gRPC service."""

    def __init__(self, address: str, beacon_id: str = "", tls: bool = False,
                 client: Optional[ProtocolClient] = None):
        self.peer = Peer(address, tls)
        self.beacon_id = beacon_id
        self.client = client or ProtocolClient()
        self._own_client = client is None

    def get(self, round_: int = 0) -> Result:
        resp = self.client.public_rand(self.peer, round_, self.beacon_id)
        return Result.from_beacon(convert.rand_to_beacon(resp))

    def watch(self, stop: Optional[threading.Event] = None
              ) -> Iterator[Result]:
        stop = stop or threading.Event()
        for resp in self.client.public_rand_stream(self.peer, 0,
                                                   self.beacon_id):
            if stop.is_set():
                return
            yield Result.from_beacon(convert.rand_to_beacon(resp))

    def info(self) -> Info:
        return convert.proto_to_info(
            self.client.chain_info(self.peer, self.beacon_id))

    def close(self) -> None:
        if self._own_client:
            self.client.close()


class HttpTransport(Client):
    """REST consumer of the L8 edge: `/info`, `/public/{round|latest}`
    (client/http/http.go; validates randomness == SHA256(sig),
    http.go:341-354).  Watch is by polling (wrap in PollingWatcher)."""

    def __init__(self, base_url: str, chain_hash: str = "",
                 timeout: float = 10.0):
        self.base = base_url.rstrip("/")
        if chain_hash:
            self.base = f"{self.base}/{chain_hash}"
        self.timeout = timeout
        self._info: Optional[Info] = None

    def _fetch(self, path: str) -> dict:
        url = f"{self.base}{path}"
        with urllib.request.urlopen(url, timeout=self.timeout) as r:
            # endpoints come from operator config, but cap the series set
            # anyway — a misconfigured rotating gateway URL must not mint
            # a fresh time series per request
            client_http_heartbeat.labels(
                registered_label(self.base, ns="client-endpoint",
                                 limit=16)).inc()
            return json.loads(r.read())

    def get(self, round_: int = 0) -> Result:
        path = f"/public/{round_}" if round_ else "/public/latest"
        obj = self._fetch(path)
        beacon = Beacon(
            round=int(obj["round"]),
            signature=bytes.fromhex(obj["signature"]),
            previous_sig=(bytes.fromhex(obj["previous_signature"])
                          if obj.get("previous_signature") else None))
        rand = bytes.fromhex(obj.get("randomness", ""))
        if rand and rand != beacon.randomness():
            raise ValueError("server randomness != SHA256(signature)")
        return Result.from_beacon(beacon)

    def watch(self, stop: Optional[threading.Event] = None
              ) -> Iterator[Result]:
        from .aggregator import PollingWatcher
        return PollingWatcher(self).watch(stop)

    def info(self) -> Info:
        if self._info is None:
            self._info = Info.from_json(
                json.dumps(self._fetch("/info")).encode())
        return self._info
