"""Client interfaces (reference: client/interface.go:13-41).

A `Client` fetches verified randomness from one or more drand nodes.
`Result` carries one round's randomness; `watch()` yields results as new
rounds land.
"""

import threading
from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import Iterator, Optional

from ..chain.beacon import Beacon
from ..chain.info import Info
from ..chain.timing import current_round


@dataclass(frozen=True)
class Result:
    round: int
    randomness: bytes
    signature: bytes
    previous_signature: Optional[bytes] = None

    @classmethod
    def from_beacon(cls, b: Beacon) -> "Result":
        return cls(round=b.round, randomness=b.randomness(),
                   signature=b.signature, previous_signature=b.previous_sig)

    def beacon(self) -> Beacon:
        return Beacon(round=self.round, signature=self.signature,
                      previous_sig=self.previous_signature)


class Client(ABC):
    @abstractmethod
    def get(self, round_: int = 0) -> Result:
        """Fetch one round (0 = latest)."""

    @abstractmethod
    def watch(self, stop: Optional[threading.Event] = None
              ) -> Iterator[Result]:
        """Yield results as rounds are produced."""

    @abstractmethod
    def info(self) -> Info:
        """The chain info (root of trust)."""

    def round_at(self, t: float) -> int:
        info = self.info()
        return current_round(int(t), info.period, info.genesis_time)

    def close(self) -> None:
        pass
