"""Structured, named, leveled logging (reference: log/log.go:18-34's zap
SugaredLogger wrapper; named hierarchies like
`daemon.Named(addr).Named(beaconID).Named(index)` core/drand_beacon.go:155).

Console or JSON output; bulk-operation rate limiting mirrors the reference's
`LogsToSkip=300` (common/beacon.go:21, sync_manager.go:391-401).
"""

import json
import logging
import sys
import threading
import time
from typing import Any, Optional

LOGS_TO_SKIP = 300   # bulk ops: emit 1 of every N (common/beacon.go:21)

_root_config = {"json": False, "level": logging.INFO, "stream": None}
_config_lock = threading.Lock()


def configure(level: str = "info", json_output: bool = False,
              stream=None) -> None:
    """Process-wide logging config (CLI --verbose / --json flags)."""
    with _config_lock:
        _root_config["level"] = getattr(logging, level.upper(), logging.INFO)
        _root_config["json"] = json_output
        _root_config["stream"] = stream


class Logger:
    """Named logger with key-value structured fields."""

    def __init__(self, name: str = "drand", fields: Optional[dict] = None):
        self.name = name
        self.fields = fields or {}
        self._skip_counter = 0
        self._skip_lock = threading.Lock()

    def named(self, suffix: str) -> "Logger":
        return Logger(f"{self.name}.{suffix}", dict(self.fields))

    def with_fields(self, **fields: Any) -> "Logger":
        merged = dict(self.fields)
        merged.update(fields)
        return Logger(self.name, merged)

    # -- emit ----------------------------------------------------------------

    def _emit(self, level: int, msg: str, kv: dict) -> None:
        if level < _root_config["level"]:
            return
        stream = _root_config["stream"] or sys.stderr
        fields = dict(self.fields)
        fields.update(kv)
        if _root_config["json"]:
            rec = {"ts": time.time(), "level": logging.getLevelName(level),
                   "logger": self.name, "msg": msg, **fields}
            print(json.dumps(rec, default=str), file=stream)
        else:
            kvs = " ".join(f"{k}={v}" for k, v in fields.items())
            ts = time.strftime("%H:%M:%S")
            lvl = logging.getLevelName(level)[:4]
            print(f"{ts} {lvl} [{self.name}] {msg}"
                  + (f" {kvs}" if kvs else ""), file=stream)

    def debug(self, msg: str, **kv):
        self._emit(logging.DEBUG, msg, kv)

    def info(self, msg: str, **kv):
        self._emit(logging.INFO, msg, kv)

    def warn(self, msg: str, **kv):
        self._emit(logging.WARNING, msg, kv)

    def error(self, msg: str, **kv):
        self._emit(logging.ERROR, msg, kv)

    def rate_limited_info(self, msg: str, **kv):
        """Emit 1 of every LOGS_TO_SKIP calls (bulk sync loops)."""
        with self._skip_lock:
            self._skip_counter += 1
            if self._skip_counter % LOGS_TO_SKIP != 1:
                return
        self._emit(logging.INFO, msg, kv)


DEFAULT = Logger()
