"""Regenerate drand_pb2.py from drand.proto.

Run: `python -m drand_tpu.protos.gen`.  Only message codegen is used
(`protoc --python_out`); gRPC service plumbing is hand-built from the
message classes in drand_tpu/net/rpc.py (no grpc protoc plugin in this
environment).
"""

import pathlib
import subprocess
import sys

HERE = pathlib.Path(__file__).resolve().parent


def main() -> int:
    proc = subprocess.run(
        ["protoc", f"--proto_path={HERE}", f"--python_out={HERE}",
         str(HERE / "drand.proto")],
        capture_output=True, text=True)
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr)
        return proc.returncode
    print("wrote", HERE / "drand_pb2.py")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
