# -*- coding: utf-8 -*-
# Generated protocol buffer code (drand_tpu/protos/gen.py, or the
# in-repo descriptor appender when protoc is unavailable).  DO NOT EDIT!
# source: drand.proto
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor as _descriptor
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database
# @@protoc_insertion_point(imports)

_sym_db = _symbol_database.Default()




DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile(b'\n\x0bdrand.proto\x12\x05drand":\n\x0bNodeVersion\x12\r\n\x05major\x18\x01 \x01(\r\x12\r\n\x05minor\x18\x02 \x01(\r\x12\r\n\x05patch\x18\x03 \x01(\r"Z\n\x08Metadata\x12(\n\x0cnode_version\x18\x01 \x01(\x0b2\x12.drand.NodeVersion\x12\x10\n\x08beaconID\x18\x02 \x01(\t\x12\x12\n\nchain_hash\x18\x03 \x01(\x0c"*\n\x05Empty\x12!\n\x08metadata\x18\x01 \x01(\x0b2\x0f.drand.Metadata"H\n\x08Identity\x12\x0f\n\x07address\x18\x01 \x01(\t\x12\x0b\n\x03key\x18\x02 \x01(\x0c\x12\x0b\n\x03tls\x18\x03 \x01(\x08\x12\x11\n\tsignature\x18\x04 \x01(\x0c";\n\tGroupNode\x12\x1f\n\x06public\x18\x01 \x01(\x0b2\x0f.drand.Identity\x12\r\n\x05index\x18\x02 \x01(\r"\xf5\x01\n\x0bGroupPacket\x12\x1f\n\x05nodes\x18\x01 \x03(\x0b2\x10.drand.GroupNode\x12\x11\n\tthreshold\x18\x02 \x01(\r\x12\x0e\n\x06period\x18\x03 \x01(\r\x12\x14\n\x0cgenesis_time\x18\x04 \x01(\x04\x12\x17\n\x0ftransition_time\x18\x05 \x01(\x04\x12\x14\n\x0cgenesis_seed\x18\x06 \x01(\x0c\x12\x10\n\x08dist_key\x18\x07 \x03(\x0c\x12\x16\n\x0ecatchup_period\x18\x08 \x01(\r\x12\x10\n\x08schemeID\x18\t \x01(\t\x12!\n\x08metadata\x18\n \x01(\x0b2\x0f.drand.Metadata"4\n\x0fIdentityRequest\x12!\n\x08metadata\x18\x01 \x01(\x0b2\x0f.drand.Metadata"\x87\x01\n\x10IdentityResponse\x12\x0f\n\x07address\x18\x01 \x01(\t\x12\x0b\n\x03key\x18\x02 \x01(\x0c\x12\x0b\n\x03tls\x18\x03 \x01(\x08\x12\x11\n\tsignature\x18\x04 \x01(\x0c\x12!\n\x08metadata\x18\x05 \x01(\x0b2\x0f.drand.Metadata\x12\x12\n\nschemeName\x18\x06 \x01(\t"\x86\x01\n\x0fSignalDKGPacket\x12\x1d\n\x04node\x18\x01 \x01(\x0b2\x0f.drand.Identity\x12\x14\n\x0csecret_proof\x18\x02 \x01(\x0c\x12\x1b\n\x13previous_group_hash\x18\x03 \x01(\x0c\x12!\n\x08metadata\x18\x04 \x01(\x0b2\x0f.drand.Metadata"\xb1\x01\n\rDKGInfoPacket\x12%\n\tnew_group\x18\x01 \x01(\x0b2\x12.drand.GroupPacket\x12\x14\n\x0csecret_proof\x18\x02 \x01(\x0c\x12\x13\n\x0bdkg_timeout\x18\x03 \x01(\r\x12\x11\n\tsignature\x18\x04 \x01(\x0c\x12!\n\x08metadata\x18\x05 \x01(\x0b2\x0f.drand.Metadata\x12\x18\n\x10kickoff_grace_ms\x18\x06 \x01(\r"x\n\x13PartialBeaconPacket\x12\r\n\x05round\x18\x01 \x01(\x04\x12\x1a\n\x12previous_signature\x18\x02 \x01(\x0c\x12\x13\n\x0bpartial_sig\x18\x03 \x01(\x0c\x12!\n\x08metadata\x18\x04 \x01(\x0b2\x0f.drand.Metadata"9\n\tDealShare\x12\x13\n\x0bshare_index\x18\x01 \x01(\r\x12\x17\n\x0fencrypted_share\x18\x02 \x01(\x0c"{\n\nDealBundle\x12\x14\n\x0cdealer_index\x18\x01 \x01(\r\x12\x0f\n\x07commits\x18\x02 \x03(\x0c\x12\x1f\n\x05deals\x18\x03 \x03(\x0b2\x10.drand.DealShare\x12\x12\n\nsession_id\x18\x04 \x01(\x0c\x12\x11\n\tsignature\x18\x05 \x01(\x0c"4\n\x0cDealerStatus\x12\x14\n\x0cdealer_index\x18\x01 \x01(\r\x12\x0e\n\x06status\x18\x02 \x01(\x08"t\n\x0eResponseBundle\x12\x13\n\x0bshare_index\x18\x01 \x01(\r\x12&\n\tresponses\x18\x02 \x03(\x0b2\x13.drand.DealerStatus\x12\x12\n\nsession_id\x18\x03 \x01(\x0c\x12\x11\n\tsignature\x18\x04 \x01(\x0c"8\n\x12JustificationShare\x12\x13\n\x0bshare_index\x18\x01 \x01(\r\x12\r\n\x05share\x18\x02 \x01(\x0c"\x85\x01\n\x13JustificationBundle\x12\x14\n\x0cdealer_index\x18\x01 \x01(\r\x121\n\x0ejustifications\x18\x02 \x03(\x0b2\x19.drand.JustificationShare\x12\x12\n\nsession_id\x18\x03 \x01(\x0c\x12\x11\n\tsignature\x18\x04 \x01(\x0c"\xbb\x01\n\tDKGBundle\x12!\n\x04deal\x18\x01 \x01(\x0b2\x11.drand.DealBundleH\x00\x12)\n\x08response\x18\x02 \x01(\x0b2\x15.drand.ResponseBundleH\x00\x123\n\rjustification\x18\x03 \x01(\x0b2\x1a.drand.JustificationBundleH\x00\x12!\n\x08metadata\x18\x04 \x01(\x0b2\x0f.drand.MetadataB\x08\n\x06bundle"M\n\tDKGPacket\x12\x1d\n\x03dkg\x18\x01 \x01(\x0b2\x10.drand.DKGBundle\x12!\n\x08metadata\x18\x02 \x01(\x0b2\x0f.drand.Metadata"D\n\x0bSyncRequest\x12\x12\n\nfrom_round\x18\x01 \x01(\x04\x12!\n\x08metadata\x18\x02 \x01(\x0b2\x0f.drand.Metadata"o\n\x0cBeaconPacket\x12\x1a\n\x12previous_signature\x18\x01 \x01(\x0c\x12\r\n\x05round\x18\x02 \x01(\x04\x12\x11\n\tsignature\x18\x03 \x01(\x0c\x12!\n\x08metadata\x18\x04 \x01(\x0b2\x0f.drand.Metadata"E\n\x11PublicRandRequest\x12\r\n\x05round\x18\x01 \x01(\x04\x12!\n\x08metadata\x18\x02 \x01(\x0b2\x0f.drand.Metadata"\x89\x01\n\x12PublicRandResponse\x12\r\n\x05round\x18\x01 \x01(\x04\x12\x11\n\tsignature\x18\x02 \x01(\x0c\x12\x1a\n\x12previous_signature\x18\x03 \x01(\x0c\x12\x12\n\nrandomness\x18\x04 \x01(\x0c\x12!\n\x08metadata\x18\x05 \x01(\x0b2\x0f.drand.Metadata"5\n\x10ChainInfoRequest\x12!\n\x08metadata\x18\x01 \x01(\x0b2\x0f.drand.Metadata"\xa2\x01\n\x0fChainInfoPacket\x12\x12\n\npublic_key\x18\x01 \x01(\x0c\x12\x0e\n\x06period\x18\x02 \x01(\r\x12\x14\n\x0cgenesis_time\x18\x03 \x01(\x03\x12\x0c\n\x04hash\x18\x04 \x01(\x0c\x12\x12\n\ngroup_hash\x18\x05 \x01(\x0c\x12\x10\n\x08schemeID\x18\x06 \x01(\t\x12!\n\x08metadata\x18\x07 \x01(\x0b2\x0f.drand.Metadata"0\n\x0bHomeRequest\x12!\n\x08metadata\x18\x01 \x01(\x0b2\x0f.drand.Metadata"A\n\x0cHomeResponse\x12\x0e\n\x06status\x18\x01 \x01(\t\x12!\n\x08metadata\x18\x02 \x01(\x0b2\x0f.drand.Metadata"-\n\rStatusAddress\x12\x0f\n\x07address\x18\x01 \x01(\t\x12\x0b\n\x03tls\x18\x02 \x01(\x08"\\\n\rStatusRequest\x12(\n\ncheck_conn\x18\x01 \x03(\x0b2\x14.drand.StatusAddress\x12!\n\x08metadata\x18\x02 \x01(\x0b2\x0f.drand.Metadata"\x1f\n\rDkgStatusPart\x12\x0e\n\x06status\x18\x01 \x01(\r"r\n\x10BeaconStatusPart\x12\x0e\n\x06status\x18\x01 \x01(\r\x12\x12\n\nis_running\x18\x02 \x01(\x08\x12\x12\n\nis_stopped\x18\x03 \x01(\x08\x12\x12\n\nis_started\x18\x04 \x01(\x08\x12\x12\n\nis_serving\x18\x05 \x01(\x08"L\n\x14ChainStoreStatusPart\x12\x10\n\x08is_empty\x18\x01 \x01(\x08\x12\x12\n\nlast_round\x18\x02 \x01(\x04\x12\x0e\n\x06length\x18\x03 \x01(\x04"\xa6\x02\n\x0eStatusResponse\x12!\n\x03dkg\x18\x01 \x01(\x0b2\x14.drand.DkgStatusPart\x12%\n\x07reshare\x18\x02 \x01(\x0b2\x14.drand.DkgStatusPart\x12\'\n\x06beacon\x18\x03 \x01(\x0b2\x17.drand.BeaconStatusPart\x120\n\x0bchain_store\x18\x04 \x01(\x0b2\x1b.drand.ChainStoreStatusPart\x12;\n\x0bconnections\x18\x05 \x03(\x0b2&.drand.StatusResponse.ConnectionsEntry\x1a2\n\x10ConnectionsEntry\x12\x0b\n\x03key\x18\x01 \x01(\t\x12\r\n\x05value\x18\x02 \x01(\x08:\x028\x01")\n\x04Ping\x12!\n\x08metadata\x18\x01 \x01(\x0b2\x0f.drand.Metadata")\n\x04Pong\x12!\n\x08metadata\x18\x01 \x01(\x0b2\x0f.drand.Metadata"\x8d\x01\n\tSetupInfo\x12\x0e\n\x06leader\x18\x01 \x01(\x08\x12\x16\n\x0eleader_address\x18\x02 \x01(\t\x12\r\n\x05nodes\x18\x03 \x01(\r\x12\x11\n\tthreshold\x18\x04 \x01(\r\x12\x17\n\x0ftimeout_seconds\x18\x05 \x01(\r\x12\x0e\n\x06secret\x18\x06 \x01(\x0c\x12\r\n\x05force\x18\x07 \x01(\r"\xa3\x01\n\rInitDKGPacket\x12\x1e\n\x04info\x18\x01 \x01(\x0b2\x10.drand.SetupInfo\x12\x1d\n\x15beacon_period_seconds\x18\x02 \x01(\r\x12\x1e\n\x16catchup_period_seconds\x18\x03 \x01(\r\x12\x10\n\x08schemeID\x18\x04 \x01(\t\x12!\n\x08metadata\x18\x05 \x01(\x0b2\x0f.drand.Metadata"n\n\x11InitResharePacket\x12\x1e\n\x04info\x18\x01 \x01(\x0b2\x10.drand.SetupInfo\x12\x16\n\x0eold_group_path\x18\x02 \x01(\t\x12!\n\x08metadata\x18\x03 \x01(\x0b2\x0f.drand.Metadata"5\n\x10PublicKeyRequest\x12!\n\x08metadata\x18\x01 \x01(\x0b2\x0f.drand.Metadata"G\n\x11PublicKeyResponse\x12\x0f\n\x07pub_key\x18\x01 \x01(\x0c\x12!\n\x08metadata\x18\x02 \x01(\x0b2\x0f.drand.Metadata"6\n\x11PrivateKeyRequest\x12!\n\x08metadata\x18\x01 \x01(\x0b2\x0f.drand.Metadata"H\n\x12PrivateKeyResponse\x12\x0f\n\x07pri_key\x18\x01 \x01(\x0c\x12!\n\x08metadata\x18\x02 \x01(\x0b2\x0f.drand.Metadata"1\n\x0cGroupRequest\x12!\n\x08metadata\x18\x01 \x01(\x0b2\x0f.drand.Metadata"4\n\x0fShutdownRequest\x12!\n\x08metadata\x18\x01 \x01(\x0b2\x0f.drand.Metadata"5\n\x10ShutdownResponse\x12!\n\x08metadata\x18\x01 \x01(\x0b2\x0f.drand.Metadata"6\n\x11LoadBeaconRequest\x12!\n\x08metadata\x18\x01 \x01(\x0b2\x0f.drand.Metadata"7\n\x12LoadBeaconResponse\x12!\n\x08metadata\x18\x01 \x01(\x0b2\x0f.drand.Metadata"\x89\x01\n\x10StartSyncRequest\x12\r\n\x05nodes\x18\x01 \x03(\t\x12\x0e\n\x06is_tls\x18\x02 \x01(\x08\x12\r\n\x05up_to\x18\x03 \x01(\x04\x12\x10\n\x08beaconID\x18\x04 \x01(\t\x12\x12\n\nchain_hash\x18\x05 \x01(\t\x12!\n\x08metadata\x18\x06 \x01(\x0b2\x0f.drand.Metadata"R\n\x0cSyncProgress\x12\x0f\n\x07current\x18\x01 \x01(\x04\x12\x0e\n\x06target\x18\x02 \x01(\x04\x12!\n\x08metadata\x18\x03 \x01(\x0b2\x0f.drand.Metadata"I\n\x0fBackupDBRequest\x12\x13\n\x0boutput_file\x18\x01 \x01(\t\x12!\n\x08metadata\x18\x02 \x01(\x0b2\x0f.drand.Metadata"5\n\x10BackupDBResponse\x12!\n\x08metadata\x18\x01 \x01(\x0b2\x0f.drand.Metadata"7\n\x12ListSchemesRequest\x12!\n\x08metadata\x18\x01 \x01(\x0b2\x0f.drand.Metadata"E\n\x13ListSchemesResponse\x12\x0b\n\x03ids\x18\x01 \x03(\t\x12!\n\x08metadata\x18\x02 \x01(\x0b2\x0f.drand.Metadata"9\n\x14ListBeaconIDsRequest\x12!\n\x08metadata\x18\x01 \x01(\x0b2\x0f.drand.Metadata"G\n\x15ListBeaconIDsResponse\x12\x0b\n\x03ids\x18\x01 \x03(\t\x12!\n\x08metadata\x18\x02 \x01(\x0b2\x0f.drand.Metadata"a\n\x13RemoteStatusRequest\x12\'\n\taddresses\x18\x01 \x03(\x0b2\x14.drand.StatusAddress\x12!\n\x08metadata\x18\x02 \x01(\x0b2\x0f.drand.Metadata"J\n\x10RemoteStatusNode\x12\x0f\n\x07address\x18\x01 \x01(\t\x12%\n\x06status\x18\x02 \x01(\x0b2\x15.drand.StatusResponse"d\n\x14RemoteStatusResponse\x12)\n\x08statuses\x18\x01 \x03(\x0b2\x17.drand.RemoteStatusNode\x12!\n\x08metadata\x18\x02 \x01(\x0b2\x0f.drand.Metadata"3\n\x0eMetricsRequest\x12!\n\x08metadata\x18\x01 \x01(\x0b2\x0f.drand.Metadata"E\n\x0fMetricsResponse\x12\x0f\n\x07metrics\x18\x01 \x01(\x0c\x12!\n\x08metadata\x18\x02 \x01(\x0b2\x0f.drand.Metadata"\x99\x01\n\x12GossipBeaconPacket\x12\x12\n\nchain_hash\x18\x01 \x01(\x0c\x12\r\n\x05round\x18\x02 \x01(\x04\x12\x11\n\tsignature\x18\x03 \x01(\x0c\x12\x1a\n\x12previous_signature\x18\x04 \x01(\x0c\x12\x0e\n\x06sender\x18\x05 \x01(\t\x12!\n\x08metadata\x18\x06 \x01(\x0b2\x0f.drand.Metadata"\xb1\x01\n\x15HandelAggregatePacket\x12\r\n\x05round\x18\x01 \x01(\x04\x12\x1a\n\x12previous_signature\x18\x02 \x01(\x0c\x12\r\n\x05level\x18\x03 \x01(\r\x12\x0f\n\x07bitmask\x18\x04 \x01(\x0c\x12\x14\n\x0cpartial_sigs\x18\x05 \x03(\x0c\x12\x14\n\x0csender_index\x18\x06 \x01(\r\x12!\n\x08metadata\x18\x07 \x01(\x0b2\x0f.drand.Metadata"\xd3\x01\n\x12TenantConfigPacket\x12\x0c\n\x04name\x18\x01 \x01(\t\x12\x0e\n\x06weight\x18\x02 \x01(\x01\x12\x0c\n\x04rate\x18\x03 \x01(\x01\x12\r\n\x05burst\x18\x04 \x01(\r\x12\x15\n\rdevice_budget\x18\x05 \x01(\x01\x12\x0e\n\x06chains\x18\x06 \x03(\t\x12\x11\n\tpin_group\x18\x07 \x01(\x05\x12\x15\n\ranti_affinity\x18\x08 \x01(\x08\x12\x0e\n\x06paused\x18\t \x01(\x08\x12!\n\x08metadata\x18\n \x01(\x0b2\x0f.drand.Metadata"@\n\rTenantRequest\x12\x0c\n\x04name\x18\x01 \x01(\t\x12!\n\x08metadata\x18\x02 \x01(\x0b2\x0f.drand.Metadata"c\n\x12TenantListResponse\x12*\n\x07tenants\x18\x01 \x03(\x0b2\x19.drand.TenantConfigPacket\x12!\n\x08metadata\x18\x02 \x01(\x0b2\x0f.drand.Metadata"}\n\x10TokenMintRequest\x12\x0e\n\x06tenant\x18\x01 \x01(\t\x12\x0e\n\x06chains\x18\x02 \x03(\t\x12\x13\n\x0bttl_seconds\x18\x03 \x01(\x01\x12\x11\n\tread_only\x18\x04 \x01(\x08\x12!\n\x08metadata\x18\x05 \x01(\x0b2\x0f.drand.Metadata"h\n\x11TokenMintResponse\x12\r\n\x05token\x18\x01 \x01(\t\x12\x10\n\x08token_id\x18\x02 \x01(\t\x12\x0f\n\x07expires\x18\x03 \x01(\x01\x12!\n\x08metadata\x18\x04 \x01(\x0b2\x0f.drand.Metadata"C\n\x0cTokenRequest\x12\x10\n\x08token_id\x18\x01 \x01(\t\x12!\n\x08metadata\x18\x02 \x01(\x0b2\x0f.drand.Metadata"r\n\tTokenInfo\x12\x10\n\x08token_id\x18\x01 \x01(\t\x12\x0e\n\x06tenant\x18\x02 \x01(\t\x12\x0f\n\x07expires\x18\x03 \x01(\x01\x12\x11\n\tread_only\x18\x04 \x01(\x08\x12\x0f\n\x07revoked\x18\x05 \x01(\x08\x12\x0e\n\x06chains\x18\x06 \x03(\t"X\n\x11TokenListResponse\x12 \n\x06tokens\x18\x01 \x03(\x0b2\x10.drand.TokenInfo\x12!\n\x08metadata\x18\x02 \x01(\x0b2\x0f.drand.Metadatab\x06proto3')

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, 'drand_pb2', globals())
if _descriptor._USE_C_DESCRIPTORS == False:

  DESCRIPTOR._options = None
  _NODEVERSION._serialized_start=22
  _NODEVERSION._serialized_end=80
  _METADATA._serialized_start=82
  _METADATA._serialized_end=172
  _EMPTY._serialized_start=174
  _EMPTY._serialized_end=216
  _IDENTITY._serialized_start=218
  _IDENTITY._serialized_end=290
  _GROUPNODE._serialized_start=292
  _GROUPNODE._serialized_end=351
  _GROUPPACKET._serialized_start=354
  _GROUPPACKET._serialized_end=599
  _IDENTITYREQUEST._serialized_start=601
  _IDENTITYREQUEST._serialized_end=653
  _IDENTITYRESPONSE._serialized_start=656
  _IDENTITYRESPONSE._serialized_end=791
  _SIGNALDKGPACKET._serialized_start=794
  _SIGNALDKGPACKET._serialized_end=928
  _DKGINFOPACKET._serialized_start=931
  _DKGINFOPACKET._serialized_end=1108
  _PARTIALBEACONPACKET._serialized_start=1110
  _PARTIALBEACONPACKET._serialized_end=1230
  _DEALSHARE._serialized_start=1232
  _DEALSHARE._serialized_end=1289
  _DEALBUNDLE._serialized_start=1291
  _DEALBUNDLE._serialized_end=1414
  _DEALERSTATUS._serialized_start=1416
  _DEALERSTATUS._serialized_end=1468
  _RESPONSEBUNDLE._serialized_start=1470
  _RESPONSEBUNDLE._serialized_end=1586
  _JUSTIFICATIONSHARE._serialized_start=1588
  _JUSTIFICATIONSHARE._serialized_end=1644
  _JUSTIFICATIONBUNDLE._serialized_start=1647
  _JUSTIFICATIONBUNDLE._serialized_end=1780
  _DKGBUNDLE._serialized_start=1783
  _DKGBUNDLE._serialized_end=1970
  _DKGPACKET._serialized_start=1972
  _DKGPACKET._serialized_end=2049
  _SYNCREQUEST._serialized_start=2051
  _SYNCREQUEST._serialized_end=2119
  _BEACONPACKET._serialized_start=2121
  _BEACONPACKET._serialized_end=2232
  _PUBLICRANDREQUEST._serialized_start=2234
  _PUBLICRANDREQUEST._serialized_end=2303
  _PUBLICRANDRESPONSE._serialized_start=2306
  _PUBLICRANDRESPONSE._serialized_end=2443
  _CHAININFOREQUEST._serialized_start=2445
  _CHAININFOREQUEST._serialized_end=2498
  _CHAININFOPACKET._serialized_start=2501
  _CHAININFOPACKET._serialized_end=2663
  _HOMEREQUEST._serialized_start=2665
  _HOMEREQUEST._serialized_end=2713
  _HOMERESPONSE._serialized_start=2715
  _HOMERESPONSE._serialized_end=2780
  _STATUSADDRESS._serialized_start=2782
  _STATUSADDRESS._serialized_end=2827
  _STATUSREQUEST._serialized_start=2829
  _STATUSREQUEST._serialized_end=2921
  _DKGSTATUSPART._serialized_start=2923
  _DKGSTATUSPART._serialized_end=2954
  _BEACONSTATUSPART._serialized_start=2956
  _BEACONSTATUSPART._serialized_end=3070
  _CHAINSTORESTATUSPART._serialized_start=3072
  _CHAINSTORESTATUSPART._serialized_end=3148
  _STATUSRESPONSE._serialized_start=3151
  _STATUSRESPONSE._serialized_end=3445
  _STATUSRESPONSE_CONNECTIONSENTRY._serialized_start=3395
  _STATUSRESPONSE_CONNECTIONSENTRY._serialized_end=3445
  _PING._serialized_start=3447
  _PING._serialized_end=3488
  _PONG._serialized_start=3490
  _PONG._serialized_end=3531
  _SETUPINFO._serialized_start=3534
  _SETUPINFO._serialized_end=3675
  _INITDKGPACKET._serialized_start=3678
  _INITDKGPACKET._serialized_end=3841
  _INITRESHAREPACKET._serialized_start=3843
  _INITRESHAREPACKET._serialized_end=3953
  _PUBLICKEYREQUEST._serialized_start=3955
  _PUBLICKEYREQUEST._serialized_end=4008
  _PUBLICKEYRESPONSE._serialized_start=4010
  _PUBLICKEYRESPONSE._serialized_end=4081
  _PRIVATEKEYREQUEST._serialized_start=4083
  _PRIVATEKEYREQUEST._serialized_end=4137
  _PRIVATEKEYRESPONSE._serialized_start=4139
  _PRIVATEKEYRESPONSE._serialized_end=4211
  _GROUPREQUEST._serialized_start=4213
  _GROUPREQUEST._serialized_end=4262
  _SHUTDOWNREQUEST._serialized_start=4264
  _SHUTDOWNREQUEST._serialized_end=4316
  _SHUTDOWNRESPONSE._serialized_start=4318
  _SHUTDOWNRESPONSE._serialized_end=4371
  _LOADBEACONREQUEST._serialized_start=4373
  _LOADBEACONREQUEST._serialized_end=4427
  _LOADBEACONRESPONSE._serialized_start=4429
  _LOADBEACONRESPONSE._serialized_end=4484
  _STARTSYNCREQUEST._serialized_start=4487
  _STARTSYNCREQUEST._serialized_end=4624
  _SYNCPROGRESS._serialized_start=4626
  _SYNCPROGRESS._serialized_end=4708
  _BACKUPDBREQUEST._serialized_start=4710
  _BACKUPDBREQUEST._serialized_end=4783
  _BACKUPDBRESPONSE._serialized_start=4785
  _BACKUPDBRESPONSE._serialized_end=4838
  _LISTSCHEMESREQUEST._serialized_start=4840
  _LISTSCHEMESREQUEST._serialized_end=4895
  _LISTSCHEMESRESPONSE._serialized_start=4897
  _LISTSCHEMESRESPONSE._serialized_end=4966
  _LISTBEACONIDSREQUEST._serialized_start=4968
  _LISTBEACONIDSREQUEST._serialized_end=5025
  _LISTBEACONIDSRESPONSE._serialized_start=5027
  _LISTBEACONIDSRESPONSE._serialized_end=5098
  _REMOTESTATUSREQUEST._serialized_start=5100
  _REMOTESTATUSREQUEST._serialized_end=5197
  _REMOTESTATUSNODE._serialized_start=5199
  _REMOTESTATUSNODE._serialized_end=5273
  _REMOTESTATUSRESPONSE._serialized_start=5275
  _REMOTESTATUSRESPONSE._serialized_end=5375
  _METRICSREQUEST._serialized_start=5377
  _METRICSREQUEST._serialized_end=5428
  _METRICSRESPONSE._serialized_start=5430
  _METRICSRESPONSE._serialized_end=5499
  _GOSSIPBEACONPACKET._serialized_start=5502
  _GOSSIPBEACONPACKET._serialized_end=5655
  _HANDELAGGREGATEPACKET._serialized_start=5658
  _HANDELAGGREGATEPACKET._serialized_end=5835
  _TENANTCONFIGPACKET._serialized_start=5838
  _TENANTCONFIGPACKET._serialized_end=6049
  _TENANTREQUEST._serialized_start=6051
  _TENANTREQUEST._serialized_end=6115
  _TENANTLISTRESPONSE._serialized_start=6117
  _TENANTLISTRESPONSE._serialized_end=6216
  _TOKENMINTREQUEST._serialized_start=6218
  _TOKENMINTREQUEST._serialized_end=6343
  _TOKENMINTRESPONSE._serialized_start=6345
  _TOKENMINTRESPONSE._serialized_end=6449
  _TOKENREQUEST._serialized_start=6451
  _TOKENREQUEST._serialized_end=6518
  _TOKENINFO._serialized_start=6520
  _TOKENINFO._serialized_end=6634
  _TOKENLISTRESPONSE._serialized_start=6636
  _TOKENLISTRESPONSE._serialized_end=6724
# @@protoc_insertion_point(module_scope)
