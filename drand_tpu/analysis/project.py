"""Phase 1 of the two-phase tpu-vet engine: the project-wide view.

`symbols.ModuleInfo` answers questions about ONE file; this module joins
every scanned file into a `Project` — a cross-module symbol table, a
call graph, and per-function summaries — so phase-2 checkers can follow
a value across a call boundary: a share flowing through a helper into a
log line, a `time.time()` value returned by a utility and consumed as a
deadline, a blocking RPC whose timeout parameter no caller ever threads.
Both failure shapes burned real campaigns (r06's 42 hung probes, the
PRs 7/8/12 thread leaks) and are invisible to a per-function pass.

Resolution is deliberately name-shaped, like everything else in this
framework: imports are rewritten through each module's import table
(`ModuleInfo.resolve`), then matched against module dotted paths by
suffix, so `from ..net import client` and `from drand_tpu.net import
client` meet at the same `net/client.py` entry.  `self.method()` resolves
through the enclosing class; `self.attr.method()` through the class's
typed attribute constructors.  Anything unresolvable is simply absent
from the graph — summaries only ever ADD findings a per-function pass
misses, never suppress one.

Summaries (computed to a fixed point over the call graph):

  * ``returns_secret``    — the function returns key material (or the
    result of a function that does).
  * ``returns_wallclock`` — the function returns a raw ``time.time()/
    monotonic()`` value (or launders one through another function).
  * ``returns_thread``    — the function hands ownership of a
    ``threading.Thread`` to its caller.
  * ``jit_factory``       — the function returns a ``jax.jit(...)``
    product (each call is a fresh program flavor).
  * ``logged_params``     — parameters whose values reach a log/print
    sink inside the function.
  * ``required_deadline`` — ``timeout/deadline/budget`` parameters that
    default to None and flow BARE (no ``or``-fallback, no None-guard)
    into a blocking primitive or a callee's required deadline — callers
    that omit them run unbounded.
  * ``static_args``       — static argument names/positions of jitted
    definitions (cache-key slots for the ``recompile`` checker).

The taxonomies shared with the per-function checkers (secret
identifiers, log sinks, wall-clock calls, blocking primitives) live HERE
and the checkers import them, so phase-1 summaries and phase-2 matching
cannot drift apart.
"""

import ast
import re
import threading
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .symbols import (LOCK_KINDS, ClassInfo, ModuleInfo, dotted, walk_scope)

# -- shared taxonomies (checkers import these) -------------------------------

SECRET_IDS = re.compile(
    r"^(secret|secrets|sk|pri_key|private|private_key|secret_key|"
    r"longterm|share|_share|new_share|old_share|dist_share)$")
SAFE_IDS = {"secret_proof", "share_index", "sharemap", "shares_total"}
SANITIZERS = {"hash_secret", "len", "type", "bool", "id", "index_of"}
SECRET_GETTERS = {"get_share", "load_share"}
LOG_METHODS = {"debug", "info", "warn", "warning", "error", "exception",
               "critical", "rate_limited_info"}
LOG_RECEIVERS = ("log", "logger", "LOG", "DEFAULT")

WALLCLOCK_CALLS = {"time.time", "time.time_ns",
                   "time.monotonic", "time.monotonic_ns"}

JIT_NAMES = {"jit", "jax.jit", "pjit", "jax.pjit"}

THREAD_CTOR = "threading.Thread"

# timeout/deadline/budget-shaped parameter names (the deadline checker's
# threading contract keys on these)
DEADLINE_PARAM = re.compile(
    r"(^|_)(timeout|deadline|budget|wait|ttl)(_|$)|"
    r"(timeout|deadline|budget)s?$")

# blocking primitives that default to "forever": resolved qualname ->
# (timeout kwarg name, positional index of that timeout, or None)
BLOCKING_CALLS = {
    "subprocess.run": ("timeout", None),
    "subprocess.call": ("timeout", None),
    "subprocess.check_call": ("timeout", None),
    "subprocess.check_output": ("timeout", None),
    "urllib.request.urlopen": ("timeout", 2),
    "socket.create_connection": ("timeout", 1),
}
# method-shaped blocking calls (receiver type unknowable to an AST pass;
# these names are unambiguous in practice — Popen.communicate)
BLOCKING_METHODS = {
    "communicate": ("timeout", 0),
    # Popen.wait / Event.wait / Condition.wait — all take the bound as
    # the first positional or `timeout=`; all block forever without it
    # (the fleet harness's subprocess reaps hang CI exactly like r06)
    "wait": ("timeout", 0),
}

# in-place container mutators: the lock checker's rule-1 write set and the
# ``mutates_params`` summary (helper-laundered writes) share this list
MUTATORS = {"append", "extend", "insert", "remove", "pop", "clear",
            "update", "setdefault", "add", "discard", "popleft",
            "appendleft", "popitem"}

# method names that stall the calling thread regardless of receiver type
# (the lock checker's rule-2 set; ``may_block`` summaries reuse it)
BLOCKING_STALL_NAMES = {"wait_until", "serve_forever"}


# -- lock-graph nodes (lock checker v3 + phase-1 lockset summaries) ----------

# (module rel, owning class name or "" for a module-level lock, lock name)
LockNode = Tuple[str, str, str]


def lock_node_at(module: ModuleInfo, cls: Optional[ClassInfo],
                 expr: str) -> Optional["LockNode"]:
    """The lock-graph node a dotted with-context expression names: a
    typed `self.<lock>` attribute of the enclosing class, or a top-level
    module lock of the same module.  None for anything else."""
    if expr.startswith("self.") and expr.count(".") == 1:
        if cls is None:
            return None
        attr = expr.split(".", 1)[1]
        if cls.attr_kinds.get(attr) in LOCK_KINDS:
            return (module.rel, cls.name, attr)
        return None
    if "." not in expr and expr in module.module_locks:
        return (module.rel, "", expr)
    return None


def held_lockset(module: ModuleInfo, cls: Optional[ClassInfo],
                 node: ast.AST) -> Set["LockNode"]:
    """Lock nodes provably held at `node` (enclosing `with` statements)."""
    out: Set[LockNode] = set()
    for d in module.withs_holding(node):
        ln = lock_node_at(module, cls, d)
        if ln is not None:
            out.add(ln)
    return out


def lock_label(ln: "LockNode") -> str:
    """Human name of a lock node: `Class.attr` or `pkg.module._lock`."""
    rel, owner, name = ln
    if owner:
        return f"{owner}.{name}"
    mod = rel[:-3] if rel.endswith(".py") else rel
    return mod.replace("/", ".") + f".{name}"


def is_log_call(node: ast.Call) -> bool:
    """Logger-style sink: `.debug/.info/...` on a log-ish receiver."""
    if not isinstance(node.func, ast.Attribute):
        return False
    if node.func.attr not in LOG_METHODS:
        return False
    recv = dotted(node.func.value) or ""
    return recv.rsplit(".", 1)[-1] in LOG_RECEIVERS or recv.endswith(".log")


def blocking_call(module: ModuleInfo, node: ast.Call
                  ) -> Optional[Tuple[str, Optional[ast.AST]]]:
    """(label, timeout-value-expr-or-None) when `node` is a recognized
    blocking primitive; None otherwise.  An explicit ``timeout=None``
    counts as absent."""
    qual = module.resolve(dotted(node.func) or "")
    spec = BLOCKING_CALLS.get(qual)
    label = qual
    if spec is None and isinstance(node.func, ast.Attribute) \
            and node.func.attr in BLOCKING_METHODS:
        spec = BLOCKING_METHODS[node.func.attr]
        label = f".{node.func.attr}()"
    if spec is None:
        return None
    kwarg, pos = spec
    expr = None
    for kw in node.keywords:
        if kw.arg == kwarg:
            expr = kw.value
    if expr is None and pos is not None and len(node.args) > pos:
        expr = node.args[pos]
    if isinstance(expr, ast.Constant) and expr.value is None:
        expr = None
    return label, expr


# -- per-function summary -----------------------------------------------------


@dataclass
class FunctionSummary:
    module: ModuleInfo
    cls: Optional[ClassInfo]
    node: ast.AST
    qual: str                        # "fname" or "Class.method"
    params: List[str] = field(default_factory=list)
    defaults: Dict[str, ast.AST] = field(default_factory=dict)
    returns_secret: bool = False
    returns_wallclock: bool = False
    returns_thread: bool = False
    jit_factory: bool = False
    logged_params: Set[str] = field(default_factory=set)
    required_deadline: Set[str] = field(default_factory=set)
    static_args: Dict[str, int] = field(default_factory=dict)
    # resolved call sites inside this function: (call node, callee key)
    calls: List[Tuple[ast.Call, Optional[Tuple[str, str]]]] = \
        field(default_factory=list)
    # lockset summaries (lock checker v3): what this function acquires —
    # directly and closed over resolved callees — whether it can stall
    # the calling thread, and which parameters it mutates or invokes
    acquires: Set["LockNode"] = field(default_factory=set)
    acquires_trans: Set["LockNode"] = field(default_factory=set)
    blocks_reason: Optional[str] = None
    may_block: Optional[str] = None
    mutates_params: Set[str] = field(default_factory=set)
    calls_params: Set[str] = field(default_factory=set)

    @property
    def rel(self) -> str:
        return self.module.rel

    @property
    def display(self) -> str:
        return f"{self.rel}::{self.qual}"

    def arg_param(self, call: ast.Call, param: str) -> Optional[ast.AST]:
        """The expression a call site binds to `param`, or None if the
        call omits it (keyword, or positional with `self` accounted)."""
        for kw in call.keywords:
            if kw.arg == param:
                return kw.value
        try:
            idx = self.params.index(param)
        except ValueError:
            return None
        if self.cls is not None and self.params[:1] == ["self"]:
            idx -= 1                       # bound call: self not at the site
        if 0 <= idx < len(call.args):
            arg = call.args[idx]
            return None if isinstance(arg, ast.Starred) else arg
        return None


class Project:
    """The project-wide call graph + summaries (phase 1)."""

    def __init__(self, modules: Iterable[ModuleInfo]):
        self.modules: List[ModuleInfo] = list(modules)
        self.functions: Dict[Tuple[str, str], FunctionSummary] = {}
        self._dotted: Dict[str, List[ModuleInfo]] = {}
        for m in self.modules:
            self._dotted.setdefault(m.dotted, []).append(m)
        # project-scoped memo for checkers that derive a whole-project
        # structure once (the lock checker's global order graph); guarded
        # because the per-file sweep may run on a worker pool
        self._memo: Dict[str, object] = {}
        self._memo_lock = threading.Lock()
        self._collect()
        self._resolve_calls()
        self._summarize()

    def memo(self, key: str, build):
        """`build()` once per project under `key`; cached thereafter.
        Thread-safe so parallel per-file checker workers share one
        instance of an expensive project-wide derivation."""
        with self._memo_lock:
            if key not in self._memo:
                self._memo[key] = build()
            return self._memo[key]

    # -- construction --------------------------------------------------------

    def _collect(self) -> None:
        for m in self.modules:
            for qual, (cls, fn) in m.defs_by_qual().items():
                args = fn.args
                params = [a.arg for a in args.posonlyargs + args.args]
                kw_params = [a.arg for a in args.kwonlyargs]
                s = FunctionSummary(module=m, cls=cls, node=fn, qual=qual,
                                    params=params + kw_params)
                pos_defaults = args.defaults
                for name, d in zip(params[len(params) - len(pos_defaults):],
                                   pos_defaults):
                    s.defaults[name] = d
                for name, d in zip(kw_params, args.kw_defaults):
                    if d is not None:
                        s.defaults[name] = d
                s.static_args = self._static_args(m, fn, params)
                self.functions[(m.rel, qual)] = s

    def _static_args(self, m: ModuleInfo, fn: ast.AST,
                     params: List[str]) -> Dict[str, int]:
        """static_argnums/static_argnames of a jit-decorated def."""
        out: Dict[str, int] = {}
        for dec in getattr(fn, "decorator_list", ()):
            call = dec if isinstance(dec, ast.Call) else None
            if call is None:
                continue
            head = m.resolve(dotted(call.func) or "")
            if head not in JIT_NAMES and not (
                    head.endswith("partial") and call.args
                    and m.resolve(dotted(call.args[0]) or "") in JIT_NAMES):
                continue
            for kw in call.keywords:
                if kw.arg == "static_argnums":
                    vals = kw.value.elts \
                        if isinstance(kw.value, (ast.Tuple, ast.List)) \
                        else [kw.value]
                    for e in vals:
                        if isinstance(e, ast.Constant) \
                                and isinstance(e.value, int) \
                                and 0 <= e.value < len(params):
                            out[params[e.value]] = e.value
                elif kw.arg == "static_argnames":
                    vals = kw.value.elts \
                        if isinstance(kw.value, (ast.Tuple, ast.List)) \
                        else [kw.value]
                    for e in vals:
                        if isinstance(e, ast.Constant) \
                                and str(e.value) in params:
                            out[str(e.value)] = params.index(str(e.value))
        return out

    # -- cross-module resolution ---------------------------------------------

    def _module_for(self, modname: str) -> Optional[ModuleInfo]:
        """Match a dotted module path by suffix; the package prefix of an
        absolute import ("drand_tpu.net.client") and the anchored rel
        ("net.client") meet here."""
        hit = self._dotted.get(modname)
        if hit:
            return hit[0]
        for d, mods in self._dotted.items():
            if d.endswith("." + modname) or modname.endswith("." + d):
                return mods[0]
        return None

    def _lookup(self, module: ModuleInfo, name: str
                ) -> Optional[Tuple[str, str]]:
        """Resolve a dotted symbol (already import-rewritten) to a
        (rel, qual) function key."""
        if not name:
            return None
        if (module.rel, name) in self.functions:      # local fn / Cls.meth
            return (module.rel, name)
        parts = name.split(".")
        for i in range(len(parts) - 1, 0, -1):
            m2 = self._module_for(".".join(parts[:i]))
            if m2 is None:
                continue
            qual = ".".join(parts[i:])
            if (m2.rel, qual) in self.functions:
                return (m2.rel, qual)
        return None

    def resolve_call(self, module: ModuleInfo, call: ast.Call,
                     cls: Optional[ClassInfo] = None
                     ) -> Optional[FunctionSummary]:
        """The FunctionSummary a call site dispatches to, if the name
        analysis can prove one."""
        d = dotted(call.func)
        if d is None:
            return None
        if cls is None:
            cls = module.enclosing_class(call)
        if d.startswith("self.") and cls is not None:
            parts = d.split(".")
            if len(parts) == 2:                       # self.method()
                key = self._lookup(module, f"{cls.name}.{parts[1]}")
                return self.functions.get(key) if key else None
            if len(parts) == 3:                       # self.attr.method()
                ctor = cls.attr_ctors.get(parts[1], "")
                key = self._lookup(module, f"{ctor}.{parts[2]}") \
                    if ctor else None
                return self.functions.get(key) if key else None
            return None
        key = self._lookup(module, module.resolve(d))
        return self.functions.get(key) if key else None

    def _resolve_calls(self) -> None:
        for s in self.functions.values():
            for node in walk_scope(s.node):
                if isinstance(node, ast.Call):
                    callee = self.resolve_call(s.module, node, s.cls)
                    s.calls.append(
                        (node, (callee.rel, callee.qual) if callee else None))

    def callee(self, key: Optional[Tuple[str, str]]
               ) -> Optional[FunctionSummary]:
        return self.functions.get(key) if key else None

    # -- summaries ------------------------------------------------------------

    def _summarize(self) -> None:
        for s in self.functions.values():
            s.logged_params = self._logged_params(s)
            self._lockset_direct(s)
        # return-taint + deadline fixed point: a pass can only flip flags
        # from False to True, so iteration is monotone and converges
        for _ in range(4):
            changed = False
            for s in self.functions.values():
                changed |= self._return_taint(s)
                changed |= self._deadline_pass(s)
            if not changed:
                break
        # lockset fixed point: acquires_trans/may_block/mutates_params
        # only ever grow, so this is monotone too; deep call chains need
        # more sweeps than the 4-pass taint loop, bounded hard anyway
        for _ in range(16):
            changed = False
            for s in self.functions.values():
                changed |= self._lockset_propagate(s)
            if not changed:
                break

    # -- lockset summaries ----------------------------------------------------

    def _direct_block_reason(self, m: ModuleInfo, cls: Optional[ClassInfo],
                             call: ast.Call) -> Optional[str]:
        """Does this call stall the calling thread?  Mirrors the lock
        checker's per-function rule-2 vocabulary so static and
        interprocedural matching cannot drift.  `Condition.wait` is NOT a
        stall for summary purposes: it releases its own condition (the cv
        pattern); flagging helpers that park on a cv would bury the real
        holds-a-foreign-lock-across-sleep findings in noise."""
        qual = m.resolve(dotted(call.func) or "")
        if qual == "time.sleep":
            return "time.sleep"
        if not isinstance(call.func, ast.Attribute):
            return None
        meth = call.func.attr
        if meth in BLOCKING_STALL_NAMES:
            return f".{meth}()"
        recv = dotted(call.func.value) or ""
        attr = recv.split(".", 1)[1] \
            if recv.startswith("self.") and recv.count(".") == 1 else None
        kind = cls.attr_kinds.get(attr) if (cls and attr) else None
        if meth == "join" and kind == "thread":
            return f"Thread.join on self.{attr}"
        if meth == "wait" and kind == "event":
            return f"Event.wait on self.{attr}"
        if meth in ("get", "put") and kind == "queue":
            for kw in call.keywords:
                if kw.arg == "block" \
                        and isinstance(kw.value, ast.Constant) \
                        and kw.value.value is False:
                    return None
            if call.args and isinstance(call.args[0], ast.Constant) \
                    and call.args[0].value is False:
                return None
            return f"blocking Queue.{meth} on self.{attr}"
        return None

    def _lockset_direct(self, s: FunctionSummary) -> None:
        m, cls = s.module, s.cls
        params = set(s.params) - {"self"}
        for node in walk_scope(s.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    d = dotted(item.context_expr)
                    ln = lock_node_at(m, cls, d) if d else None
                    if ln is not None:
                        s.acquires.add(ln)
            elif isinstance(node, ast.Call):
                if s.blocks_reason is None:
                    s.blocks_reason = self._direct_block_reason(m, cls, node)
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr in MUTATORS \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id in params:
                    s.mutates_params.add(f.value.id)
                elif isinstance(f, ast.Name) and f.id in params:
                    s.calls_params.add(f.id)
            elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                                   ast.Delete)):
                targets = node.targets if isinstance(
                    node, (ast.Assign, ast.Delete)) else [node.target]
                for t in targets:
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id in params:
                        s.mutates_params.add(t.value.id)
        s.acquires_trans = set(s.acquires)
        s.may_block = s.blocks_reason

    def _lockset_propagate(self, s: FunctionSummary) -> bool:
        """One monotone sweep over s's resolved calls: union in callee
        acquisitions, propagate blocking (tagging the original site), and
        lift parameter mutation through pass-through helpers."""
        params = set(s.params) - {"self"}
        changed = False
        for call, key in s.calls:
            callee = self.functions.get(key) if key else None
            if callee is None or callee is s:
                continue
            extra = callee.acquires_trans - s.acquires_trans
            if extra:
                s.acquires_trans |= extra
                changed = True
            if s.may_block is None and callee.may_block is not None:
                s.may_block = callee.may_block if " in " in callee.may_block \
                    else f"{callee.may_block} in {callee.display}"
                changed = True
            for p in callee.mutates_params:
                bound = callee.arg_param(call, p)
                if isinstance(bound, ast.Name) and bound.id in params \
                        and bound.id not in s.mutates_params:
                    s.mutates_params.add(bound.id)
                    changed = True
        return changed

    # names whose values flow into this expression (through containers,
    # f-strings, binops and non-sanitizer calls)
    def _flowing_names(self, node: ast.AST, out: Set[str]) -> None:
        if isinstance(node, ast.Call):
            fname = dotted(node.func) or ""
            if fname.rsplit(".", 1)[-1] in SANITIZERS:
                return
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                self._flowing_names(a, out)
        elif isinstance(node, ast.JoinedStr):
            for v in node.values:
                if isinstance(v, ast.FormattedValue):
                    self._flowing_names(v.value, out)
        elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            for e in node.elts:
                self._flowing_names(e, out)
        elif isinstance(node, ast.Dict):
            for v in node.values:
                self._flowing_names(v, out)
        elif isinstance(node, ast.BinOp):
            self._flowing_names(node.left, out)
            self._flowing_names(node.right, out)
        elif isinstance(node, ast.Name):
            out.add(node.id)

    def _logged_params(self, s: FunctionSummary) -> Set[str]:
        params = set(s.params) - {"self"}
        hit: Set[str] = set()
        for node in walk_scope(s.node):
            if not isinstance(node, ast.Call):
                continue
            is_print = isinstance(node.func, ast.Name) \
                and node.func.id == "print"
            if not (is_print or is_log_call(node)):
                continue
            names: Set[str] = set()
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                self._flowing_names(a, names)
            hit |= names & params
        return hit

    def _secretish(self, module: ModuleInfo, node: ast.AST) -> bool:
        """Is this return expression secret-bearing?  Terminal-identifier
        match, a known getter, or a call into a returns_secret summary."""
        if isinstance(node, ast.Call):
            fname = dotted(node.func) or ""
            leaf = fname.rsplit(".", 1)[-1]
            if leaf in SANITIZERS:
                return False
            if leaf in SECRET_GETTERS:
                return True
            callee = self.resolve_call(module, node)
            if callee is not None and callee.returns_secret:
                return True
            return False
        if isinstance(node, (ast.Attribute, ast.Name)):
            d = dotted(node) or ""
            term = d.rsplit(".", 1)[-1]
            return term not in SAFE_IDS and bool(SECRET_IDS.match(term))
        if isinstance(node, ast.Tuple):
            return any(self._secretish(module, e) for e in node.elts)
        return False

    def _wallclockish(self, module: ModuleInfo, node: ast.AST,
                      tainted: Set[str]) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                d = dotted(sub.func) or ""
                qual = module.resolve(d)
                if qual in WALLCLOCK_CALLS:
                    return True
                # `self.clock.now()`-shaped reads go through an
                # attribute-typed receiver — an injection point whose
                # runtime type tests replace (FakeClock) — so the
                # default implementation's taint must not flow through
                if d.startswith("self.") and d.count(".") >= 2:
                    continue
                callee = self.resolve_call(module, sub)
                if callee is not None and callee.returns_wallclock:
                    return True
            elif isinstance(sub, ast.Name) and sub.id in tainted:
                return True
        return False

    def _threadish(self, module: ModuleInfo, node: ast.AST,
                   tainted: Set[str]) -> bool:
        if isinstance(node, ast.Call):
            if module.resolve(dotted(node.func) or "") == THREAD_CTOR:
                return True
            callee = self.resolve_call(module, node)
            return callee is not None and callee.returns_thread
        return isinstance(node, ast.Name) and node.id in tainted

    def _return_taint(self, s: FunctionSummary) -> bool:
        """One monotone pass over s's returns; True when a flag flipped."""
        m = s.module
        clock_taint: Set[str] = set()
        thread_taint: Set[str] = set()
        for node in walk_scope(s.node):      # one assignment hop
            if isinstance(node, ast.Assign):
                names = [t.id for t in node.targets
                         if isinstance(t, ast.Name)]
                if names and self._wallclockish(m, node.value, clock_taint):
                    clock_taint.update(names)
                if names and self._threadish(m, node.value, thread_taint):
                    thread_taint.update(names)
        changed = False
        for node in walk_scope(s.node):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            v = node.value
            if not s.returns_secret and self._secretish(m, v):
                s.returns_secret = changed = True
            if not s.returns_wallclock \
                    and self._wallclockish(m, v, clock_taint):
                s.returns_wallclock = changed = True
            if not s.returns_thread and self._threadish(m, v, thread_taint):
                s.returns_thread = changed = True
            if not s.jit_factory and isinstance(v, ast.Call) \
                    and m.resolve(dotted(v.func) or "") in JIT_NAMES:
                s.jit_factory = changed = True
        return changed

    # -- deadline threading ---------------------------------------------------

    @staticmethod
    def _has_fallback(fn: ast.AST, param: str) -> bool:
        """`p or default`, `if p is None`, or a reassignment of p — the
        function bounds itself, callers need not thread the deadline."""
        for node in walk_scope(fn):
            if isinstance(node, ast.BoolOp) and isinstance(node.op, ast.Or):
                if any(isinstance(v, ast.Name) and v.id == param
                       for v in node.values):
                    return True
            elif isinstance(node, ast.Compare) \
                    and isinstance(node.left, ast.Name) \
                    and node.left.id == param \
                    and any(isinstance(op, (ast.Is, ast.IsNot))
                            for op in node.ops):
                return True
            elif isinstance(node, ast.Assign) \
                    and any(isinstance(t, ast.Name) and t.id == param
                            for t in node.targets):
                return True
        return False

    def _deadline_pass(self, s: FunctionSummary) -> bool:
        candidates = [
            p for p in s.params
            if p not in s.required_deadline and DEADLINE_PARAM.search(p)
            and isinstance(s.defaults.get(p), ast.Constant)
            and s.defaults[p].value is None]
        if not candidates:
            return False
        changed = False
        for p in candidates:
            if self._has_fallback(s.node, p):
                continue
            if self._param_reaches_blocking(s, p):
                s.required_deadline.add(p)
                changed = True
        return changed

    def _param_reaches_blocking(self, s: FunctionSummary, p: str) -> bool:
        for call, key in s.calls:
            info = blocking_call(s.module, call)
            if info is not None:
                _, expr = info
                if isinstance(expr, ast.Name) and expr.id == p:
                    return True
            callee = self.callee(key)
            if callee is None:
                continue
            for req in callee.required_deadline:
                bound = callee.arg_param(call, req)
                if isinstance(bound, ast.Name) and bound.id == p:
                    return True
        return False
