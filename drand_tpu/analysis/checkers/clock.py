"""clock-discipline: all time flows through the injected Clock.

The engine's determinism story (tests/chaos.py, FakeClock) only holds if
nothing reads the wall clock behind the Clock abstraction's back — one
stray `time.time()` and a chaos scenario that replays byte-identically on
a fake clock diverges in production.  The reference makes the same
promise structurally (clockwork in core/util_test.go); here the checker
enforces it.

Banned: `time.time`, `time.monotonic`, `time.sleep` (and their `_ns`
variants), resolved through import aliases (`import time as _t`;
`from time import sleep`).  `time.perf_counter` stays allowed: latency
*measurement* (metrics observers) is not schedule logic and must not be
steered by a fake clock.  Allowlist: the Clock implementations
themselves (beacon/clock.py) and log.py (timestamps on log records are
wall-clock by definition).

Interprocedural (v2): with a phase-1 `Project`, calls to helpers whose
return value is wall-clock-tainted (`def wall_now(): return time.time()`
in another module) are flagged too — laundering the read through a
utility function no longer hides it.  Helpers defined in the allowlisted
Clock modules are the sanctioned route and stay exempt.
"""

import ast
from typing import Iterator, Optional

from ..core import Finding
from ..symbols import ModuleInfo, dotted

BANNED = {
    "time.time", "time.time_ns",
    "time.monotonic", "time.monotonic_ns",
    "time.sleep",
}

# rel-path suffixes exempt from the discipline.  net/chaosproxy.py and
# the fleet harness/CLI (fleet.py) are wall-clock by design: they shape
# real wire traffic and supervise real subprocesses, and an injected
# fake clock cannot reach across process boundaries.  analysis/tsan.py
# is the runtime lock sanitizer: hold/wait durations are measurements of
# the real interpreter, not schedule logic, and must not be faked.
ALLOWED_FILES = ("beacon/clock.py", "log.py", "net/chaosproxy.py",
                 "fleet.py", "analysis/tsan.py")


def _allowed_rel(rel: str) -> bool:
    return any(rel == a or rel.endswith("/" + a) for a in ALLOWED_FILES)


class ClockChecker:
    name = "clock"
    description = ("direct (or helper-laundered) time.time()/monotonic()/"
                   "sleep() outside the injected-Clock implementations")
    uses_project = True

    def check(self, module: ModuleInfo,
              project: Optional[object] = None) -> Iterator[Finding]:
        if _allowed_rel(module.rel):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            qual = module.resolve(dotted(node.func) or "")
            if qual in BANNED:
                yield Finding(
                    checker=self.name, code="clock-direct-call",
                    message=(f"direct call to {qual}(); route through the "
                             "injected Clock (beacon/clock.py) so chaos "
                             "tests stay deterministic"),
                    path=module.rel, line=node.lineno, col=node.col_offset)
                continue
            if project is None:
                continue
            callee = project.resolve_call(module, node)
            if callee is not None and callee.returns_wallclock \
                    and not _allowed_rel(callee.rel):
                yield Finding(
                    checker=self.name, code="clock-interproc-call",
                    message=(f"call to {callee.display} returns a raw "
                             "wall-clock value; route through the injected "
                             "Clock (beacon/clock.py) so chaos tests stay "
                             "deterministic"),
                    path=module.rel, line=node.lineno, col=node.col_offset)
