"""secret-hygiene: key material never reaches logs, exceptions, or repr.

Security reviews of beacon-chain clients (arXiv:2109.11677) put
key-material hygiene next to concurrency misuse as the dominant finding
class; in this codebase the dangerous values are the DKG share
(`key.Share` / `vault.get_share()`), the long-term private key
(`pair.key`, `longterm`), and setup secrets.  A leak needs no exploit —
one `log.debug("dkg state", share=self.share)` and the share sits in
every log aggregator the operator ships to.

The identity plane (net/identity.py + core/authz.py) adds two more
bearer-grade classes: the tenant-token ROOT KEY (`_root_key`,
`token_key` — whoever holds it mints arbitrary tenant tokens) and TLS
PRIVATE KEYS (`key_pem`, `node_key`, `tls_key`, `ca_key` — whoever
holds one impersonates the node, or with the CA key the whole roster).
Token *ids* and certificate PEMs (`cert_pem`, `ca_pem` public halves)
are deliberately NOT matched: ids are public handles and certs are what
the wire already shows every peer.

Taint-lite, intra-function:

  * sources — names/attributes whose terminal identifier is secret-ish
    (`secret`, `sk`, `private_key`, `pri_key`, `secret_key`,
    `longterm`, `share`/`_share`, `.private`), plus calls to
    `get_share()` / `load_share()` / `sign_partial` inputs excluded.
  * sanitizers — `hash_secret(...)`, `len()`, `type()`, `bool()`, `id()`
    produce clean values (a *hash* of the setup secret is the designed
    wire form).  Identifiers on the safe-list (`secret_proof`) are
    already sanitized upstream.
  * sinks — Logger-style calls (`.debug/.info/.warn/.warning/.error/
    .exception/.critical/.rate_limited_info` on a `log`-ish receiver),
    `print`, exception constructors inside `raise`, and return values of
    `__repr__`/`__str__`/`__format__`.

One assignment hop is tracked (`s = self._share` then `log.info(x=s)`);
within one function that is the scope.  With a phase-1 `Project`
(interprocedural v2) two cross-function flows join in:

  * sources — a call to ANY function whose summary says it returns
    secret material (`def current_material(vault): return
    vault.get_share()` makes `current_material(v)` a source at every
    resolved call site), not just the two hard-coded getter names.
  * sinks — passing a secret expression into a callee parameter that the
    callee's summary says reaches a log/print sink
    (`secret-interproc-log`): the leak happens one frame down, the bug
    is at the call site.
"""

import ast
import re
from typing import Iterator, Optional, Set

from ..core import Finding
from ..symbols import ModuleInfo, dotted

SECRET_IDS = re.compile(
    r"^_?(secret|secrets|sk|pri_key|private|private_key|secret_key|"
    r"longterm|share|new_share|old_share|dist_share|"
    # identity plane (PR 19): the token-authority root key mints
    # arbitrary tenant tokens, a node's TLS private key impersonates it
    # to the whole committee — both are bearer-grade material.
    r"root_key|token_key|key_pem|tls_key|node_key|ca_key)$")

SAFE_IDS = {"secret_proof", "share_index", "sharemap", "shares_total"}

SANITIZERS = {"hash_secret", "len", "type", "bool", "id", "index_of"}

LOG_METHODS = {"debug", "info", "warn", "warning", "error", "exception",
               "critical", "rate_limited_info"}

REPR_METHODS = {"__repr__", "__str__", "__format__"}


def _terminal(name: str) -> str:
    return name.rsplit(".", 1)[-1]


class SecretChecker:
    name = "secret"
    description = ("secret/share/private-key values flowing into logging, "
                   "exception messages, or __repr__ (cross-function with "
                   "the v2 engine)")
    uses_project = True

    def __init__(self):
        self._project = None

    # -- taint predicates ----------------------------------------------------

    def _is_source(self, module: ModuleInfo, node: ast.AST,
                   tainted: Set[str]) -> Optional[str]:
        """Returns a human name for the secret expression, or None."""
        if isinstance(node, ast.Call):
            fname = dotted(node.func) or ""
            if _terminal(fname) in SANITIZERS:
                return None
            if _terminal(fname) in ("get_share", "load_share"):
                return f"{fname}()"
            if self._project is not None:
                callee = self._project.resolve_call(module, node)
                if callee is not None and callee.returns_secret:
                    return f"{fname}()"
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                hit = self._is_source(module, arg, tainted)
                if hit:
                    return hit
            return None
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                if isinstance(v, ast.FormattedValue):
                    hit = self._is_source(module, v.value, tainted)
                    if hit:
                        return hit
            return None
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            for e in node.elts:
                hit = self._is_source(module, e, tainted)
                if hit:
                    return hit
            return None
        if isinstance(node, ast.Dict):
            for v in node.values:
                hit = self._is_source(module, v, tainted)
                if hit:
                    return hit
            return None
        if isinstance(node, ast.BinOp):
            for side in (node.left, node.right):
                hit = self._is_source(module, side, tainted)
                if hit:
                    return hit
            return None
        if isinstance(node, ast.Attribute):
            d = dotted(node)
            if d is None:
                # chained off a call, e.g. vault.get_share().private
                inner = node.value
                if isinstance(inner, ast.Call):
                    return self._is_source(module, inner, tainted)
                return None
            term = _terminal(d)
            if term in SAFE_IDS:
                return None
            if SECRET_IDS.match(term):
                return d
            return None
        if isinstance(node, ast.Name):
            if node.id in SAFE_IDS:
                return None
            if node.id in tainted or SECRET_IDS.match(node.id):
                return node.id
            return None
        return None

    def _taint_pass(self, module: ModuleInfo, fn: ast.AST) -> Set[str]:
        """One-hop flow: local names assigned from a source expression."""
        tainted: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                if self._is_source(module, node.value, tainted):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            tainted.add(t.id)
        return tainted

    # -- sinks ---------------------------------------------------------------

    def _log_call(self, module: ModuleInfo, node: ast.Call) -> bool:
        if not isinstance(node.func, ast.Attribute):
            return False
        if node.func.attr not in LOG_METHODS:
            return False
        recv = dotted(node.func.value) or ""
        return _terminal(recv) in ("log", "logger", "LOG", "DEFAULT") \
            or recv.endswith(".log")

    def check(self, module: ModuleInfo,
              project=None) -> Iterator[Finding]:
        self._project = project
        for cls, fn in module.functions():
            tainted = self._taint_pass(module, fn)
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    is_log = self._log_call(module, node)
                    is_print = isinstance(node.func, ast.Name) \
                        and node.func.id == "print"
                    if not (is_log or is_print):
                        for finding in self._interproc_sink(module, node,
                                                            tainted):
                            yield finding
                        continue
                    for arg in list(node.args) \
                            + [kw.value for kw in node.keywords]:
                        hit = self._is_source(module, arg, tainted)
                        if hit:
                            sink = "log call" if is_log else "print()"
                            yield Finding(
                                checker=self.name, code="secret-in-log",
                                message=(f"secret-bearing value `{hit}` "
                                         f"reaches a {sink}"),
                                path=module.rel, line=node.lineno,
                                col=node.col_offset)
                elif isinstance(node, ast.Raise) and node.exc is not None:
                    exc = node.exc
                    args = []
                    if isinstance(exc, ast.Call):
                        args = list(exc.args) \
                            + [kw.value for kw in exc.keywords]
                    for arg in args:
                        hit = self._is_source(module, arg, tainted)
                        if hit:
                            yield Finding(
                                checker=self.name,
                                code="secret-in-exception",
                                message=(f"secret-bearing value `{hit}` is "
                                         "embedded in an exception message "
                                         "(exceptions get logged and "
                                         "serialized over RPC)"),
                                path=module.rel, line=node.lineno,
                                col=node.col_offset)
            if getattr(fn, "name", "") in REPR_METHODS:
                for node in ast.walk(fn):
                    if isinstance(node, ast.Return) and node.value is not None:
                        hit = self._is_source(module, node.value, tainted)
                        if hit:
                            yield Finding(
                                checker=self.name, code="secret-in-repr",
                                message=(f"secret-bearing value `{hit}` is "
                                         f"part of {getattr(fn, 'name', '?')}"
                                         " output"),
                                path=module.rel, line=node.lineno,
                                col=node.col_offset)

    def _interproc_sink(self, module: ModuleInfo, node: ast.Call,
                        tainted: Set[str]) -> Iterator[Finding]:
        """v2 sink: a secret expression bound to a callee parameter the
        callee's summary logs — the leak is one frame down, the bug is
        at this call site."""
        if self._project is None:
            return
        callee = self._project.resolve_call(module, node)
        if callee is None or not callee.logged_params:
            return
        for param in sorted(callee.logged_params):
            bound = callee.arg_param(node, param)
            if bound is None:
                continue
            hit = self._is_source(module, bound, tainted)
            if hit:
                yield Finding(
                    checker=self.name, code="secret-interproc-log",
                    message=(f"secret-bearing value `{hit}` is passed as "
                             f"`{param}` to {callee.display}, which logs "
                             "that parameter"),
                    path=module.rel, line=node.lineno,
                    col=node.col_offset)
