"""bounds-discipline: serving-path queues, pools and servers must be
bounded.

The serving plane survives overload by *refusing* work (net/admission.py),
and that only holds if no construct underneath can absorb unbounded work
first: an unbounded `queue.Queue()` buffers a flood instead of shedding
it, a `ThreadPoolExecutor()` without `max_workers` scales threads with
CPU count silently, and `ThreadingHTTPServer` spawns one thread per
request with no ceiling at all — the exact resource-exhaustion bug the
beacon-client security review (arXiv:2109.11677) calls the dominant
practical failure class.

Scope: the serving paths only — `net/`, `http_server.py`, `relay.py`,
and `core/tenancy.py` (the tenant registry sits on every admission
decision and every Control-plane edit: any queue or executor grown there
is flood-reachable, so it must be bounded like the rest of the serving
plane).  Internal planes (DKG broadcast buffers, the aggregator's
partial queue) are ingress-validated and threshold-bounded upstream, so
they keep their simpler constructs.  A deliberate unbounded construct in
scope carries a `tpu-vet: disable=bounds` comment WITH a
justification.

Flagged:
  * ``queue.Queue()`` / ``LifoQueue`` / ``PriorityQueue`` /
    ``SimpleQueue`` with no ``maxsize`` (or an explicit ``maxsize=0``) —
    SimpleQueue cannot be bounded at all.
  * ``ThreadPoolExecutor(...)`` / ``ProcessPoolExecutor(...)`` without
    ``max_workers``.
  * ``ThreadingHTTPServer`` / ``ThreadingTCPServer`` construction or
    subclassing (thread-per-request; use http_server.BoundedHTTPServer).
"""

import ast
from typing import Iterator, Optional

from ..core import Finding
from ..symbols import ModuleInfo, dotted

SCOPE_PREFIXES = ("net/",)
SCOPE_FILES = ("http_server.py", "relay.py", "core/tenancy.py")

BOUNDED_QUEUES = {"queue.Queue", "queue.LifoQueue", "queue.PriorityQueue"}
UNBOUNDABLE_QUEUES = {"queue.SimpleQueue"}
EXECUTORS = {"ThreadPoolExecutor", "ProcessPoolExecutor"}
THREAD_PER_REQUEST = {"ThreadingHTTPServer", "ThreadingTCPServer",
                      "ThreadingUnixStreamServer"}


def _in_scope(rel: str) -> bool:
    return any(rel.startswith(p) for p in SCOPE_PREFIXES) \
        or rel in SCOPE_FILES


def _positive_const(node: ast.AST) -> Optional[bool]:
    """True/False for a literal int bound; None when the value is
    computed (give it the benefit of the doubt — the bound exists)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value > 0
    return None


class BoundsChecker:
    name = "bounds"
    description = ("unbounded queue/executor/thread-per-request server "
                   "construction on serving paths (net/, http_server.py, "
                   "relay.py)")

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        if not _in_scope(module.rel):
            return
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                for base in node.bases:
                    qual = module.resolve(dotted(base) or "")
                    if qual.split(".")[-1] in THREAD_PER_REQUEST:
                        yield self._finding(
                            module, node, "bounds-thread-per-request",
                            f"class {node.name} inherits "
                            f"{qual.split('.')[-1]}: thread-per-request "
                            "with no ceiling; build on a bounded worker "
                            "pool (http_server.BoundedHTTPServer)")
                continue
            if not isinstance(node, ast.Call):
                continue
            qual = module.resolve(dotted(node.func) or "")
            last = qual.split(".")[-1]
            if qual in UNBOUNDABLE_QUEUES:
                yield self._finding(
                    module, node, "bounds-unbounded-queue",
                    "queue.SimpleQueue cannot be bounded; use "
                    "queue.Queue(maxsize=...) on serving paths")
            elif qual in BOUNDED_QUEUES:
                if not self._has_bound(node, "maxsize"):
                    yield self._finding(
                        module, node, "bounds-unbounded-queue",
                        f"{qual}() without a positive maxsize buffers an "
                        "unbounded backlog on a serving path; bound it "
                        "(shedding beats buffering under overload)")
            elif last in EXECUTORS:
                if not self._has_bound(node, "max_workers"):
                    yield self._finding(
                        module, node, "bounds-unbounded-executor",
                        f"{last}() without max_workers sizes the pool "
                        "from the machine, not the workload; pass an "
                        "explicit bound on serving paths")
            elif last in THREAD_PER_REQUEST:
                yield self._finding(
                    module, node, "bounds-thread-per-request",
                    f"{last} spawns one thread per request with no "
                    "ceiling; use a bounded worker pool "
                    "(http_server.BoundedHTTPServer)")

    @staticmethod
    def _has_bound(node: ast.Call, kw_name: str) -> bool:
        if node.args:
            first = _positive_const(node.args[0])
            return first is not False    # literal 0 is "unbounded" spelled out
        for kw in node.keywords:
            if kw.arg == kw_name:
                return _positive_const(kw.value) is not False
            if kw.arg is None:
                return True              # **kwargs: cannot prove either way
        return False

    def _finding(self, module: ModuleInfo, node: ast.AST, code: str,
                 message: str) -> Finding:
        return Finding(checker=self.name, code=code, message=message,
                       path=module.rel, line=node.lineno,
                       col=node.col_offset)
