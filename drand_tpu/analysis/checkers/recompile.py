"""recompile: jit cache-key hygiene — no fresh program flavors per call.

PR 11's watchdog-floor incident was a single callsite deriving a Python
int from device data (`.item()`) and passing it where the jitted kernel
treated it as a trace-time constant: every distinct value minted a fresh
program flavor, compile time ate the round budget, and the watchdog
fired on a healthy chip.  The kernels in `crypto/` and `ops/` hold the
cache-key discipline by construction (flavor constants are config-derived
at factory time, placement is centralized in
`crypto/device_pool.build_round_sharding`); this checker keeps it held.

Scope: `crypto/` + `ops/` for the dispatch-hygiene codes; the placement
code applies everywhere (a per-call `Mesh(...)` in `beacon/` would churn
compilation just the same).

Codes:

  * ``recompile-data-dependent-static`` — a static-arg slot of a jitted
    function receives `.item()` / `.tolist()` / `int(x)` / `float(x)`
    of a runtime value: every distinct value is a fresh program flavor.
    (Cross-function: the static slots come from the callee's phase-1
    summary, so the callsite and the `@jit(static_argnums=...)` def can
    live in different modules.)
  * ``recompile-data-dependent-flavor`` — same data-dependent shapes
    passed to a `jit_factory` function (one that returns `jax.jit(...)`):
    each call already builds a fresh program; feeding it data-dependent
    flavor constants makes the cache key unbounded.
  * ``recompile-unhashable-static`` — a list/dict/set display (or a
    mutable default on a static param) in a static-arg slot: jit hashes
    static args, unhashables raise at dispatch, and "fixing" it with
    id()-keyed wrappers silently unbounds the cache.
  * ``recompile-per-call-placement`` — `Mesh` / `NamedSharding` /
    `PositionalSharding` constructed outside `crypto/device_pool.py`, or
    inside any loop: placement objects belong in the one cached factory,
    not on the dispatch path.
"""

import ast
from typing import Iterator, Optional

from ..core import Finding
from ..symbols import ModuleInfo, dotted

SCOPES = ("crypto/", "ops/")

# constructors that mint placement objects; allowed only in the pool
PLACEMENT_CTORS = {
    "Mesh", "jax.sharding.Mesh", "sharding.Mesh", "maps.Mesh",
    "NamedSharding", "jax.sharding.NamedSharding", "sharding.NamedSharding",
    "PositionalSharding", "jax.sharding.PositionalSharding",
    "sharding.PositionalSharding",
}
PLACEMENT_HOME = "crypto/device_pool.py"

# conversions that turn runtime (device) data into Python scalars
SCALAR_EXTRACTORS = {"item", "tolist"}
SCALAR_CASTS = {"int", "float"}


def _in_scope(rel: str) -> bool:
    return any(rel.startswith(s) or f"/{s}" in f"/{rel}" for s in SCOPES)


def _data_dependent(node: ast.AST) -> Optional[str]:
    """A human label when `node` derives a Python scalar from runtime
    data; None otherwise.  Shape reads (`x.shape[0]`, `len(x)`) are
    exempt — shapes legitimately select program flavors."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            if isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in SCALAR_EXTRACTORS:
                return f".{sub.func.attr}()"
            if isinstance(sub.func, ast.Name) \
                    and sub.func.id in SCALAR_CASTS \
                    and len(sub.args) == 1 \
                    and isinstance(sub.args[0], (ast.Name, ast.Attribute)):
                inner = dotted(sub.args[0]) or ""
                if ".shape" not in f".{inner}":
                    return f"{sub.func.id}({inner})"
    return None


def _unhashable(node: ast.AST) -> bool:
    return isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                             ast.DictComp, ast.SetComp))


class RecompileChecker:
    name = "recompile"
    description = ("jit cache-key hygiene: data-dependent flavor constants, "
                   "unhashable static args, per-call placement construction")
    uses_project = True

    def check(self, module: ModuleInfo,
              project: Optional[object] = None) -> Iterator[Finding]:
        yield from self._placement(module)
        if not _in_scope(module.rel):
            return
        yield from self._static_defaults(module, project)
        if project is None:
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = project.resolve_call(module, node)
            if callee is None:
                continue
            if callee.static_args:
                yield from self._static_site(module, node, callee)
            if callee.jit_factory:
                yield from self._factory_site(module, node, callee)

    # -- placement ------------------------------------------------------------

    def _placement(self, module: ModuleInfo) -> Iterator[Finding]:
        at_home = module.rel == PLACEMENT_HOME \
            or module.rel.endswith("/" + PLACEMENT_HOME)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            qual = module.resolve(dotted(node.func) or "")
            if qual not in PLACEMENT_CTORS:
                continue
            in_loop = module.enclosing(node, ast.For, ast.While,
                                       ast.AsyncFor) is not None
            if at_home and not in_loop:
                continue
            where = "inside a loop" if in_loop else \
                f"outside {PLACEMENT_HOME}"
            yield Finding(
                checker=self.name, code="recompile-per-call-placement",
                message=(f"{qual}(...) constructed {where}; placement "
                         "objects belong in crypto/device_pool."
                         "build_round_sharding (cached), not on the "
                         "dispatch path"),
                path=module.rel, line=node.lineno, col=node.col_offset)

    # -- static-arg hygiene ---------------------------------------------------

    def _static_defaults(self, module: ModuleInfo,
                         project) -> Iterator[Finding]:
        """Mutable default on a static param of a jitted def."""
        if project is None:
            return
        for (rel, qual), s in project.functions.items():
            if rel != module.rel or not s.static_args:
                continue
            for p in s.static_args:
                d = s.defaults.get(p)
                if d is not None and _unhashable(d):
                    yield Finding(
                        checker=self.name,
                        code="recompile-unhashable-static",
                        message=(f"static arg `{p}` of {s.display} has an "
                                 "unhashable default; jit hashes static "
                                 "args — use a tuple or None"),
                        path=module.rel, line=d.lineno, col=d.col_offset)

    def _static_site(self, module: ModuleInfo, call: ast.Call,
                     callee) -> Iterator[Finding]:
        for p in sorted(callee.static_args):
            bound = callee.arg_param(call, p)
            if bound is None:
                continue
            if _unhashable(bound):
                yield Finding(
                    checker=self.name, code="recompile-unhashable-static",
                    message=(f"unhashable value passed as static arg `{p}` "
                             f"of {callee.display}; jit hashes static args "
                             "— pass a tuple"),
                    path=module.rel, line=call.lineno, col=call.col_offset)
                continue
            label = _data_dependent(bound)
            if label:
                yield Finding(
                    checker=self.name,
                    code="recompile-data-dependent-static",
                    message=(f"data-dependent scalar ({label}) passed as "
                             f"static arg `{p}` of {callee.display}; every "
                             "distinct value mints a fresh program flavor "
                             "(the PR 11 watchdog-floor class)"),
                    path=module.rel, line=call.lineno, col=call.col_offset)

    def _factory_site(self, module: ModuleInfo, call: ast.Call,
                      callee) -> Iterator[Finding]:
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            label = _data_dependent(arg)
            if label:
                yield Finding(
                    checker=self.name,
                    code="recompile-data-dependent-flavor",
                    message=(f"data-dependent scalar ({label}) passed to "
                             f"jit factory {callee.display}; factory args "
                             "are trace-time flavor constants — derive "
                             "them from config, not device data"),
                    path=module.rel, line=call.lineno, col=call.col_offset)
                break
