"""verifier-discipline: all device verification flows through the
resident verify service.

The verify service (crypto/verify_service.py) exists so the device sees
ONE owner — coalesced canonical batches, priority lanes, a persistent
mesh — instead of per-consumer ad-hoc dispatch.  That architecture only
holds if consumers cannot quietly regrow private dispatch paths, so
constructing `BatchBeaconVerifier` directly is banned outside `crypto/`
(the service and the crypto package internals).  Everything else gets a
`VerifyService.handle(...)` (or passes `device=False` for the jax-free
`HostBatchVerifier` fallback behind the same submit API).
"""

import ast
from typing import Iterator

from ..core import Finding
from ..symbols import ModuleInfo, dotted

TARGET = "BatchBeaconVerifier"

# modules allowed to construct the raw verifier: the crypto package owns
# the device pipelines and the service that fronts them
ALLOWED_PREFIX = "crypto/"


class VerifierChecker:
    name = "verifier"
    description = ("direct BatchBeaconVerifier construction outside "
                   "crypto/ (bypasses the resident verify service)")

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        if module.rel.startswith(ALLOWED_PREFIX):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            qual = module.resolve(dotted(node.func) or "")
            if qual.split(".")[-1] != TARGET:
                continue
            yield Finding(
                checker=self.name, code="verifier-direct-construction",
                message=(f"direct {TARGET}(...) construction outside "
                         "crypto/; submit through the resident verify "
                         "service (crypto/verify_service.py handle/"
                         "submit API) so dispatch stays coalesced and "
                         "priority-laned"),
                path=module.rel, line=node.lineno, col=node.col_offset)
