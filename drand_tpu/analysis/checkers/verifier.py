"""verifier-discipline: all device verification flows through the
resident verify service, and all device ENUMERATION through its pool.

The verify service (crypto/verify_service.py) exists so the device sees
ONE owner — coalesced canonical batches, priority lanes, per-handle
device groups over a persistent pool — instead of per-consumer ad-hoc
dispatch.  That architecture only holds if consumers cannot quietly
regrow private dispatch paths, so two rules:

  * constructing `BatchBeaconVerifier` directly is banned outside
    `crypto/` (the service and the crypto package internals own the
    pipelines).  Everything else gets a `VerifyService.handle(...)` (or
    passes `device=False` for the jax-free `HostBatchVerifier` fallback
    behind the same submit API).
  * calling `jax.devices()` / `jax.local_devices()` is banned outside
    `crypto/device_pool.py` — the pool owns inventory, group layout and
    the pool-wide mesh, and device enumeration can block indefinitely
    while holding jax's global client lock when an accelerator tunnel is
    down (drand_tpu/accel.py), so there must be exactly one, cached,
    call site.  Bench/dryrun tooling outside the package carries its own
    justified suppressions.
"""

import ast
from typing import Iterator

from ..core import Finding
from ..symbols import ModuleInfo, dotted

TARGET = "BatchBeaconVerifier"

# modules allowed to construct the raw verifier: the crypto package owns
# the device pipelines and the service that fronts them
ALLOWED_PREFIX = "crypto/"

# the one sanctioned device-enumeration call site (the pool)
DEVICE_CALLS = {"jax.devices", "jax.local_devices"}
POOL_MODULE = "crypto/device_pool.py"


class VerifierChecker:
    name = "verifier"
    description = ("direct BatchBeaconVerifier construction outside "
                   "crypto/ (bypasses the resident verify service) and "
                   "jax device enumeration outside crypto/device_pool.py")

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        construction_exempt = module.rel.startswith(ALLOWED_PREFIX)
        enumeration_exempt = module.rel == POOL_MODULE
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            qual = module.resolve(dotted(node.func) or "")
            if not construction_exempt and qual.split(".")[-1] == TARGET:
                yield Finding(
                    checker=self.name, code="verifier-direct-construction",
                    message=(f"direct {TARGET}(...) construction outside "
                             "crypto/; submit through the resident verify "
                             "service (crypto/verify_service.py handle/"
                             "submit API) so dispatch stays coalesced and "
                             "priority-laned"),
                    path=module.rel, line=node.lineno, col=node.col_offset)
            elif not enumeration_exempt and qual in DEVICE_CALLS:
                yield Finding(
                    checker=self.name, code="verifier-device-enumeration",
                    message=(f"{qual}() outside crypto/device_pool.py; "
                             "the device pool owns inventory and group "
                             "layout (and enumeration hangs on a dead "
                             "accelerator tunnel) — use device_pool."
                             "jax_devices() or a DevicePool"),
                    path=module.rel, line=node.lineno, col=node.col_offset)
