"""trace-safety: JAX pitfalls in the device code (ops/, crypto/batch.py).

Inside a jitted function every array argument is a tracer: Python `if`/
`while`/`for` on one raises (or silently specializes) at trace time,
`.item()/int()/float()` forces a device sync that kills the whole
pipelined batch, and mutating captured Python state bakes one trace's
view into the compiled program forever (the classic "works once, wrong
on the second call" bug).  The repo has already shipped one of these —
the `crypto/batch.py` pad-lane mask shadowing a traced `n` (CHANGES.md,
PR 1) — which is exactly the class this checker pins down.

Scope: files under ops/ and crypto/batch.py (SCOPES) — the rest of the
codebase is host code where Python control flow is the point.

A second, host-level pass (`sync-in-loop`, ISSUE 10) covers crypto/
orchestration code: synchronous device readback (np.asarray / bool() /
int() / float() / .item() / .block_until_ready() / jax.device_get) on a
device-produced value INSIDE a per-chunk for/while loop serializes the
whole stream — every iteration pays a full interconnect round trip (the
r5 finding: ~1 RPC latency of pure stall per chunk).  Hot-path loops
must stay async (pack/dispatch/resolve with a depth-k window) and sync
once per stream.  Device taint: values from `dispatch_packed`/
`_rlc_dispatch` calls or from invoking a compiled `*_pipeline` object.

Taint: parameters of a jitted function are traced; values derived from
them are traced; `.shape/.ndim/.dtype/.size`, `len()`, and parameters
named in `static_argnums`/`static_argnames` are static and break the
chain.  Conservative by design: only Name-rooted taint is tracked, so a
finding is near-certainly real.
"""

import ast
from typing import Iterator, List, Optional, Set

from ..core import Finding
from ..symbols import ModuleInfo, dotted

SCOPES = ("ops/", "crypto/batch.py")
# the sync-in-loop pass covers the crypto/ hot-path orchestration code
SYNC_SCOPES = ("crypto/",)

STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
STATIC_CALLS = {"len", "isinstance", "type", "range"}  # range(static) common
CONCRETIZERS = {"int", "float", "bool", "complex"}
CONCRETIZE_METHODS = {"item", "tolist"}
JIT_NAMES = {"jit", "jax.jit", "pjit", "jax.pjit"}

# sync-in-loop: producers whose results are device values (async until
# read), sync sinks that force the readback, and the unambiguous
# blocking calls that are findings on their own
SYNC_PRODUCER_METHODS = {"dispatch_packed", "_rlc_dispatch"}
SYNC_SINKS = {"bool", "int", "float"}
SYNC_SINK_METHODS = {"item", "tolist"}
SYNC_BLOCKERS = {"jax.block_until_ready", "jax.device_get"}

# host-hash-in-loop (ISSUE 14): per-lane host hashing inside a loop on a
# hot-path module is O(n) GIL-bound work per chunk — the exact stage the
# device hash-to-field front removed from steady-state packing.  Flags
# direct hashlib constructions AND the known host hash-to-field/digest
# helpers when called per element in a for/while/comprehension.
# Sanctioned sites (the parity oracle and the below-threshold host
# fallback) carry justified `tpu-vet: disable=trace` suppressions.
HASH_SCOPES = ("ops/", "crypto/batch.py", "crypto/partials.py",
               "crypto/verify_service.py")
HOST_HASH_HELPERS = {"hash_to_field_fp", "hash_to_field_fp2",
                     "expand_message_xmd", "hash_to_curve_g1",
                     "hash_to_curve_g2", "digest_beacon"}


def _in_scope(rel: str) -> bool:
    return any(rel.startswith(s) or f"/{s}" in f"/{rel}" for s in SCOPES) \
        or rel.endswith("batch.py") and "crypto" in rel


def _in_sync_scope(rel: str) -> bool:
    return any(rel.startswith(s) or f"/{s}" in f"/{rel}"
               for s in SYNC_SCOPES)


def _in_hash_scope(rel: str) -> bool:
    return any(rel.startswith(s) or f"/{s}" in f"/{rel}"
               for s in HASH_SCOPES)


class TraceChecker:
    name = "trace"
    description = ("Python control flow on tracers, .item()/int() inside "
                   "jit, mutated captured state")

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        if _in_sync_scope(module.rel):
            yield from self._check_sync_loops(module)
        if _in_hash_scope(module.rel):
            yield from self._check_hash_loops(module)
        if not _in_scope(module.rel):
            return
        for fn, static in self._jitted_functions(module):
            yield from self._check_jitted(module, fn, static)

    # -- host-hash-in-loop (hot-path pack stage pass) ------------------------

    _LOOPY = (ast.For, ast.While, ast.ListComp, ast.SetComp,
              ast.GeneratorExp, ast.DictComp)

    def _check_hash_loops(self, module: ModuleInfo) -> Iterator[Finding]:
        jitted = {fn for fn, _ in self._jitted_functions(module)}
        seen = set()
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node not in jitted:
                for f in self._hash_loops_in(module, node):
                    key = (f.line, f.col)
                    if key not in seen:         # nested loops: flag once
                        seen.add(key)
                        yield f

    def _is_host_hash_call(self, module: ModuleInfo,
                           call: ast.Call) -> Optional[str]:
        d = module.resolve(dotted(call.func) or "") or ""
        if d.startswith("hashlib."):
            return d
        leaf = d.rsplit(".", 1)[-1]
        if leaf in HOST_HASH_HELPERS:
            return leaf + "()"
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in HOST_HASH_HELPERS:
            return call.func.attr + "()"
        return None

    def _hash_loops_in(self, module: ModuleInfo,
                       fn: ast.AST) -> Iterator[Finding]:
        for loop in self._walk_scope(fn):
            if not isinstance(loop, self._LOOPY):
                continue
            for node in self._walk_scope(loop):
                if node is loop or not isinstance(node, ast.Call):
                    continue
                what = self._is_host_hash_call(module, node)
                if what:
                    yield Finding(
                        checker=self.name, code="trace-host-hash-in-loop",
                        message=(f"per-lane host hash {what} inside a "
                                 f"loop in {fn.name}() is O(n) GIL-bound "
                                 "pack work per chunk; ship raw message "
                                 "words and hash on device "
                                 "(ops/h2c.py device hash-to-field), or "
                                 "suppress at a sanctioned parity-oracle/"
                                 "fallback site"),
                        path=module.rel, line=node.lineno,
                        col=node.col_offset)

    # -- sync-in-loop (host orchestration pass) ------------------------------

    @staticmethod
    def _walk_scope(fn: ast.AST):
        """Walk a function's OWN body without descending into nested
        function definitions — each nested function is its own scope and
        gets its own standalone visit (a jitted nested `run` is traced
        device code and must not be judged by host-loop rules through
        its enclosing factory)."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                stack.extend(ast.iter_child_nodes(node))

    def _check_sync_loops(self, module: ModuleInfo) -> Iterator[Finding]:
        jitted = {fn for fn, _ in self._jitted_functions(module)}
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node not in jitted:
                yield from self._sync_loops_in(module, node)

    def _device_tainted(self, module: ModuleInfo, fn: ast.AST) -> Set[str]:
        """Names in `fn` bound to device values (async until read):
        results of dispatch_packed/_rlc_dispatch, or of calling a name
        that was itself bound from a `*_pipeline*` factory call."""
        device_fns: Set[str] = set()
        tainted: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for node in self._walk_scope(fn):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    continue
                callee = dotted(node.value.func) or ""
                leaf = callee.rsplit(".", 1)[-1]
                is_dev = (leaf in SYNC_PRODUCER_METHODS
                          or leaf in device_fns
                          or (isinstance(node.value.func, ast.Name)
                              and node.value.func.id in device_fns))
                is_factory = "_pipeline" in leaf
                for t in node.targets:
                    if not isinstance(t, ast.Name):
                        continue
                    if is_dev and t.id not in tainted:
                        tainted.add(t.id)
                        changed = True
                    if is_factory and t.id not in device_fns:
                        device_fns.add(t.id)
                        changed = True
        self._device_fns = device_fns
        return tainted

    def _is_device_expr(self, module: ModuleInfo, e: ast.AST,
                        tainted: Set[str]) -> bool:
        for sub in ast.walk(e):
            if isinstance(sub, ast.Name) and sub.id in tainted:
                return True
            if isinstance(sub, ast.Call):
                callee = dotted(sub.func) or ""
                leaf = callee.rsplit(".", 1)[-1]
                if leaf in SYNC_PRODUCER_METHODS \
                        or leaf in getattr(self, "_device_fns", set()):
                    return True
        return False

    def _sync_loops_in(self, module: ModuleInfo,
                       fn: ast.AST) -> Iterator[Finding]:
        tainted = self._device_tainted(module, fn)
        for loop in self._walk_scope(fn):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for node in self._walk_scope(loop):
                if node is loop or not isinstance(node, ast.Call):
                    continue
                d = module.resolve(dotted(node.func) or "")
                if d in SYNC_BLOCKERS:
                    yield self._sync_finding(module, fn, node,
                                             d.rsplit(".", 1)[-1] + "()")
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "block_until_ready":
                    yield self._sync_finding(module, fn, node,
                                             ".block_until_ready()")
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr in SYNC_SINK_METHODS \
                        and self._is_device_expr(module, node.func.value,
                                                 tainted):
                    yield self._sync_finding(module, fn, node,
                                             f".{node.func.attr}()")
                elif ((isinstance(node.func, ast.Name)
                       and node.func.id in SYNC_SINKS)
                      or d == "numpy.asarray") and node.args \
                        and self._is_device_expr(module, node.args[0],
                                                 tainted):
                    label = d.rsplit(".", 1)[-1] if d == "numpy.asarray" \
                        else node.func.id
                    yield self._sync_finding(module, fn, node,
                                             f"{label}()")

    def _sync_finding(self, module: ModuleInfo, fn: ast.AST,
                      node: ast.AST, what: str) -> Finding:
        return Finding(
            checker=self.name, code="trace-sync-in-loop",
            message=(f"synchronous device readback {what} inside a "
                     f"per-chunk loop in {fn.name}() serializes the "
                     "stream (one interconnect round trip per "
                     "iteration); keep the loop async "
                     "(pack/dispatch/resolve, depth-k window) and sync "
                     "once per stream"),
            path=module.rel, line=node.lineno, col=node.col_offset)

    # -- jit discovery -------------------------------------------------------

    def _jit_decorator(self, module: ModuleInfo,
                       dec: ast.AST) -> Optional[ast.Call]:
        """Returns the jit Call node (for static_arg* extraction) or a
        dummy marker when the decorator is a bare `@jit`."""
        d = dotted(dec)
        if d and module.resolve(d) in JIT_NAMES:
            return ast.Call(func=dec, args=[], keywords=[])
        if isinstance(dec, ast.Call):
            d = dotted(dec.func)
            if d and module.resolve(d) in JIT_NAMES:
                return dec
            # functools.partial(jax.jit, static_argnums=...)
            if d and module.resolve(d).endswith("partial") and dec.args:
                inner = dotted(dec.args[0])
                if inner and module.resolve(inner) in JIT_NAMES:
                    return dec
        return None

    def _static_params(self, fn: ast.AST, call: ast.Call) -> Set[str]:
        params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        static: Set[str] = set()
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                nums: List[int] = []
                if isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, int):
                    nums = [kw.value.value]
                elif isinstance(kw.value, (ast.Tuple, ast.List)):
                    nums = [e.value for e in kw.value.elts
                            if isinstance(e, ast.Constant)]
                for n in nums:
                    if 0 <= n < len(params):
                        static.add(params[n])
            elif kw.arg == "static_argnames":
                if isinstance(kw.value, ast.Constant):
                    static.add(str(kw.value.value))
                elif isinstance(kw.value, (ast.Tuple, ast.List)):
                    static |= {str(e.value) for e in kw.value.elts
                               if isinstance(e, ast.Constant)}
        return static

    def _jitted_functions(self, module: ModuleInfo):
        # decorated defs
        wrapped: Set[str] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                if d and module.resolve(d) in JIT_NAMES and node.args:
                    inner = node.args[0]
                    if isinstance(inner, ast.Name):
                        wrapped.add(inner.id)   # f2 = jax.jit(f)
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                call = self._jit_decorator(module, dec)
                if call is not None:
                    yield node, self._static_params(node, call)
                    break
            else:
                if node.name in wrapped:
                    yield node, set()

    # -- taint + findings ----------------------------------------------------

    def _is_static_expr(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Attribute) and node.attr in STATIC_ATTRS:
            return True
        if isinstance(node, ast.Subscript):
            # x.shape[0] is static
            return self._is_static_expr(node.value)
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            if d and d.rsplit(".", 1)[-1] in STATIC_CALLS | {"shape"}:
                return True
        return False

    def _mentions_tainted(self, node: ast.AST, tainted: Set[str]
                          ) -> Optional[str]:
        for sub in ast.walk(node):
            if self._is_static_expr(sub):
                continue
            if isinstance(sub, ast.Name) and sub.id in tainted:
                # static wrapper anywhere above this name?
                if self._under_static(node, sub):
                    continue
                return sub.id
        return None

    def _under_static(self, root: ast.AST, target: ast.AST) -> bool:
        """True when `target` only appears under a static extractor
        (shape/ndim/dtype/len) within `root`."""
        parents = {}
        for n in ast.walk(root):
            for c in ast.iter_child_nodes(n):
                parents[id(c)] = n
        cur = parents.get(id(target))
        while cur is not None:
            if self._is_static_expr(cur):
                return True
            cur = parents.get(id(cur))
        return False

    def _check_jitted(self, module: ModuleInfo, fn: ast.AST,
                      static: Set[str]) -> Iterator[Finding]:
        params = {a.arg for a in fn.args.posonlyargs + fn.args.args
                  + fn.args.kwonlyargs} - static - {"self"}
        tainted: Set[str] = set(params)
        # fixpoint over simple assignments: y = f(x) with x tainted -> y
        changed = True
        while changed:
            changed = False
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) \
                        and not self._is_static_expr(node.value) \
                        and self._mentions_tainted(node.value, tainted):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id not in tainted:
                            tainted.add(t.id)
                            changed = True
                elif isinstance(node, (ast.For,)) \
                        and isinstance(node.target, ast.Name) \
                        and node.target.id not in tainted \
                        and not self._is_static_expr(node.iter) \
                        and self._mentions_tainted(node.iter, tainted):
                    tainted.add(node.target.id)
                    changed = True

        locals_: Set[str] = set(params) | static
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        locals_.add(t.id)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                locals_.add(node.name)

        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)):
                hit = self._mentions_tainted(node.test, tainted)
                if hit:
                    kind = "if" if isinstance(node, ast.If) else "while"
                    yield Finding(
                        checker=self.name, code="trace-python-branch",
                        message=(f"Python `{kind}` on traced value `{hit}` "
                                 f"inside jitted {fn.name}(); use "
                                 "lax.cond/select or a mask"),
                        path=module.rel, line=node.lineno,
                        col=node.col_offset)
            elif isinstance(node, ast.For):
                hit = self._mentions_tainted(node.iter, tainted)
                if hit:
                    yield Finding(
                        checker=self.name, code="trace-python-loop",
                        message=(f"Python `for` over traced value `{hit}` "
                                 f"inside jitted {fn.name}(); use "
                                 "lax.scan/fori_loop"),
                        path=module.rel, line=node.lineno,
                        col=node.col_offset)
            elif isinstance(node, ast.Call):
                d = dotted(node.func) or ""
                if isinstance(node.func, ast.Name) \
                        and node.func.id in CONCRETIZERS and node.args:
                    hit = self._mentions_tainted(node.args[0], tainted)
                    if hit:
                        yield Finding(
                            checker=self.name, code="trace-concretize",
                            message=(f"{node.func.id}() on traced value "
                                     f"`{hit}` inside jitted {fn.name}() "
                                     "forces a trace-time concretization"),
                            path=module.rel, line=node.lineno,
                            col=node.col_offset)
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr in CONCRETIZE_METHODS:
                    hit = self._mentions_tainted(node.func.value, tainted)
                    if hit:
                        yield Finding(
                            checker=self.name, code="trace-concretize",
                            message=(f".{node.func.attr}() on traced value "
                                     f"`{hit}` inside jitted {fn.name}() "
                                     "forces a device sync"),
                            path=module.rel, line=node.lineno,
                            col=node.col_offset)
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("append", "extend", "update",
                                               "add", "insert", "pop",
                                               "setdefault") \
                        and isinstance(node.func.value, ast.Name):
                    name = node.func.value.id
                    if name not in locals_ and name not in module.imports \
                            and name not in module.module_defs:
                        yield Finding(
                            checker=self.name, code="trace-captured-mutation",
                            message=(f"jitted {fn.name}() mutates captured "
                                     f"state `{name}.{node.func.attr}(...)`; "
                                     "one trace's view is baked into the "
                                     "compiled program"),
                            path=module.rel, line=node.lineno,
                            col=node.col_offset)
