"""threadlife: every thread has a registered name and a stop path.

PRs 7, 8, and 12 each hand-fixed the same leak: a service thread started
in one place with no join reachable from the owner's `stop()`, found
only when the chaos harness counted threads at teardown.  The checker
moves that from runtime archaeology to lint time.

Rules (non-test code only; pytest owns thread hygiene in tests):

  * ``threadlife-unnamed`` — `threading.Thread(...)` without ``name=``:
    an anonymous thread in a stack dump is unattributable.
  * ``threadlife-unregistered-name`` — the static prefix of the name
    (the literal part, for f-strings the leading literal) is not in the
    project registry below.  The registry is the debugging contract:
    `py-spy dump` output groups by these prefixes.
  * ``threadlife-no-join`` — a thread stored on ``self`` whose ``join``
    is not reachable from a stop root (`stop`/`close`/`shutdown`/
    `terminate`/`abort`/`__exit__`) by walking intra-class `self.`
    calls.  A class with a thread attribute and no stop root at all is
    flagged too.
  * ``threadlife-orphan`` — a fire-and-forget start: an unbound
    `threading.Thread(...).start()`, or a local thread that is started
    but never joined, returned, stored, or handed to another call.
    Returning the thread transfers ownership to the caller — and with
    the phase-1 project, a local assigned from a function whose summary
    says ``returns_thread`` is held to the same rules as a local
    constructed here.
"""

import ast
import os
from typing import Dict, Iterator, List, Optional, Set

from ..core import Finding
from ..symbols import ClassInfo, ModuleInfo, dotted, walk_scope

THREAD_CTOR = "threading.Thread"

STOP_ROOTS = {"stop", "close", "shutdown", "terminate", "abort", "__exit__"}

# the project thread-name registry: every service thread's name starts
# with one of these (py-spy/faulthandler dumps group by prefix)
REGISTERED_PREFIXES = (
    "rest-",            # http_server workers + edge
    "thr-mon-",         # metrics threshold monitor
    "metrics-",         # metrics exporter http
    "relay-", "s3-", "http-",      # relay pumps + servers
    "verify-",          # verify service scheduler/watchdog/probe
    "aggregator", "watch-",        # chainstore/client aggregation
    "sync-",            # sync manager + stream pump
    "handel-",          # handel aggregation overlay
    "ticker",           # round ticker
    "handler-", "catchup-",        # beacon node
    "callback-",        # store callback fan-out
    "speed-test",       # optimizing client prober
    "integrity-", "transition-",   # beacon process maintenance
    "dkg-",             # DKG session/broadcast
    "check-chain", "follow-",      # daemon utilities
    "partial-",         # partial-signature send fan-out
    "stop-",            # async stop trampolines
    "loadgen-", "bench-",          # operator tools
    "probe-",           # preflight probes
    "chaos-",           # chaos proxy accept loop + stream pumps
)


def _is_test_code(rel: str) -> bool:
    base = os.path.basename(rel)
    return base.startswith("test_") or base.endswith("_test.py") \
        or rel.startswith("tests/") or "/tests/" in rel \
        or base in ("conftest.py", "chaos.py")


def _is_thread_ctor(module: ModuleInfo, node: ast.AST) -> bool:
    return isinstance(node, ast.Call) \
        and module.resolve(dotted(node.func) or "") == THREAD_CTOR


def _static_prefix(name_expr: ast.AST) -> Optional[str]:
    """The literal leading part of a name expression; None when the name
    is fully dynamic (flagged — a registry cannot match it)."""
    if isinstance(name_expr, ast.Constant) and isinstance(name_expr.value,
                                                          str):
        return name_expr.value
    if isinstance(name_expr, ast.JoinedStr) and name_expr.values:
        head = name_expr.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            return head.value
    return None


class ThreadLifeChecker:
    name = "threadlife"
    description = ("threads must carry registered name prefixes and a "
                   "join/stop path reachable from the owner's stop()/close()")
    uses_project = True

    def check(self, module: ModuleInfo,
              project: Optional[object] = None) -> Iterator[Finding]:
        if _is_test_code(module.rel):
            return
        yield from self._names(module)
        for info in module.classes:
            yield from self._join_paths(module, info)
        for cls, fn in module.functions():
            yield from self._orphans(module, fn, project)

    # -- naming ---------------------------------------------------------------

    def _names(self, module: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if not _is_thread_ctor(module, node):
                continue
            name_expr = None
            for kw in node.keywords:
                if kw.arg == "name":
                    name_expr = kw.value
            if name_expr is None:
                yield Finding(
                    checker=self.name, code="threadlife-unnamed",
                    message=("threading.Thread(...) without name=; an "
                             "anonymous thread in a py-spy dump is "
                             "unattributable — use a registered prefix"),
                    path=module.rel, line=node.lineno, col=node.col_offset)
                continue
            prefix = _static_prefix(name_expr)
            if prefix is None or not any(
                    prefix.startswith(p) for p in REGISTERED_PREFIXES):
                shown = prefix if prefix is not None else "<dynamic>"
                yield Finding(
                    checker=self.name, code="threadlife-unregistered-name",
                    message=(f"thread name `{shown}...` does not start "
                             "with a registered prefix (see "
                             "analysis/checkers/threadlife.py registry)"),
                    path=module.rel, line=node.lineno, col=node.col_offset)

    # -- join reachability ----------------------------------------------------

    def _join_paths(self, module: ModuleInfo,
                    info: ClassInfo) -> Iterator[Finding]:
        thread_attrs = [a for a, k in info.attr_kinds.items()
                        if k == "thread"]
        if not thread_attrs:
            return
        # method -> methods it calls via self.
        edges: Dict[str, Set[str]] = {}
        join_sites: Dict[str, Set[str]] = {}     # attr -> methods joining it
        for mname, fn in info.methods.items():
            edges[mname] = set()
            # local -> thread attrs it may alias.  Collected BEFORE the
            # join scan (walk order is not source order) and through the
            # idioms the codebase actually uses: plain `t = self._thread`,
            # the swap `t, self._thread = self._thread, None`, and a
            # for-loop over a collection holding aliases
            # (`for t in threads + [wd, probe]: t.join(...)`).
            aliases: Dict[str, Set[str]] = {}

            def note_alias(target: ast.AST, value: ast.AST) -> None:
                if not isinstance(target, ast.Name):
                    return
                d = dotted(value) or ""
                if d.startswith("self.") and d.count(".") == 1 \
                        and d[5:] in thread_attrs:
                    aliases.setdefault(target.id, set()).add(d[5:])

            for _ in range(2):       # second pass closes alias-of-alias
                for node in walk_scope(fn):
                    if isinstance(node, ast.Assign):
                        for t in node.targets:
                            if isinstance(t, ast.Tuple) \
                                    and isinstance(node.value, ast.Tuple) \
                                    and len(t.elts) == len(node.value.elts):
                                for te, ve in zip(t.elts, node.value.elts):
                                    note_alias(te, ve)
                            else:
                                note_alias(t, node.value)
                    elif isinstance(node, ast.For) \
                            and isinstance(node.target, ast.Name):
                        hit: Set[str] = set()
                        for sub in ast.walk(node.iter):
                            if isinstance(sub, ast.Name) \
                                    and sub.id in aliases:
                                hit |= aliases[sub.id]
                            d = dotted(sub) or ""
                            if d.startswith("self.") and d.count(".") == 1 \
                                    and d[5:] in thread_attrs:
                                hit.add(d[5:])
                        if hit:
                            aliases.setdefault(node.target.id,
                                               set()).update(hit)
            for node in walk_scope(fn):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func) or ""
                if d.startswith("self.") and d.count(".") == 1 \
                        and d[5:] in info.methods:
                    edges[mname].add(d[5:])
                if d.endswith(".join"):
                    recv = d[:-len(".join")]
                    attrs: Set[str] = set()
                    if recv.startswith("self.") and recv.count(".") == 1 \
                            and recv[5:] in thread_attrs:
                        attrs.add(recv[5:])
                    attrs |= aliases.get(recv, set())
                    for attr in attrs:
                        join_sites.setdefault(attr, set()).add(mname)
        roots = [m for m in STOP_ROOTS if m in info.methods]
        reachable: Set[str] = set(roots)
        frontier = list(roots)
        while frontier:
            cur = frontier.pop()
            for nxt in edges.get(cur, ()):
                if nxt not in reachable:
                    reachable.add(nxt)
                    frontier.append(nxt)
        for attr in sorted(thread_attrs):
            line, col = info.node.lineno, info.node.col_offset
            for mname, fn in info.methods.items():
                for node in walk_scope(fn):
                    if isinstance(node, ast.Assign) \
                            and _is_thread_ctor(module, node.value) \
                            and any((dotted(t) or "") == f"self.{attr}"
                                    for t in node.targets):
                        line, col = node.lineno, node.col_offset
            if not roots:
                yield Finding(
                    checker=self.name, code="threadlife-no-join",
                    message=(f"class {info.name} owns thread `self.{attr}` "
                             "but has no stop()/close()/shutdown() method "
                             "to join it from"),
                    path=module.rel, line=line, col=col)
            elif not (join_sites.get(attr, set()) & reachable):
                yield Finding(
                    checker=self.name, code="threadlife-no-join",
                    message=(f"thread `self.{attr}` of {info.name} has no "
                             "join reachable from "
                             f"{'/'.join(sorted(roots))}() — the PR 7/8/12 "
                             "leak class"),
                    path=module.rel, line=line, col=col)

    # -- orphans --------------------------------------------------------------

    def _orphans(self, module: ModuleInfo, fn: ast.AST,
                 project) -> Iterator[Finding]:
        def is_threadish(value: ast.AST) -> bool:
            if _is_thread_ctor(module, value):
                return True
            if project is not None and isinstance(value, ast.Call):
                callee = project.resolve_call(module, value)
                if callee is not None and callee.returns_thread:
                    return True
            return False

        locals_: Dict[str, ast.AST] = {}
        list_locals: Set[str] = set()
        started: Set[str] = set()
        released: Set[str] = set()     # joined / returned / stored / passed
        any_join = False
        # pass 1 — bind thread locals (walk order is not source order, so
        # a use must never be judged before its binding is seen)
        for node in walk_scope(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tgt = node.targets[0].id
                if is_threadish(node.value):
                    locals_[tgt] = node
                elif isinstance(node.value, (ast.ListComp, ast.List)):
                    elts = node.value.elts \
                        if isinstance(node.value, ast.List) \
                        else [node.value.elt]
                    if any(is_threadish(e) for e in elts):
                        list_locals.add(tgt)
        # pass 2 — starts, joins, ownership transfers
        for node in walk_scope(fn):
            if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
                call = node.value
                if isinstance(call.func, ast.Attribute) \
                        and call.func.attr == "start" \
                        and is_threadish(call.func.value):
                    yield Finding(
                        checker=self.name, code="threadlife-orphan",
                        message=("fire-and-forget threading.Thread(...)"
                                 ".start(); bind the thread and join it, "
                                 "or hand it to an owner with a stop path"),
                        path=module.rel, line=node.lineno,
                        col=node.col_offset)
            if isinstance(node, ast.Call):
                d = dotted(node.func) or ""
                base, _, meth = d.rpartition(".")
                if meth == "start" and base in locals_:
                    started.add(base)
                elif meth == "join":
                    any_join = True
                    if base in locals_:
                        released.add(base)
                # a local handed to any other call transfers ownership
                for arg in list(node.args) \
                        + [kw.value for kw in node.keywords]:
                    if isinstance(arg, ast.Name) and arg.id in locals_:
                        released.add(arg.id)
            if isinstance(node, ast.Return) and node.value is not None:
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Name) and sub.id in locals_:
                        released.add(sub.id)
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)):
                        for sub in ast.walk(node.value):
                            if isinstance(sub, ast.Name) \
                                    and sub.id in locals_:
                                released.add(sub.id)
        for name in sorted(started - released):
            node = locals_[name]
            yield Finding(
                checker=self.name, code="threadlife-orphan",
                message=(f"local thread `{name}` is started but never "
                         "joined, returned, or stored — nothing can stop "
                         "or await it"),
                path=module.rel, line=node.lineno, col=node.col_offset)
        if list_locals and not any_join:
            node = fn
            yield Finding(
                checker=self.name, code="threadlife-orphan",
                message=(f"thread list(s) {sorted(list_locals)} built in "
                         f"{getattr(fn, 'name', '?')}() with no join "
                         "anywhere in the function"),
                path=module.rel, line=getattr(fn, "lineno", 1),
                col=getattr(fn, "col_offset", 0))
