"""wait-discipline: no unbounded blocking waits outside test code.

The failure-domain work (verify-service watchdog, host failover) exists
because a wedged dependency must cost a deadline, never a hang.  That
guarantee is only as strong as the weakest wait: one `future.result()`
with no timeout and a single stuck dispatch freezes its caller forever,
invisibly.  This checker flags the three stdlib waits that default to
"forever":

  * ``Future.result()``        (concurrent.futures)
  * ``Thread.join()``          (threading)
  * ``Condition.wait()`` / ``Event.wait()``

A call is flagged when it has NO positional argument and NO ``timeout=``
keyword.  Matching is name-based (``.result()`` / ``.join()`` /
``.wait()`` with zero arguments): static typing is out of reach for an
AST pass, but the zero-argument forms of these names are blocking waits
in practice — ``str.join``/``os.path.join`` always take an argument, and
a bounded wait always carries one.  Paths that legitimately wait forever
(a caller whose resolution is guaranteed by a supervising watchdog, a
shutdown join on a daemon thread) carry a
``tpu-vet: disable=wait`` suppression WITH a justification comment.

Test code is exempt: tests wait on work they control, and pytest's own
timeout machinery bounds them.
"""

import ast
from typing import Iterator

from ..core import Finding
from ..symbols import ModuleInfo

# zero-arg attribute calls that block forever by default
UNBOUNDED = {"result", "join", "wait"}


def _is_test_code(rel: str) -> bool:
    base = rel.rsplit("/", 1)[-1]
    return rel.startswith("tests/") or "/tests/" in rel \
        or base.startswith("test_") or base == "conftest.py"


class WaitChecker:
    name = "wait"
    description = ("unbounded Future.result()/Thread.join()/"
                   "Condition.wait() (no timeout) outside test code")

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        if _is_test_code(module.rel):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            if func.attr not in UNBOUNDED:
                continue
            if node.args or node.keywords:
                continue        # bounded (or at least parameterized)
            yield Finding(
                checker=self.name, code="wait-unbounded",
                message=(f"unbounded .{func.attr}() — pass a timeout (a "
                         "wedged dependency must cost a deadline, not a "
                         "hang) or suppress with a justification naming "
                         "what guarantees resolution"),
                path=module.rel, line=node.lineno, col=node.col_offset)
