"""lock-discipline: the poor-Python's `-race` for classes that own locks.

Three per-class rules, all derived from the class's own usage (no
annotations):

  1. **unguarded write** — an attribute that is assigned (or mutated via
     list/dict/set methods) inside `with self.<lock>` in one method is
     lock-guarded state; any OTHER method writing it without the lock is
     a data race.  `__init__` is exempt (construction happens-before
     publication).  Helpers whose contract is "caller holds the lock"
     carry an inline `tpu-vet: disable=lock` comment with the reason.

  2. **blocking call under lock** — while holding `with self.<lock>`:
     `time.sleep`, `<clock>.wait_until`, `Thread.join`, `serve_forever`,
     `Event.wait` (does NOT release the lock — unlike `Condition.wait`),
     and blocking `Queue.get/put` (the `_nowait` variants and
     `block=False` are fine).  A lock held across a blocking call stalls
     every thread behind it — the exact failure mode the reference
     avoids by keeping Go's mutexes around pure state transitions.

  3. **lock-order cycle** — a directed graph over (class, lock) nodes:
     edge A→B when B is acquired while A is held, either by nested
     `with` or through a same-class method call (closure over the
     class's own call graph).  Any cycle is a deadlock candidate;
     re-acquiring a non-reentrant Lock/Condition (a self-edge) is
     reported the same way.

With a phase-1 `Project` (v3, ``uses_project``), the cycle graph goes
project-wide and three interprocedural rules join, all riding the
per-function lockset summaries (`FunctionSummary.acquires_trans`,
``may_block``, ``mutates_params``, ``calls_params``):

  4. **cross-module lock-order cycle** — the (owner, lock) graph closes
     over RESOLVED calls anywhere in the project: `self._reg.snapshot()`
     acquiring the registry's lock while this class's lock is held is an
     edge, as is a callback registered with another class and invoked
     under that class's lock (the tenancy ``on_change`` →
     admission/placement shape).  Module-level locks (`_PACK_LOCK =
     threading.Lock()`) are graph nodes too.

  5. **helper-laundered write** (``lock-helper-mutation``) — passing a
     guarded container (`self.plan`) to a function whose summary says it
     mutates that parameter, at a call site not holding the guarding
     lock, is the same data race as rule 1 one frame removed.

  6. **transitive blocking** (``lock-blocking-transitive`` /
     ``lock-callback-blocking``) — a call made while holding a lock to a
     callee that MAY block (directly or further down), or a registered
     callback that may block invoked under the registrar's lock.

``check(module)`` with no project reproduces the per-class v2 pass
exactly — the both-ways regression tests in tests/test_vet.py rely on
it.  The project-wide graph and findings are derived ONCE per project
(``project.memo``) and sliced per module, so the parallel per-file sweep
pays for phase 2 once.
"""

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..core import Finding
from ..project import (MUTATORS, FunctionSummary, LockNode, Project,
                       held_lockset, lock_label, lock_node_at)
from ..symbols import (LOCK_KINDS, NON_REENTRANT, ClassInfo, ModuleInfo,
                       dotted, walk_scope)

BLOCKING_NAMES = {"wait_until", "serve_forever"}

CONSTRUCTION = ("__init__", "__new__", "__del__", "__enter__", "__exit__")

# local snapshot spellings that preserve element identity: `cbs =
# list(self._subs)` still iterates the registered callbacks
_SNAPSHOT_FNS = ("list", "tuple", "sorted")


def _self_attr(node: ast.AST) -> Optional[str]:
    d = dotted(node)
    if d and d.startswith("self.") and d.count(".") == 1:
        return d.split(".", 1)[1]
    return None


class LockChecker:
    name = "lock"
    description = ("unguarded writes to lock-guarded attributes, blocking "
                   "calls under a lock, lock-order cycles (project-wide "
                   "with phase 1), helper-laundered writes, transitive "
                   "blocking")
    uses_project = True

    def check(self, module: ModuleInfo,
              project: Optional[Project] = None) -> Iterator[Finding]:
        edges: Dict[Tuple[str, str], List[Tuple[Tuple[str, str], ast.AST]]] = {}
        for cls in module.classes:
            locks = cls.lock_attrs()
            if not locks:
                continue
            yield from self._unguarded_writes(module, cls, locks)
            yield from self._blocking_under_lock(module, cls, locks)
            if project is None:
                self._order_edges(module, cls, locks, edges)
        if project is None:
            yield from self._cycles(module, edges)
            return
        global_pass = project.memo(
            "lock-global", lambda: _GlobalLockPass(self, project))
        yield from global_pass.findings_for(module.rel)

    # -- rule 1: unguarded writes -------------------------------------------

    def _writes(self, cls: ClassInfo, fn: ast.AST):
        """(attr, node) for every mutation of a self attribute in `fn`:
        assignment, augmented assignment, del, subscript store, or a
        mutating method call (append/update/...)."""
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                # unpack tuple/list targets: the snapshot-and-null idiom
                # `local, self.x = self.x, None` writes self.x
                flat = []
                for t in targets:
                    if isinstance(t, (ast.Tuple, ast.List)):
                        flat.extend(t.elts)
                    else:
                        flat.append(t)
                for t in flat:
                    attr = _self_attr(t)
                    if attr:
                        yield attr, node
                    elif isinstance(t, ast.Subscript):
                        attr = _self_attr(t.value)
                        if attr:
                            yield attr, node
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr:
                        yield attr, node
                    elif isinstance(t, ast.Subscript):
                        attr = _self_attr(t.value)
                        if attr:
                            yield attr, node
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in MUTATORS:
                attr = _self_attr(node.func.value)
                if attr:
                    yield attr, node

    def _held_locks(self, module: ModuleInfo, node: ast.AST,
                    locks: List[str]) -> Set[str]:
        held = set()
        for d in module.withs_holding(node):
            attr = d.split(".", 1)[1] if d.startswith("self.") else None
            if attr in locks:
                held.add(attr)
        return held

    def _guarded_attrs(self, module: ModuleInfo, cls: ClassInfo,
                       locks: List[str]) -> Set[str]:
        """Attributes this class treats as lock-guarded state: written
        at least once while holding one of the class's locks."""
        guarded: Set[str] = set()
        for name, fn in cls.methods.items():
            for attr, node in self._writes(cls, fn):
                if attr in cls.attr_kinds and \
                        cls.attr_kinds[attr] in LOCK_KINDS:
                    continue            # the lock object itself
                if self._held_locks(module, node, locks):
                    guarded.add(attr)
        return guarded

    def _unguarded_writes(self, module: ModuleInfo, cls: ClassInfo,
                          locks: List[str]) -> Iterator[Finding]:
        guarded = self._guarded_attrs(module, cls, locks)
        if not guarded:
            return
        for name, fn in cls.methods.items():
            if name in CONSTRUCTION:
                continue
            for attr, node in self._writes(cls, fn):
                if attr in guarded \
                        and not self._held_locks(module, node, locks):
                    yield Finding(
                        checker=self.name, code="lock-unguarded-write",
                        message=(f"{cls.name}.{name} mutates self.{attr} "
                                 "without holding the lock that guards it "
                                 "elsewhere in the class"),
                        path=module.rel, line=node.lineno,
                        col=node.col_offset)

    # -- rule 2: blocking calls under a lock --------------------------------

    def _blocking_reason(self, module: ModuleInfo, cls: ClassInfo,
                         node: ast.Call) -> Optional[str]:
        qual = module.resolve(dotted(node.func) or "")
        if qual == "time.sleep":
            return "time.sleep"
        if not isinstance(node.func, ast.Attribute):
            return None
        meth = node.func.attr
        if meth in BLOCKING_NAMES:
            return f".{meth}()"
        recv = _self_attr(node.func.value)
        kind = cls.attr_kinds.get(recv) if recv else None
        if meth == "join" and kind == "thread":
            return f"Thread.join on self.{recv}"
        if meth == "wait" and kind == "event":
            # Event.wait keeps the lock held; Condition.wait releases it
            return f"Event.wait on self.{recv}"
        if meth in ("get", "put") and kind == "queue":
            for kw in node.keywords:
                if kw.arg == "block" \
                        and isinstance(kw.value, ast.Constant) \
                        and kw.value.value is False:
                    return None
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and node.args[0].value is False:
                return None
            return f"blocking Queue.{meth} on self.{recv}"
        return None

    def _blocking_under_lock(self, module: ModuleInfo, cls: ClassInfo,
                             locks: List[str]) -> Iterator[Finding]:
        for name, fn in cls.methods.items():
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                held = self._held_locks(module, node, locks)
                if not held:
                    continue
                # waiting on the very condition you hold is the cv
                # pattern, not a stall: Condition.wait releases it
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("wait", "wait_for"):
                    recv = _self_attr(node.func.value)
                    if recv in held and \
                            cls.attr_kinds.get(recv) == "condition":
                        continue
                reason = self._blocking_reason(module, cls, node)
                if reason:
                    yield Finding(
                        checker=self.name, code="lock-blocking-call",
                        message=(f"{cls.name}.{name} makes a blocking call "
                                 f"({reason}) while holding "
                                 f"self.{sorted(held)[0]}"),
                        path=module.rel, line=node.lineno,
                        col=node.col_offset)

    # -- rule 3 (v2, project=None): per-class lock-order cycles --------------

    def _acquires(self, cls: ClassInfo, locks: List[str]
                  ) -> Dict[str, Set[str]]:
        """method -> locks it may acquire, closed over same-class calls."""
        direct: Dict[str, Set[str]] = {}
        calls: Dict[str, Set[str]] = {}
        for name, fn in cls.methods.items():
            acq, callees = set(), set()
            for node in ast.walk(fn):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        attr = _self_attr(item.context_expr)
                        if attr in locks:
                            acq.add(attr)
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id == "self" \
                        and node.func.attr in cls.methods:
                    callees.add(node.func.attr)
            direct[name] = acq
            calls[name] = callees
        closed = {m: set(s) for m, s in direct.items()}
        changed = True
        while changed:
            changed = False
            for m, callees in calls.items():
                for c in callees:
                    extra = closed.get(c, set()) - closed[m]
                    if extra:
                        closed[m] |= extra
                        changed = True
        return closed

    def _order_edges(self, module: ModuleInfo, cls: ClassInfo,
                     locks: List[str], edges) -> None:
        closed = self._acquires(cls, locks)
        for name, fn in cls.methods.items():
            for node in ast.walk(fn):
                acquired: Set[str] = set()
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        attr = _self_attr(item.context_expr)
                        if attr in locks:
                            acquired.add(attr)
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id == "self" \
                        and node.func.attr in cls.methods:
                    acquired |= closed.get(node.func.attr, set())
                if not acquired:
                    continue
                held = self._held_locks(module, node, locks)
                for h in held:
                    for a in acquired:
                        if a == h and \
                                cls.attr_kinds.get(a) not in NON_REENTRANT:
                            continue    # RLock re-entry is fine
                        src, dst = (cls.name, h), (cls.name, a)
                        edges.setdefault(src, []).append((dst, node))

    def _cycles(self, module: ModuleInfo, edges) -> Iterator[Finding]:
        seen_cycles = set()
        for start in edges:
            stack = [(start, [start])]
            while stack:
                cur, path = stack.pop()
                for dst, node in edges.get(cur, ()):  # noqa: B007
                    if dst == start:
                        cyc = tuple(sorted(set(path)))
                        if cyc in seen_cycles:
                            continue
                        seen_cycles.add(cyc)
                        pretty = " -> ".join(
                            f"{c}.{a}" for c, a in path + [start])
                        yield Finding(
                            checker=self.name, code="lock-order-cycle",
                            message=("lock-order cycle (deadlock "
                                     f"candidate): {pretty}"),
                            path=module.rel, line=node.lineno,
                            col=node.col_offset)
                    elif dst not in path and len(path) < 6:
                        stack.append((dst, path + [dst]))


# -- v3: the project-wide pass ------------------------------------------------


class _GlobalLockPass:
    """Everything the lock checker derives from a whole project, built
    once per `Project` and sliced per module: the global (owner, lock)
    order graph + its cycles, helper-laundered writes, and transitive /
    callback blocking.  Cycle findings attach to the module holding the
    cycle-closing edge; call-site findings attach to the call site's
    module, so per-module suppressions keep their usual scope."""

    def __init__(self, checker: LockChecker, project: Project):
        self.checker = checker
        self.project = project
        # lock node -> kind ("lock" | "rlock" | "condition")
        self.kinds: Dict[LockNode, str] = {}
        # src node -> [(dst node, module rel, line, col)]
        self.edges: Dict[LockNode, List[Tuple[LockNode, str, int, int]]] = {}
        self._findings: Dict[str, List[Finding]] = {}
        self._guarded: Dict[Tuple[str, str], Set[str]] = {}
        self._collect_kinds()
        callbacks = self._callback_tables()
        self._build_edges(callbacks)
        self._cycle_findings()

    def findings_for(self, rel: str) -> List[Finding]:
        return self._findings.get(rel, [])

    def _emit(self, f: Finding) -> None:
        self._findings.setdefault(f.path, []).append(f)

    # -- tables ---------------------------------------------------------------

    def _collect_kinds(self) -> None:
        for m in self.project.modules:
            for name, kind in m.module_locks.items():
                self.kinds[(m.rel, "", name)] = kind
            for cls in m.classes:
                for attr, kind in cls.attr_kinds.items():
                    if kind in LOCK_KINDS:
                        self.kinds[(m.rel, cls.name, attr)] = kind

    def _callback_tables(self):
        """registrars[(rel, "Cls.meth")] -> [(param, attr)] for methods
        that store a parameter into a self container/slot; invokes[(rel,
        Cls, attr)] -> [(held lockset, node, rel)] for sites where that
        attribute's contents (or the attribute itself) are CALLED —
        directly, through a loop, or via a list()/tuple()/sorted()
        snapshot one alias hop away."""
        registrars: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
        invokes: Dict[Tuple[str, str, str],
                      List[Tuple[Set[LockNode], ast.AST]]] = {}
        for key, s in self.project.functions.items():
            if s.cls is None:
                continue
            m, cls = s.module, s.cls
            params = set(s.params) - {"self"}
            # registration: self.<A>.append(q) / self.<A>[k] = q /
            # self.<A> = q with q a parameter
            for node in walk_scope(s.node):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("append", "add", "insert") \
                        and node.args:
                    attr = _self_attr(node.func.value)
                    arg = node.args[-1]
                    if attr and isinstance(arg, ast.Name) \
                            and arg.id in params:
                        registrars.setdefault(key, []).append((arg.id, attr))
                elif isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id in params:
                    for t in node.targets:
                        attr = _self_attr(t)
                        if attr is None and isinstance(t, ast.Subscript):
                            attr = _self_attr(t.value)
                        if attr:
                            registrars.setdefault(key, []).append(
                                (node.value.id, attr))
            # invocation sites of attr contents
            self._invoke_sites(m, cls, s, invokes)
        return registrars, invokes

    def _snapshot_of(self, node: ast.AST) -> Optional[str]:
        """`self.A`, `list(self.A)`, `tuple(self.A)`, `sorted(self.A)`
        -> "A"; None otherwise."""
        attr = _self_attr(node)
        if attr:
            return attr
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in _SNAPSHOT_FNS and len(node.args) == 1:
            return _self_attr(node.args[0])
        return None

    def _invoke_sites(self, m: ModuleInfo, cls: ClassInfo,
                      s: FunctionSummary, invokes) -> None:
        aliases: Dict[str, str] = {}       # local name -> attr
        for node in walk_scope(s.node):
            if isinstance(node, ast.Assign):
                attr = self._snapshot_of(node.value)
                if attr:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            aliases[t.id] = attr
        loopvars: Dict[str, str] = {}      # loop variable -> attr
        for node in walk_scope(s.node):
            if isinstance(node, (ast.For, ast.AsyncFor)) \
                    and isinstance(node.target, ast.Name):
                attr = self._snapshot_of(node.iter)
                if attr is None and isinstance(node.iter, ast.Name):
                    attr = aliases.get(node.iter.id)
                if attr:
                    loopvars[node.target.id] = attr
        for node in walk_scope(s.node):
            if not isinstance(node, ast.Call):
                continue
            attr = None
            f = node.func
            if isinstance(f, ast.Name) and f.id in loopvars:
                attr = loopvars[f.id]
            elif isinstance(f, ast.Subscript):
                attr = self._snapshot_of(f.value)
            else:
                d = _self_attr(f)
                # calling the slot itself: `self._on_change(...)`
                if d and cls.attr_kinds.get(d) is None \
                        and d not in cls.methods:
                    attr = d
            if attr is None:
                continue
            held = held_lockset(m, cls, node)
            invokes.setdefault((m.rel, cls.name, attr), []).append(
                (held, node))

    # -- the global order graph ----------------------------------------------

    def _add_edge(self, src: LockNode, dst: LockNode, rel: str,
                  node: ast.AST) -> None:
        if src == dst and self.kinds.get(dst) not in NON_REENTRANT:
            return                          # RLock re-entry is fine
        self.edges.setdefault(src, []).append(
            (dst, rel, node.lineno, node.col_offset))

    def _build_edges(self, callbacks) -> None:
        registrars, invokes = callbacks
        proj = self.project
        for key, s in proj.functions.items():
            m, cls = s.module, s.cls
            # nested `with` acquisitions
            for node in walk_scope(s.node):
                if not isinstance(node, (ast.With, ast.AsyncWith)):
                    continue
                acquired = set()
                for item in node.items:
                    d = dotted(item.context_expr)
                    ln = lock_node_at(m, cls, d) if d else None
                    if ln is not None:
                        acquired.add(ln)
                if not acquired:
                    continue
                held = held_lockset(m, cls, node)
                for h in held:
                    for a in acquired:
                        self._add_edge(h, a, m.rel, node)
            # resolved calls: the callee's transitive lockset is acquired
            # while everything at the site is held; blocking callees under
            # a held lock are findings in their own right
            for call, ckey in s.calls:
                callee = proj.functions.get(ckey) if ckey else None
                if callee is None:
                    continue
                held = held_lockset(m, cls, call)
                if held:
                    for h in held:
                        for a in callee.acquires_trans:
                            self._add_edge(h, a, m.rel, call)
                    self._transitive_blocking(s, call, callee, held)
                self._helper_mutation(s, call, callee)
                self._callback_registration(
                    s, call, ckey, callee, registrars, invokes)

    # -- interprocedural findings --------------------------------------------

    def _transitive_blocking(self, s: FunctionSummary, call: ast.Call,
                             callee: FunctionSummary,
                             held: Set[LockNode]) -> None:
        if callee.may_block is None:
            return
        # the per-class rule 2 already covers direct blocking primitives
        if s.cls is not None and self.checker._blocking_reason(
                s.module, s.cls, call) is not None:
            return
        label = lock_label(sorted(held)[0])
        self._emit(Finding(
            checker=self.checker.name, code="lock-blocking-transitive",
            message=(f"{s.qual} calls {callee.display}, which may block "
                     f"({callee.may_block}), while holding {label}"),
            path=s.module.rel, line=call.lineno, col=call.col_offset))

    def _helper_mutation(self, s: FunctionSummary, call: ast.Call,
                         callee: FunctionSummary) -> None:
        cls = s.cls
        if cls is None or not callee.mutates_params:
            return
        locks = cls.lock_attrs()
        if not locks:
            return
        mname = s.qual.rsplit(".", 1)[-1]
        if mname in CONSTRUCTION:
            return
        if self.checker._held_locks(s.module, call, locks):
            return
        gkey = (s.module.rel, cls.name)
        if gkey not in self._guarded:
            self._guarded[gkey] = self.checker._guarded_attrs(
                s.module, cls, locks)
        guarded = self._guarded[gkey]
        for p in callee.mutates_params:
            bound = callee.arg_param(call, p)
            attr = _self_attr(bound) if bound is not None else None
            if attr and attr in guarded:
                self._emit(Finding(
                    checker=self.checker.name, code="lock-helper-mutation",
                    message=(f"{cls.name}.{mname} passes self.{attr} to "
                             f"{callee.display}, which mutates it, without "
                             "holding the lock that guards it elsewhere in "
                             "the class"),
                    path=s.module.rel, line=call.lineno,
                    col=call.col_offset))

    def _callback_registration(self, s: FunctionSummary, call: ast.Call,
                               ckey, callee: FunctionSummary,
                               registrars, invokes) -> None:
        """`other.subscribe(self.on_event)`: every site where the
        registrar's class invokes the stored slot contributes edges from
        the locks held THERE to whatever the callback acquires — and a
        blocking callback invoked under the registrar's lock is the
        listener-under-lock stall outright."""
        regs = registrars.get(ckey)
        if not regs or s.cls is None or callee.cls is None:
            return
        for q, attr in regs:
            bound = callee.arg_param(call, q)
            mattr = _self_attr(bound) if bound is not None else None
            if mattr is None:
                continue
            cb = self.project.functions.get(
                (s.module.rel, f"{s.cls.name}.{mattr}"))
            if cb is None:
                continue
            for held, inode in invokes.get(
                    (callee.module.rel, callee.cls.name, attr), ()):
                for h in held:
                    for a in cb.acquires_trans:
                        self._add_edge(h, a, callee.module.rel, inode)
                if held and cb.may_block is not None:
                    label = lock_label(sorted(held)[0])
                    self._emit(Finding(
                        checker=self.checker.name,
                        code="lock-callback-blocking",
                        message=(f"{s.qual} registers self.{mattr} with "
                                 f"{callee.display}; it is invoked holding "
                                 f"{label} and may block "
                                 f"({cb.may_block})"),
                        path=s.module.rel, line=call.lineno,
                        col=call.col_offset))

    # -- cycles ---------------------------------------------------------------

    def _cycle_findings(self) -> None:
        seen_cycles = set()
        for start in self.edges:
            stack = [(start, [start])]
            while stack:
                cur, path = stack.pop()
                for dst, rel, line, col in self.edges.get(cur, ()):
                    if dst == start:
                        cyc = tuple(sorted(set(path)))
                        if cyc in seen_cycles:
                            continue
                        seen_cycles.add(cyc)
                        pretty = " -> ".join(
                            lock_label(n) for n in path + [start])
                        self._emit(Finding(
                            checker=self.checker.name,
                            code="lock-order-cycle",
                            message=("lock-order cycle (deadlock "
                                     f"candidate): {pretty}"),
                            path=rel, line=line, col=col))
                    elif dst not in path and len(path) < 6:
                        stack.append((dst, path + [dst]))
