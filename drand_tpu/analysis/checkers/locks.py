"""lock-discipline: the poor-Python's `-race` for classes that own locks.

Three rules, all derived from the class's own usage (no annotations):

  1. **unguarded write** — an attribute that is assigned (or mutated via
     list/dict/set methods) inside `with self.<lock>` in one method is
     lock-guarded state; any OTHER method writing it without the lock is
     a data race.  `__init__` is exempt (construction happens-before
     publication).  Helpers whose contract is "caller holds the lock"
     carry an inline `# tpu-vet: disable=lock` with the reason.

  2. **blocking call under lock** — while holding `with self.<lock>`:
     `time.sleep`, `<clock>.wait_until`, `Thread.join`, `serve_forever`,
     `Event.wait` (does NOT release the lock — unlike `Condition.wait`),
     and blocking `Queue.get/put` (the `_nowait` variants and
     `block=False` are fine).  A lock held across a blocking call stalls
     every thread behind it — the exact failure mode the reference
     avoids by keeping Go's mutexes around pure state transitions.

  3. **lock-order cycle** — a directed graph over (class, lock) nodes:
     edge A→B when B is acquired while A is held, either by nested
     `with` or through a same-class method call (closure over the
     class's own call graph).  Any cycle is a deadlock candidate;
     re-acquiring a non-reentrant Lock/Condition (a self-edge) is
     reported the same way.
"""

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..core import Finding
from ..symbols import (LOCK_KINDS, NON_REENTRANT, ClassInfo, ModuleInfo,
                       dotted)

MUTATORS = {"append", "extend", "insert", "remove", "pop", "clear",
            "update", "setdefault", "add", "discard", "popleft",
            "appendleft", "popitem"}

BLOCKING_NAMES = {"wait_until", "serve_forever"}

CONSTRUCTION = ("__init__", "__new__", "__del__", "__enter__", "__exit__")


def _self_attr(node: ast.AST) -> Optional[str]:
    d = dotted(node)
    if d and d.startswith("self.") and d.count(".") == 1:
        return d.split(".", 1)[1]
    return None


class LockChecker:
    name = "lock"
    description = ("unguarded writes to lock-guarded attributes, blocking "
                   "calls under a lock, lock-order cycles")

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        edges: Dict[Tuple[str, str], List[Tuple[Tuple[str, str], ast.AST]]] = {}
        for cls in module.classes:
            locks = cls.lock_attrs()
            if not locks:
                continue
            yield from self._unguarded_writes(module, cls, locks)
            yield from self._blocking_under_lock(module, cls, locks)
            self._order_edges(module, cls, locks, edges)
        yield from self._cycles(module, edges)

    # -- rule 1: unguarded writes -------------------------------------------

    def _writes(self, cls: ClassInfo, fn: ast.AST):
        """(attr, node) for every mutation of a self attribute in `fn`:
        assignment, augmented assignment, del, subscript store, or a
        mutating method call (append/update/...)."""
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    attr = _self_attr(t)
                    if attr:
                        yield attr, node
                    elif isinstance(t, ast.Subscript):
                        attr = _self_attr(t.value)
                        if attr:
                            yield attr, node
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr:
                        yield attr, node
                    elif isinstance(t, ast.Subscript):
                        attr = _self_attr(t.value)
                        if attr:
                            yield attr, node
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in MUTATORS:
                attr = _self_attr(node.func.value)
                if attr:
                    yield attr, node

    def _held_locks(self, module: ModuleInfo, node: ast.AST,
                    locks: List[str]) -> Set[str]:
        held = set()
        for d in module.withs_holding(node):
            attr = d.split(".", 1)[1] if d.startswith("self.") else None
            if attr in locks:
                held.add(attr)
        return held

    def _unguarded_writes(self, module: ModuleInfo, cls: ClassInfo,
                          locks: List[str]) -> Iterator[Finding]:
        guarded: Set[str] = set()
        for name, fn in cls.methods.items():
            for attr, node in self._writes(cls, fn):
                if attr in cls.attr_kinds and \
                        cls.attr_kinds[attr] in LOCK_KINDS:
                    continue            # the lock object itself
                if self._held_locks(module, node, locks):
                    guarded.add(attr)
        if not guarded:
            return
        for name, fn in cls.methods.items():
            if name in CONSTRUCTION:
                continue
            for attr, node in self._writes(cls, fn):
                if attr in guarded \
                        and not self._held_locks(module, node, locks):
                    yield Finding(
                        checker=self.name, code="lock-unguarded-write",
                        message=(f"{cls.name}.{name} mutates self.{attr} "
                                 "without holding the lock that guards it "
                                 "elsewhere in the class"),
                        path=module.rel, line=node.lineno,
                        col=node.col_offset)

    # -- rule 2: blocking calls under a lock --------------------------------

    def _blocking_reason(self, module: ModuleInfo, cls: ClassInfo,
                         node: ast.Call) -> Optional[str]:
        qual = module.resolve(dotted(node.func) or "")
        if qual == "time.sleep":
            return "time.sleep"
        if not isinstance(node.func, ast.Attribute):
            return None
        meth = node.func.attr
        if meth in BLOCKING_NAMES:
            return f".{meth}()"
        recv = _self_attr(node.func.value)
        kind = cls.attr_kinds.get(recv) if recv else None
        if meth == "join" and kind == "thread":
            return f"Thread.join on self.{recv}"
        if meth == "wait" and kind == "event":
            # Event.wait keeps the lock held; Condition.wait releases it
            return f"Event.wait on self.{recv}"
        if meth in ("get", "put") and kind == "queue":
            for kw in node.keywords:
                if kw.arg == "block" \
                        and isinstance(kw.value, ast.Constant) \
                        and kw.value.value is False:
                    return None
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and node.args[0].value is False:
                return None
            return f"blocking Queue.{meth} on self.{recv}"
        return None

    def _blocking_under_lock(self, module: ModuleInfo, cls: ClassInfo,
                             locks: List[str]) -> Iterator[Finding]:
        for name, fn in cls.methods.items():
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                held = self._held_locks(module, node, locks)
                if not held:
                    continue
                # waiting on the very condition you hold is the cv
                # pattern, not a stall: Condition.wait releases it
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("wait", "wait_for"):
                    recv = _self_attr(node.func.value)
                    if recv in held and \
                            cls.attr_kinds.get(recv) == "condition":
                        continue
                reason = self._blocking_reason(module, cls, node)
                if reason:
                    yield Finding(
                        checker=self.name, code="lock-blocking-call",
                        message=(f"{cls.name}.{name} makes a blocking call "
                                 f"({reason}) while holding "
                                 f"self.{sorted(held)[0]}"),
                        path=module.rel, line=node.lineno,
                        col=node.col_offset)

    # -- rule 3: lock-order cycles ------------------------------------------

    def _acquires(self, cls: ClassInfo, locks: List[str]
                  ) -> Dict[str, Set[str]]:
        """method -> locks it may acquire, closed over same-class calls."""
        direct: Dict[str, Set[str]] = {}
        calls: Dict[str, Set[str]] = {}
        for name, fn in cls.methods.items():
            acq, callees = set(), set()
            for node in ast.walk(fn):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        attr = _self_attr(item.context_expr)
                        if attr in locks:
                            acq.add(attr)
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id == "self" \
                        and node.func.attr in cls.methods:
                    callees.add(node.func.attr)
            direct[name] = acq
            calls[name] = callees
        closed = {m: set(s) for m, s in direct.items()}
        changed = True
        while changed:
            changed = False
            for m, callees in calls.items():
                for c in callees:
                    extra = closed.get(c, set()) - closed[m]
                    if extra:
                        closed[m] |= extra
                        changed = True
        return closed

    def _order_edges(self, module: ModuleInfo, cls: ClassInfo,
                     locks: List[str], edges) -> None:
        closed = self._acquires(cls, locks)
        for name, fn in cls.methods.items():
            for node in ast.walk(fn):
                acquired: Set[str] = set()
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        attr = _self_attr(item.context_expr)
                        if attr in locks:
                            acquired.add(attr)
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id == "self" \
                        and node.func.attr in cls.methods:
                    acquired |= closed.get(node.func.attr, set())
                if not acquired:
                    continue
                held = self._held_locks(module, node, locks)
                for h in held:
                    for a in acquired:
                        if a == h and \
                                cls.attr_kinds.get(a) not in NON_REENTRANT:
                            continue    # RLock re-entry is fine
                        src, dst = (cls.name, h), (cls.name, a)
                        edges.setdefault(src, []).append((dst, node))

    def _cycles(self, module: ModuleInfo, edges) -> Iterator[Finding]:
        seen_cycles = set()
        for start in edges:
            stack = [(start, [start])]
            while stack:
                cur, path = stack.pop()
                for dst, node in edges.get(cur, ()):  # noqa: B007
                    if dst == start:
                        cyc = tuple(sorted(set(path)))
                        if cyc in seen_cycles:
                            continue
                        seen_cycles.add(cyc)
                        pretty = " -> ".join(
                            f"{c}.{a}" for c, a in path + [start])
                        yield Finding(
                            checker=self.name, code="lock-order-cycle",
                            message=("lock-order cycle (deadlock "
                                     f"candidate): {pretty}"),
                            path=module.rel, line=node.lineno,
                            col=node.col_offset)
                    elif dst not in path and len(path) < 6:
                        stack.append((dst, path + [dst]))
