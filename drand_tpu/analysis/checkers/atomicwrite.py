"""atomic-write: group/share/journal persistence must be temp+rename.

A truncate-in-place write (`open(path, "w")`, `os.open(..., O_TRUNC)`)
leaves a torn file if the process dies between the truncate and the last
byte — and under `key/` + `core/dkg_journal.py` the files being written
are the node's group, its irreplaceable DKG share, and the crash-recovery
journal itself: exactly the state a restart must be able to trust
(arXiv:2109.11677's non-atomic key/state persistence hazard).  Every
write in scope must either go through `fs.write_atomic` or spell out the
same discipline itself: write a sibling temp file, then `os.replace`/
`os.rename` it over the target.

Scope: `key/` and `core/dkg_journal.py` (the persistent-identity plane).
Read-mode opens are untouched.  A deliberate in-place write carries a
`tpu-vet: disable=atomic` comment WITH a justification.

Flagged (per enclosing function; module-level writes count too):
  * ``open(path, "w"/"wb"/"a"...)`` — any create/truncate/append mode —
    in a scope that never calls ``os.replace``/``os.rename``.
  * ``os.open`` with ``O_TRUNC`` or ``O_CREAT`` under the same condition.
"""

import ast
from typing import Iterator, List, Tuple

from ..core import Finding
from ..symbols import ModuleInfo, dotted

SCOPE_PREFIXES = ("key/",)
SCOPE_FILES = ("core/dkg_journal.py",)

WRITE_MODES = ("w", "a", "x", "+")
RENAMES = {"os.replace", "os.rename", "replace", "rename"}
ATOMIC_HELPERS = {"fs.write_atomic", "write_atomic"}


def _in_scope(rel: str) -> bool:
    return any(rel.startswith(p) for p in SCOPE_PREFIXES) \
        or rel in SCOPE_FILES


def _open_write_mode(node: ast.Call) -> bool:
    """True when this is builtins.open with a create/truncate mode."""
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return False            # default "r"
    if not isinstance(mode, ast.Constant) or not isinstance(mode.value, str):
        return True             # computed mode: assume the worst in scope
    return any(ch in mode.value for ch in WRITE_MODES)


def _os_open_truncates(node: ast.Call, module: ModuleInfo) -> bool:
    for arg in list(node.args) + [kw.value for kw in node.keywords]:
        for n in ast.walk(arg):
            d = dotted(n) or ""
            if d.split(".")[-1] in ("O_TRUNC", "O_CREAT"):
                return True
    return False


class AtomicWriteChecker:
    name = "atomic"
    description = ("truncate-in-place writes of group/share/journal state "
                   "(key/, core/dkg_journal.py) that skip the "
                   "temp+fsync+rename discipline (fs.write_atomic)")

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        if not _in_scope(module.rel):
            return
        # walk each function scope once; module level is its own scope
        scopes: List[Tuple[str, ast.AST]] = [("<module>", module.tree)]
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append((node.name, node))
        for name, scope in scopes:
            yield from self._check_scope(module, name, scope)

    @staticmethod
    def _scope_nodes(scope: ast.AST):
        """Walk one scope WITHOUT descending into nested functions (they
        are separate scopes with their own visit)."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield n
            stack.extend(ast.iter_child_nodes(n))

    def _check_scope(self, module: ModuleInfo, name: str,
                     scope: ast.AST) -> Iterator[Finding]:
        writes: List[Tuple[ast.Call, str]] = []
        renames = False
        for node in self._scope_nodes(scope):
            if not isinstance(node, ast.Call):
                continue
            qual = module.resolve(dotted(node.func) or "")
            if qual in RENAMES or qual in ATOMIC_HELPERS:
                renames = True
            elif qual == "open" and _open_write_mode(node):
                writes.append((node, "open"))
            elif qual == "os.open" and _os_open_truncates(node, module):
                writes.append((node, "os.open"))
        if renames:
            return              # temp+rename discipline present in scope
        for node, kind in writes:
            yield Finding(
                checker=self.name, code="atomic-write-in-place",
                message=(f"{name} writes persistent key/journal state via "
                         f"{kind} with no os.replace/os.rename in scope: a "
                         "crash mid-write leaves a torn file where the "
                         "node expects its group/share/journal — use "
                         "fs.write_atomic (temp + fsync + rename)"),
                path=module.rel, line=node.lineno, col=node.col_offset)
