"""deadline: every blocking call is reachable from a threaded budget.

r06 wedged for 2h07m on 42 hung probes: each probe's subprocess had no
timeout, the caller had no deadline parameter to thread, and nothing
above it could bound the wait without killing the process.  The fix
pattern (net/client.py `_unary`, resilience budgets, accel.probe_backend)
is always the same shape — a `timeout=`/`deadline=` parameter that
REACHES the blocking primitive — and this checker enforces that shape
statically, the compile-time half of ROADMAP item 1's fail-fast
preflight.

Scope: `net/`, `beacon/`, and the operator tools where r06 actually hung
(`bench.py`, `autotune.py`, `loadgen.py`, `chaos_smoke.py`).  Test code
is exempt (pytest owns the watchdog there).

Codes:

  * ``deadline-unbounded-call`` — a recognized blocking primitive
    (`subprocess.run/call/check_call/check_output`, `urlopen`,
    `socket.create_connection`, `.communicate()`) with no timeout
    argument, or an explicit ``timeout=None``.
  * ``deadline-not-threaded`` — a call omits a parameter the callee's
    phase-1 summary marks ``required_deadline``: the callee passes that
    parameter straight into a blocking call with no fallback, so an
    omitting caller runs unbounded.  (Parameters the callee defaults
    with ``p or DEFAULT`` / ``if p is None`` are self-bounding and never
    required — net/client.py's `timeout or self.timeout` idiom stays
    clean by design.)
"""

import ast
import os
from typing import Iterator, Optional

from ..core import Finding
from ..symbols import ModuleInfo

SCOPES = ("net/", "beacon/")
TOOL_FILES = {"bench.py", "autotune.py", "loadgen.py", "chaos_smoke.py",
              # the fleet harness lives under tests/ but is NOT exempt:
              # pytest's watchdog can't unwedge a supervisor stuck in a
              # subprocess wait — a hung fleet run must die in minutes
              "fleet.py"}

# method-shaped socket blockers: with no `settimeout` discipline in the
# enclosing class these wait forever (the chaos proxy's accept loop and
# pump recv are the canonical sites)
SOCKET_BLOCKERS = ("accept", "recv")


def _is_test_code(rel: str) -> bool:
    base = os.path.basename(rel)
    return base.startswith("test_") or base.endswith("_test.py") \
        or rel.startswith("tests/") or "/tests/" in rel


def _in_scope(rel: str) -> bool:
    if os.path.basename(rel) in TOOL_FILES:
        return True         # before the test exemption: tests/fleet.py
    if _is_test_code(rel):
        return False
    return any(rel.startswith(s) or f"/{s}" in f"/{rel}" for s in SCOPES)


def _has_settimeout(tree: ast.AST) -> bool:
    """True when the subtree ever arms a non-None socket timeout — the
    discipline that turns accept()/recv() into bounded poll slices."""
    for n in ast.walk(tree):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "settimeout" and n.args \
                and not (isinstance(n.args[0], ast.Constant)
                         and n.args[0].value is None):
            return True
    return False


class DeadlineChecker:
    name = "deadline"
    description = ("blocking RPC/subprocess calls must be bounded and "
                   "budget/deadline/timeout params threaded from callers")
    uses_project = True

    def check(self, module: ModuleInfo,
              project: Optional[object] = None) -> Iterator[Finding]:
        if not _in_scope(module.rel):
            return
        yield from self._socket_loops(module)
        from ..project import blocking_call
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            info = blocking_call(module, node)
            if info is not None:
                label, expr = info
                if expr is None:
                    yield Finding(
                        checker=self.name, code="deadline-unbounded-call",
                        message=(f"blocking call {label} has no timeout; "
                                 "an unreachable peer holds this thread "
                                 "forever (the r06 hung-probe class)"),
                        path=module.rel, line=node.lineno,
                        col=node.col_offset)
                continue
            if project is None:
                continue
            callee = project.resolve_call(module, node)
            if callee is None or not callee.required_deadline:
                continue
            for p in sorted(callee.required_deadline):
                if callee.arg_param(node, p) is None:
                    yield Finding(
                        checker=self.name, code="deadline-not-threaded",
                        message=(f"call to {callee.display} omits `{p}`, "
                                 "which that function passes straight to a "
                                 "blocking call with no fallback — thread "
                                 "a budget from this caller"),
                        path=module.rel, line=node.lineno,
                        col=node.col_offset)

    def _socket_loops(self, module: ModuleInfo) -> Iterator[Finding]:
        """accept()/recv() with no settimeout discipline in the tightest
        enclosing class (or the module, for free functions): the socket
        blocks forever, so a wedged link hangs supervisor teardown."""
        def walk(node: ast.AST, owner: ast.AST) -> Iterator[Finding]:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.Call) \
                        and isinstance(child.func, ast.Attribute) \
                        and child.func.attr in SOCKET_BLOCKERS \
                        and not _has_settimeout(owner):
                    yield Finding(
                        checker=self.name, code="deadline-unbounded-call",
                        message=(f".{child.func.attr}() with no settimeout "
                                 "discipline in scope; a silent peer holds "
                                 "this thread forever — arm a poll-slice "
                                 "timeout on the socket"),
                        path=module.rel, line=child.lineno,
                        col=child.col_offset)
                nxt = child if isinstance(child, ast.ClassDef) else owner
                yield from walk(child, nxt)
        yield from walk(module.tree, module.tree)
