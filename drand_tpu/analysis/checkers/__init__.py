"""Checker registry.  Each checker is a class with `name` (the
suppression token), `description`, and `check(module) -> findings`;
interprocedural checkers set `uses_project = True` and take
`check(module, project)` (see analysis/project.py)."""

from .clock import ClockChecker
from .locks import LockChecker
from .secrets import SecretChecker
from .trace import TraceChecker
from .store import StoreChecker
from .verifier import VerifierChecker
from .wait import WaitChecker
from .bounds import BoundsChecker
from .atomicwrite import AtomicWriteChecker
from .recompile import RecompileChecker
from .deadline import DeadlineChecker
from .threadlife import ThreadLifeChecker
from .metriclabel import MetricLabelChecker

ALL_CHECKERS = (ClockChecker, LockChecker, SecretChecker, TraceChecker,
                StoreChecker, VerifierChecker, WaitChecker, BoundsChecker,
                AtomicWriteChecker, RecompileChecker, DeadlineChecker,
                ThreadLifeChecker, MetricLabelChecker)


def checker_names():
    return [c.name for c in ALL_CHECKERS]


def by_names(names):
    """Instantiate a subset by suppression token; raises on unknown."""
    table = {c.name: c for c in ALL_CHECKERS}
    out = []
    for n in names:
        if n not in table:
            raise KeyError(f"unknown checker {n!r}; "
                           f"have {', '.join(sorted(table))}")
        out.append(table[n]())
    return out
