"""metriclabel: metric label values come from provably bounded sets.

Prometheus stores one time series per label combination; a peer address,
a round number, or a tenant-supplied string as a label value turns a
gauge into an unbounded allocation in every scrape target downstream.
The repo's convention is that label values are either literals, one of
the known bounded identifiers below (`beacon_id`, `lane`, `scope`, ...),
or pass through `metrics.registered_label(...)` — the cardinality-capping
sanitizer that maps out-of-registry values to a fallback bucket.

A value expression is **bounded** when it is:

  * a literal, or an f-string / `str()` / concatenation of bounded parts;
  * a name or attribute whose terminal identifier is in the bounded
    registry (or is ALL-UPPERCASE — module constants);
  * a call to a sanctioner (`registered_label` / `bounded_label`);
  * a conditional / `or`-chain whose branches are all bounded;
  * a local assigned from a bounded expression (one hop).

Everything else that reaches `.labels(...)` is flagged
(``metriclabel-unbounded``).  Test code is exempt.
"""

import ast
import os
from typing import Iterator, Optional, Set

from ..core import Finding
from ..symbols import ModuleInfo, dotted, walk_scope

# identifiers whose values are bounded by construction in this codebase:
# config enums, registry keys, small fixed sets
BOUNDED_TERMINALS = {
    "beacon_id", "scope", "lane", "cls", "kind", "phase", "direction",
    "result", "verdict", "decision", "trigger", "state", "gid", "db",
    "op", "scheme", "label", "api_method", "route", "db_engine",
    "engine", "outcome", "status", "reason", "stage", "mode", "tier",
}

# sanitizers that produce registry-capped values no matter the input
SANCTIONERS = {"registered_label", "bounded_label"}

# casts that preserve boundedness of their (bounded) argument
CASTS = {"str", "int", "len", "repr", "format"}


def _is_test_code(rel: str) -> bool:
    base = os.path.basename(rel)
    return base.startswith("test_") or base.endswith("_test.py") \
        or rel.startswith("tests/") or "/tests/" in rel \
        or base in ("conftest.py", "chaos.py")


def _terminal(name: str) -> str:
    return name.rsplit(".", 1)[-1]


def _bounded_name(term: str) -> bool:
    """A terminal identifier reads as bounded when it IS a registered
    bounded word, ends with one (`drain_lane`, `peer_cls` — the naming
    convention that documents boundedness at the use site), or is an
    ALL-CAPS module constant."""
    if term in BOUNDED_TERMINALS:
        return True
    if any(term.endswith("_" + w) for w in BOUNDED_TERMINALS):
        return True
    return term.isupper() and len(term) > 1


class MetricLabelChecker:
    name = "metriclabel"
    description = ("metric label values must come from provably bounded "
                   "sets — no peer address, round number, or tenant string")

    def _bounded(self, module: ModuleInfo, node: ast.AST,
                 locals_: Set[str]) -> bool:
        if isinstance(node, ast.Constant):
            return True
        if isinstance(node, ast.Name):
            return node.id in locals_ or _bounded_name(node.id)
        if isinstance(node, ast.Attribute):
            return _bounded_name(node.attr)
        if isinstance(node, ast.Call):
            fname = _terminal(dotted(node.func) or "")
            if fname in SANCTIONERS:
                return True
            if fname in CASTS:
                return all(self._bounded(module, a, locals_)
                           for a in node.args)
            # `"x".join(...)`-style method on a bounded receiver
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "format":
                return all(self._bounded(module, a, locals_)
                           for a in node.args)
            return False
        if isinstance(node, ast.IfExp):
            return self._bounded(module, node.body, locals_) \
                and self._bounded(module, node.orelse, locals_)
        if isinstance(node, ast.BoolOp):
            return all(self._bounded(module, v, locals_)
                       for v in node.values)
        if isinstance(node, ast.JoinedStr):
            return all(self._bounded(module, v.value, locals_)
                       for v in node.values
                       if isinstance(v, ast.FormattedValue))
        if isinstance(node, ast.BinOp):
            return self._bounded(module, node.left, locals_) \
                and self._bounded(module, node.right, locals_)
        if isinstance(node, ast.Subscript):
            # a lookup INTO a bounded table yields one of its (bounded)
            # values — STATE_NAMES[new] — whatever the index is
            return self._bounded(module, node.value, locals_)
        return False

    def _bounded_locals(self, module: ModuleInfo, fn: ast.AST) -> Set[str]:
        out: Set[str] = set()
        for node in walk_scope(fn):
            if isinstance(node, ast.Assign):
                if self._bounded(module, node.value, out):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            out.add(t.id)
        return out

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        if _is_test_code(module.rel):
            return
        for cls, fn in module.functions():
            locals_ = self._bounded_locals(module, fn)
            for node in walk_scope(fn):
                if not isinstance(node, ast.Call):
                    continue
                if not (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "labels"):
                    continue
                for arg in list(node.args) \
                        + [kw.value for kw in node.keywords]:
                    if self._bounded(module, arg, locals_):
                        continue
                    shown = dotted(arg) or type(arg).__name__
                    yield Finding(
                        checker=self.name, code="metriclabel-unbounded",
                        message=(f"label value `{shown}` is not provably "
                                 "bounded; a per-peer/per-round/per-tenant "
                                 "label value is one time series per "
                                 "distinct value — use a bounded "
                                 "identifier or metrics.registered_label()"),
                        path=module.rel, line=node.lineno,
                        col=node.col_offset)
