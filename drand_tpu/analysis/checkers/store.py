"""store-contract: the chain/store.py durability contract, enforced.

Three rules derived from the contract docstring (chain/store.py):

  1. **conn-unlocked** — a sqlite connection opened with
     `check_same_thread=False` is by declaration shared across threads;
     every `.execute/.executemany/.commit/.rollback/.backup/.serialize/
     .close` on it must happen inside `with <owner>.<lock>` for the
     lock that lives next to the connection.  (sqlite3 serializes at the
     C level only when compiled threadsafe AND one statement at a time —
     interleaved `execute`/`commit` from two threads can commit half a
     batch under another writer's transaction.)
  2. **put-no-commit** — a `put`/`put_many`/`delete` method that runs
     mutating SQL must also commit (or run inside `with <conn>`): the
     contract promises a returned put has been committed through the
     journal, and an implicitly-open transaction breaks crash-safety AND
     `save_to` snapshots.
  3. **missing-durability** — every direct `Store` subclass declares
     where it sits on the volatile/crash-safe/server spectrum via the
     `DURABILITY` class attribute (tests/test_chain.py pins the matrix
     against it).
"""

import ast
from typing import Iterator, List, Optional

from ..core import Finding
from ..symbols import ClassInfo, ModuleInfo, dotted

CONN_METHODS = {"execute", "executemany", "executescript", "commit",
                "rollback", "backup", "serialize", "close"}

MUTATING_SQL = ("insert", "update", "delete", "replace", "create", "drop")

PUT_PATH = ("put", "put_many", "delete")


class StoreChecker:
    name = "store"
    description = ("sqlite connections used outside the store lock, "
                   "put-path without a commit, Store backends missing "
                   "DURABILITY")

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        for cls in module.classes:
            yield from self._durability(module, cls)
            conn_attrs = [a for a, k in cls.attr_kinds.items()
                          if k == "sqlite_conn"]
            if conn_attrs and self._cross_thread(module, cls, conn_attrs):
                yield from self._conn_locking(module, cls, conn_attrs)
                yield from self._put_commits(module, cls, conn_attrs)
        yield from self._foreign_conn_access(module)

    # -- rule 3: DURABILITY --------------------------------------------------

    def _durability(self, module: ModuleInfo,
                    cls: ClassInfo) -> Iterator[Finding]:
        if "Store" not in cls.base_names:
            return
        resolved = [module.resolve(b) for b in cls.base_names]
        if not any(r.endswith("store.Store") or r == "Store"
                   for r in resolved):
            return
        for item in cls.node.body:
            if isinstance(item, ast.Assign):
                for t in item.targets:
                    if isinstance(t, ast.Name) and t.id == "DURABILITY":
                        return
            elif isinstance(item, ast.AnnAssign) \
                    and isinstance(item.target, ast.Name) \
                    and item.target.id == "DURABILITY":
                return
            elif isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and item.name == "DURABILITY":
                return      # delegating @property (decorator chain)
        yield Finding(
            checker=self.name, code="store-missing-durability",
            message=(f"{cls.name} subclasses Store but does not declare "
                     "DURABILITY (volatile | crash-safe | server; see the "
                     "chain/store.py contract)"),
            path=module.rel, line=cls.node.lineno, col=cls.node.col_offset)

    # -- rule 1: connection always under the store lock ----------------------

    def _cross_thread(self, module: ModuleInfo, cls: ClassInfo,
                      conn_attrs: List[str]) -> bool:
        """True when the connection is opened check_same_thread=False —
        the declaration that it WILL be shared across threads."""
        for fn in cls.methods.values():
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) \
                        and module.resolve(dotted(node.func) or "") \
                        == "sqlite3.connect":
                    for kw in node.keywords:
                        if kw.arg == "check_same_thread" \
                                and isinstance(kw.value, ast.Constant) \
                                and kw.value.value is False:
                            return True
        return False

    def _conn_calls(self, fn: ast.AST):
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in CONN_METHODS:
                owner = dotted(node.func.value)
                if owner:
                    yield owner, node

    def _conn_locking(self, module: ModuleInfo, cls: ClassInfo,
                      conn_attrs: List[str]) -> Iterator[Finding]:
        locks = cls.lock_attrs()
        for name, fn in cls.methods.items():
            if name == "__init__":
                continue        # pre-publication: no other thread yet
            for owner, node in self._conn_calls(fn):
                if not owner.startswith("self.") \
                        or owner.split(".")[-1] not in conn_attrs:
                    continue
                held = module.withs_holding(node)
                if any(h.startswith("self.")
                       and h.split(".", 1)[1] in locks for h in held):
                    continue
                yield Finding(
                    checker=self.name, code="store-conn-unlocked",
                    message=(f"{cls.name}.{name} touches the cross-thread "
                             f"sqlite connection ({owner}."
                             f"{node.func.attr}) outside the store lock"),
                    path=module.rel, line=node.lineno, col=node.col_offset)

    # -- rule 1b: cursors reaching into another object's connection ----------

    def _foreign_conn_access(self, module: ModuleInfo) -> Iterator[Finding]:
        """`self._store._conn.execute(...)` from a cursor class must hold
        `self._store.<lock>` — the lock that lives WITH the connection."""
        for cls in module.classes:
            if any(k == "sqlite_conn" for k in cls.attr_kinds.values()):
                continue        # own-connection classes handled above
            for name, fn in cls.methods.items():
                for owner, node in self._conn_calls(fn):
                    parts = owner.split(".")
                    if len(parts) < 3 or parts[0] != "self" \
                            or "conn" not in parts[-1]:
                        continue
                    prefix = ".".join(parts[:-1])   # e.g. self._store
                    held = module.withs_holding(node)
                    if any(h.startswith(prefix + ".")
                           and "lock" in h.rsplit(".", 1)[-1].lower()
                           for h in held):
                        continue
                    yield Finding(
                        checker=self.name, code="store-conn-unlocked",
                        message=(f"{cls.name}.{name} reaches into "
                                 f"{prefix}'s sqlite connection without "
                                 f"holding {prefix}'s lock"),
                        path=module.rel, line=node.lineno,
                        col=node.col_offset)

    # -- rule 2: put path commits --------------------------------------------

    def _put_commits(self, module: ModuleInfo, cls: ClassInfo,
                     conn_attrs: List[str]) -> Iterator[Finding]:
        for name, fn in cls.methods.items():
            if name not in PUT_PATH:
                continue
            mutates = False
            commits = False
            for node in ast.walk(fn):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        d = dotted(item.context_expr)
                        if d and d.split(".")[-1] in conn_attrs:
                            commits = True   # `with conn:` == transaction
                if not isinstance(node, ast.Call) \
                        or not isinstance(node.func, ast.Attribute):
                    continue
                owner = dotted(node.func.value) or ""
                if owner.split(".")[-1] not in conn_attrs:
                    continue
                if node.func.attr in ("execute", "executemany",
                                      "executescript"):
                    sql = node.args[0] if node.args else None
                    if isinstance(sql, ast.Constant) \
                            and isinstance(sql.value, str) \
                            and sql.value.strip().lower().startswith(
                                MUTATING_SQL):
                        mutates = True
                elif node.func.attr == "commit":
                    commits = True
            if mutates and not commits:
                yield Finding(
                    checker=self.name, code="store-put-no-commit",
                    message=(f"{cls.name}.{name} runs mutating SQL but "
                             "never commits; the chain/store.py contract "
                             "says a returned put is committed through "
                             "the journal"),
                    path=module.rel, line=fn.lineno, col=fn.col_offset)
