"""tpu-vet: project-native static analysis for the drand_tpu codebase.

The reference drand leans on Go's toolchain (`go vet`, the `-race`
detector) to keep a threaded daemon honest; no such analogue exists for
this Python reproduction (VERDICT.md §5.2), which now carries ~70
lock/thread sites, an injected-clock discipline the deterministic chaos
harness depends on, and secret-bearing vault/DKG code.  This package is
the replacement: a pure-stdlib AST framework (one shared parse + symbol
pass per file, `symbols.py`) with five project-specific checkers
(`checkers/`):

  * ``clock``  — no direct ``time.time()/monotonic()/sleep()`` outside
    the injected-Clock implementations (beacon/clock.py) and log.py.
  * ``lock``   — for classes owning a ``threading.Lock``: mutations of
    lock-guarded attributes without the lock, blocking calls made while
    holding it, and cycles in the derived lock-order graph.
  * ``secret`` — taint-lite flow from vault/private-share/secret-key
    values into logging calls, exception messages, or ``__repr__``.
  * ``trace``  — JAX tracing pitfalls in ops/ and crypto/batch.py:
    Python control flow on traced values, ``.item()/int()/float()`` on
    tracers, mutation of captured state inside jitted functions.
  * ``store``  — chain-store contract: sqlite connections shared across
    threads must stay behind the store lock, put-path writes must
    commit, every Store backend declares ``DURABILITY``.

Inline suppression: ``# tpu-vet: disable=<checker>[,<checker>...]`` on
the flagged line or the line above; ``# tpu-vet: disable-file=<checker>``
anywhere in the file suppresses the whole file.  A JSON baseline file
(``--baseline``/``--write-baseline`` on tools/vet.py) grandfathers
existing findings without hiding new ones.

The framework imports no JAX (analysis is textual: target files are
parsed, never imported) and runs over the whole package in well under
ten seconds on the 2-core CPU container; ``tools/vet.py`` is the CLI and
``tests/test_vet.py`` gates tier-1 at zero unsuppressed findings.
"""

from .core import (Finding, Report, load_baseline, run_vet,  # noqa: F401
                   write_baseline)
from .checkers import ALL_CHECKERS, checker_names  # noqa: F401

__all__ = ["Finding", "Report", "run_vet", "load_baseline",
           "write_baseline", "ALL_CHECKERS", "checker_names"]
