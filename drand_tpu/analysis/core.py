"""tpu-vet framework core: file discovery, suppressions, baseline, report.

Checker-facing contract: a checker is an object with a ``name`` (the
suppression token), a ``description``, and ``check(module) ->
Iterable[Finding]`` where ``module`` is a `symbols.ModuleInfo`.
Interprocedural checkers additionally set ``uses_project = True`` and
accept ``check(module, project)`` where ``project`` is the phase-1
`project.Project` built over ALL scanned files — call graph, cross-
module resolution, return-taint/deadline summaries.  The framework owns
everything around that — which files are scanned, which findings are
suppressed or baselined, and how the result is rendered.

The run is two-phase: every file is parsed FIRST (phase 1, building the
Project), then checkers run per file (phase 2).  ``context_paths`` adds
files to phase 1 only — they inform cross-module resolution but are
never themselves checked or reported, which is what makes ``--changed``
incremental runs interprocedurally honest.

Finding identity (the baseline key) is deliberately line-free:
``path|checker|code|message``.  Messages therefore name symbols, not
positions, so an unrelated edit above a grandfathered finding does not
resurrect it.
"""

import fnmatch
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence

from .symbols import ModuleInfo

# generated code is not ours to lint
DEFAULT_EXCLUDES = ("*_pb2.py", "*_pb2_grpc.py")

_SUPP_RE = re.compile(
    r"#\s*tpu-vet:\s*(disable|disable-file)\s*=\s*([A-Za-z_][A-Za-z0-9_,\- ]*)")


@dataclass(frozen=True)
class Finding:
    checker: str      # suppression token: clock | lock | secret | trace | store
    code: str         # stable machine code, e.g. "clock-direct-call"
    message: str      # human sentence; stable across unrelated edits
    path: str         # posix path relative to the scanned root
    line: int
    col: int = 0

    @property
    def key(self) -> str:
        return f"{self.path}|{self.checker}|{self.code}|{self.message}"

    def to_dict(self) -> dict:
        return {"checker": self.checker, "code": self.code,
                "message": self.message, "path": self.path,
                "line": self.line, "col": self.col}

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.checker}/{self.code}] {self.message}")


@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)   # actionable
    suppressed: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    files: int = 0
    errors: List[str] = field(default_factory=list)         # unparseable files
    # suppression hygiene (--audit-suppressions): disable comments that
    # covered nothing, and baseline budget that no current finding needs.
    # Informational on a normal run; the audit flag turns them fatal.
    stale_suppressions: List[str] = field(default_factory=list)
    stale_baseline: List[str] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.findings and not self.errors

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.checker] = out.get(f.checker, 0) + 1
        return out

    def to_dict(self) -> dict:
        return {
            "clean": self.clean,
            "files": self.files,
            "findings": [f.to_dict() for f in
                         sorted(self.findings,
                                key=lambda f: (f.path, f.line, f.code))],
            "counts": self.counts(),
            "suppressed": len(self.suppressed),
            "baselined": len(self.baselined),
            "errors": self.errors,
            "stale_suppressions": list(self.stale_suppressions),
            "stale_baseline": list(self.stale_baseline),
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    def to_sarif(self) -> str:
        """SARIF 2.1.0 — one run, one rule per checker/code pair, so CI
        diff-annotation tooling can ingest the findings directly."""
        rules: Dict[str, dict] = {}
        results = []
        for f in sorted(self.findings,
                        key=lambda f: (f.path, f.line, f.code)):
            rule_id = f"tpu-vet/{f.code}"
            rules.setdefault(rule_id, {
                "id": rule_id,
                "name": f.code,
                "properties": {"checker": f.checker},
            })
            results.append({
                "ruleId": rule_id,
                "level": "error",
                "message": {"text": f.message},
                "locations": [{
                    "physicalLocation": {
                        "artifactLocation": {"uri": f.path},
                        "region": {"startLine": max(f.line, 1),
                                   "startColumn": max(f.col, 0) + 1},
                    },
                }],
            })
        doc = {
            "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                        "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
            "version": "2.1.0",
            "runs": [{
                "tool": {"driver": {
                    "name": "tpu-vet",
                    "informationUri": "https://example.invalid/tpu-vet",
                    "rules": sorted(rules.values(),
                                    key=lambda r: r["id"]),
                }},
                "results": results,
            }],
        }
        return json.dumps(doc, indent=2, sort_keys=True)

    def render_text(self) -> str:
        lines = [f.render() for f in sorted(
            self.findings, key=lambda f: (f.path, f.line, f.code))]
        lines.extend(f"error: {e}" for e in self.errors)
        summary = (f"{len(self.findings)} finding(s) over {self.files} "
                   f"file(s) ({len(self.suppressed)} suppressed, "
                   f"{len(self.baselined)} baselined)")
        if self.counts():
            summary += "  [" + ", ".join(
                f"{k}={v}" for k, v in sorted(self.counts().items())) + "]"
        lines.append(summary)
        return "\n".join(lines)


# -- suppressions ------------------------------------------------------------


class Suppressions:
    """`# tpu-vet: disable=<checker>` on the flagged line or the line
    above; `disable-file=<checker>` anywhere suppresses the whole file.
    `all` matches every checker.

    Every entry also tracks whether it covered at least one finding this
    run, so `--audit-suppressions` can flag disable comments that have
    gone stale (the code they excused was fixed or deleted, and the
    comment now silently masks future regressions)."""

    def __init__(self, lines: Sequence[str]):
        self.by_line: Dict[int, set] = {}
        self.file_level: set = set()
        # (comment line, kind, token) -> covered a finding this run
        self.entries: Dict[tuple, bool] = {}
        for i, text in enumerate(lines, start=1):
            m = _SUPP_RE.search(text)
            if not m:
                continue
            names = {n.strip() for n in m.group(2).split(",") if n.strip()}
            if m.group(1) == "disable-file":
                self.file_level |= names
                for n in names:
                    self.entries.setdefault((i, "disable-file", n), False)
            else:
                self.by_line.setdefault(i, set()).update(names)
                for n in names:
                    self.entries.setdefault((i, "disable", n), False)

    def covers(self, finding: Finding) -> bool:
        hit = False
        for token in ("all", finding.checker):
            if token in self.file_level:
                hit = True
                for key in self.entries:
                    if key[1] == "disable-file" and key[2] == token:
                        self.entries[key] = True
        for line in (finding.line, finding.line - 1):
            names = self.by_line.get(line, ())
            for token in ("all", finding.checker):
                if token in names:
                    hit = True
                    self.entries[(line, "disable", token)] = True
        return hit

    def stale(self, ran_checkers: set) -> List[tuple]:
        """Entries that covered nothing, restricted to checkers that
        actually ran — a single-checker invocation must not condemn the
        other checkers' comments."""
        out = []
        for (line, kind, token), used in sorted(self.entries.items()):
            if used:
                continue
            if token != "all" and token not in ran_checkers:
                continue
            out.append((line, kind, token))
        return out


# -- baseline ----------------------------------------------------------------


def load_baseline(path: str) -> Dict[str, int]:
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or "findings" not in data:
        raise ValueError(f"{path}: not a tpu-vet baseline file")
    return dict(data["findings"])


def write_baseline(path: str, report: Report) -> None:
    """Grandfather the report's actionable findings (suppressed ones need
    no baseline; already-baselined ones are carried forward)."""
    counts: Dict[str, int] = {}
    for f in list(report.findings) + list(report.baselined):
        counts[f.key] = counts.get(f.key, 0) + 1
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "tool": "tpu-vet", "findings": counts},
                  f, indent=2, sort_keys=True)
        f.write("\n")


# -- discovery + run ---------------------------------------------------------


def _package_rel(path: str) -> Optional[str]:
    """rel relative to the file's topmost enclosing package directory
    (the highest ancestor holding an `__init__.py`), so
    `vet.py drand_tpu/beacon/clock.py` and `vet.py drand_tpu/beacon/`
    yield the same rel (`beacon/clock.py`) as the canonical scan of
    `drand_tpu/` — checker path scopes, allowlists, and baseline keys
    match however the target is named.  None for a file outside any
    package (fixture corpora, tmp files): those keep the caller's
    argument-relative rel."""
    top = None
    d = os.path.dirname(path)
    while os.path.isfile(os.path.join(d, "__init__.py")):
        top = d
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    if top is None:
        return None
    return os.path.relpath(path, top).replace(os.sep, "/")


def _iter_files(path: str, excludes: Sequence[str]):
    """Yield (abspath, rel) under `path`; rel is package-anchored when
    the file lives in a package (see `_package_rel`), else relative to
    the argument itself — so checker path scopes ("beacon/clock.py")
    match no matter where the tree sits on disk or which subtree the
    command line names."""
    path = os.path.abspath(path)
    if os.path.isfile(path):
        # excludes apply here too: naming a generated _pb2.py directly
        # must not lint what a directory scan deliberately skips
        if not any(fnmatch.fnmatch(os.path.basename(path), pat)
                   for pat in excludes):
            yield path, _package_rel(path) or os.path.basename(path)
        return
    for base, dirs, names in os.walk(path):
        dirs[:] = sorted(d for d in dirs
                         if d != "__pycache__" and not d.startswith("."))
        for name in sorted(names):
            if not name.endswith(".py"):
                continue
            if any(fnmatch.fnmatch(name, pat) for pat in excludes):
                continue
            full = os.path.join(base, name)
            yield full, _package_rel(full) or os.path.relpath(full, path)


def _parse_tree(paths: Sequence[str], excludes: Sequence[str],
                errors: Optional[List[str]] = None) -> List[ModuleInfo]:
    """Phase-1 parse of every .py under `paths` (dedup by abspath)."""
    modules: List[ModuleInfo] = []
    seen_paths = set()
    for root in paths:
        for full, rel in _iter_files(root, excludes):
            if full in seen_paths:
                continue
            seen_paths.add(full)
            try:
                with open(full, "r", encoding="utf-8") as f:
                    source = f.read()
                modules.append(ModuleInfo(full, rel, source))
            except (SyntaxError, UnicodeDecodeError, OSError) as e:
                if errors is not None:
                    errors.append(f"{rel}: {e}")
    return modules


# Fork-based sweep workers inherit these by copy-on-write; the indices
# they receive are offsets into _SWEEP_STATE["modules"].  Plain threads
# would not help here — the sweep is pure-Python AST walking and the GIL
# serializes it — while fork shares the parsed trees and the phase-1
# project for free and only findings (small, picklable) cross back.
_SWEEP_STATE: dict = {}

# files below this count sweep serially: fork + import costs more than
# the sweep itself, and the fixture-sized runs in the test-suite stay
# single-process and trivially debuggable
_PARALLEL_MIN_FILES = 24


def _sweep_module(idx: int):
    """Run every checker over one module.  Returns (idx, kept, covered,
    stale) where `kept` are findings the suppressions did not cover —
    the caller applies the baseline budget, which is global and must be
    consumed in deterministic module order."""
    module = _SWEEP_STATE["modules"][idx]
    checkers = _SWEEP_STATE["checkers"]
    project = _SWEEP_STATE["project"]
    supp = Suppressions(module.lines)
    kept: List[Finding] = []
    covered: List[Finding] = []
    seen = set()                # nested defs are walked by both their own
    for checker in checkers:            # pass and the enclosing one
        if getattr(checker, "uses_project", False):
            found = checker.check(module, project)
        else:
            found = checker.check(module)
        for finding in found:
            if finding in seen:
                continue
            seen.add(finding)
            if supp.covers(finding):
                covered.append(finding)
            else:
                kept.append(finding)
    ran = {c.name for c in checkers}
    stale = [f"{module.rel}:{line}: stale suppression "
             f"'# tpu-vet: {kind}={token}' (covers no current finding)"
             for line, kind, token in supp.stale(ran)]
    return idx, kept, covered, stale


def _sweep(modules, checkers, project) -> List[tuple]:
    """Per-module sweep results in module order.  Parallel (bounded fork
    pool) past _PARALLEL_MIN_FILES files on platforms with fork; output
    is byte-identical to the serial path because workers are pure
    functions of one module and the merge happens in submission order."""
    _SWEEP_STATE.update(modules=modules, checkers=checkers, project=project)
    try:
        n = len(modules)
        workers = min(8, os.cpu_count() or 1)
        if os.environ.get("TPU_VET_WORKERS", ""):
            workers = max(1, int(os.environ["TPU_VET_WORKERS"]))
        # a single-CPU box gains nothing from fork and pays its overhead
        use_parallel = n >= _PARALLEL_MIN_FILES and workers >= 2 and \
            os.environ.get("TPU_VET_SERIAL", "") != "1"
        if use_parallel:
            import multiprocessing
            if "fork" not in multiprocessing.get_all_start_methods():
                use_parallel = False
        if not use_parallel:
            return [_sweep_module(i) for i in range(n)]
        # warm the project's memoized global passes BEFORE forking so
        # every worker inherits them instead of rebuilding per process
        if project is not None and modules:
            _sweep_module(0)
        ctx = multiprocessing.get_context("fork")
        with ctx.Pool(processes=workers) as pool:
            chunk = max(1, n // (workers * 4))
            return pool.map(_sweep_module, range(n), chunksize=chunk)
    finally:
        _SWEEP_STATE.clear()


def run_vet(paths: Sequence[str], checkers: Optional[Iterable] = None,
            baseline: Optional[Dict[str, int]] = None,
            excludes: Sequence[str] = DEFAULT_EXCLUDES,
            context_paths: Sequence[str] = ()) -> Report:
    """Run `checkers` (default: all registered) over every .py file under
    `paths` and split raw findings into actionable / suppressed /
    baselined.

    Two-phase: all files parse first and feed the project-wide call
    graph; then checkers run per file.  Files under `context_paths` join
    phase 1 (cross-module resolution sees them) but are never checked —
    the incremental `--changed` mode passes the full package there so a
    two-file diff is still judged against the whole call graph.
    """
    if checkers is None:
        from .checkers import ALL_CHECKERS
        checkers = [c() for c in ALL_CHECKERS]
    else:
        checkers = list(checkers)
    report = Report()
    budget = dict(baseline or {})

    modules = _parse_tree(paths, excludes, report.errors)
    report.files = len(modules) + len(report.errors)
    checked_paths = {m.path for m in modules}
    context = [m for m in _parse_tree(context_paths, excludes)
               if m.path not in checked_paths]

    project = None
    if any(getattr(c, "uses_project", False) for c in checkers):
        from .project import Project
        project = Project(modules + context)

    for _idx, kept, covered, stale in _sweep(modules, checkers, project):
        report.suppressed.extend(covered)
        report.stale_suppressions.extend(stale)
        for finding in kept:
            if budget.get(finding.key, 0) > 0:
                budget[finding.key] -= 1
                report.baselined.append(finding)
            else:
                report.findings.append(finding)
    report.stale_baseline = sorted(
        k for k, v in budget.items() if v > 0)
    return report
