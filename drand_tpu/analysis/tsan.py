"""tpu-tsan runtime side: an opt-in lock-order sanitizer.

The static lock checker (checkers/locks.py) proves what the AST can
prove; this module watches what actually happens.  When ``DRAND_TSAN=1``
the ``common.make_lock/make_rlock/make_condition`` factories hand out
instrumented wrappers instead of raw ``threading`` primitives.  Each
wrapper records, per thread, the stack of locks currently held; every
acquisition attempted while other locks are held adds edges to a global
runtime lock-order graph.  At process exit (or on demand via
``report()``) the graph is scanned for cycles.

Findings (fail a ``chaos_smoke --tsan`` run):

  * **lock-order cycle** — two locks acquired in both orders anywhere in
    the process's life.  Edges carry the first acquisition stack of each
    direction so the report names both call paths.
  * **non-reentrant re-entry** — a thread acquiring a ``make_lock``/
    condition lock it already holds: a guaranteed self-deadlock the
    moment the interleaving lines up.

Warnings (reported, never fatal — a cold XLA compile under a lock is
slow, not wrong):

  * **long hold** — a lock held longer than ``DRAND_TSAN_HOLD_MS``
    (default 1000 ms).
  * **slow acquire** — waiting longer than ``DRAND_TSAN_WAIT_MS``
    (default 500 ms) to get a lock, i.e. measured contention.

Trust model: the sanitizer observes only locks built through the
factories — raw ``threading.Lock()`` construction stays invisible, and
the instrumentation never changes blocking semantics (a detected
re-entry is recorded, then the acquire proceeds and deadlocks exactly as
it would have; the SIGUSR1 held-lock table is how an operator reads the
wreck).  With ``DRAND_TSAN`` unset this module is never imported and the
serving path is byte-identical.

``threading.Condition`` needs no wrapper of its own: ``make_condition``
builds a stock Condition around an instrumented lock, and the
condition's own release/re-acquire in ``wait()`` flows through the
wrapper, so held-sets stay correct across cv waits for free.
"""

import os
import threading
import time
import traceback
from typing import Dict, List, Optional, Set, Tuple

__all__ = [
    "instrumented_lock", "instrumented_rlock", "enabled", "report",
    "reset", "findings", "warnings", "held_locks_by_thread",
    "render_held_table", "render_report",
]

_STACK_LIMIT = 12        # frames kept per recorded acquisition site


def enabled() -> bool:
    return os.environ.get("DRAND_TSAN", "") not in ("", "0")


def _hold_limit() -> float:
    return float(os.environ.get("DRAND_TSAN_HOLD_MS", "1000")) / 1000.0


def _wait_limit() -> float:
    return float(os.environ.get("DRAND_TSAN_WAIT_MS", "500")) / 1000.0


class _Registry:
    """Process-global sanitizer state.  Guarded by a RAW threading.Lock
    (never an instrumented one — the sanitizer must not sanitize
    itself)."""

    def __init__(self):
        self._mu = threading.Lock()
        self.seq = 0
        # (src lock id, dst lock id) -> (src name, dst name, stack text)
        self.edges: Dict[Tuple[int, int], Tuple[str, str, str]] = {}
        self.findings: List[dict] = []
        self.warnings: List[dict] = []
        # thread ident -> live reference to that thread's held stack
        self.thread_held: Dict[int, Tuple[str, list]] = {}

    def next_name(self, base: str) -> str:
        with self._mu:
            self.seq += 1
            return f"{base}#{self.seq}"

    def add_edges(self, held: list, lock: "_TsanLockBase") -> None:
        pairs = []
        for entry in held:
            src = entry.lock
            if src is lock:
                continue
            key = (id(src), id(lock))
            pairs.append((key, src.name))
        if not pairs:
            return
        with self._mu:
            fresh = [p for p in pairs if p[0] not in self.edges]
            if not fresh:
                return
            stack = _stack_text()
            for key, src_name in fresh:
                self.edges[key] = (src_name, lock.name, stack)

    def add_finding(self, kind: str, detail: str, stack: str = "") -> None:
        with self._mu:
            self.findings.append(
                {"kind": kind, "detail": detail, "stack": stack,
                 "thread": threading.current_thread().name})

    def add_warning(self, kind: str, detail: str, stack: str = "") -> None:
        with self._mu:
            self.warnings.append(
                {"kind": kind, "detail": detail, "stack": stack,
                 "thread": threading.current_thread().name})

    def register_thread(self, held: list) -> None:
        t = threading.current_thread()
        with self._mu:
            self.thread_held[t.ident] = (t.name, held)


_registry = _Registry()


class _HeldEntry:
    __slots__ = ("lock", "t0", "count")

    def __init__(self, lock, t0):
        self.lock = lock
        self.t0 = t0
        self.count = 1


_tls = threading.local()


def _held() -> list:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
        _registry.register_thread(held)
    return held


def _stack_text() -> str:
    frames = traceback.extract_stack()
    # drop the sanitizer's own frames off the top
    while frames and frames[-1].filename == __file__:
        frames.pop()
    return "".join(traceback.format_list(frames[-_STACK_LIMIT:]))


class _TsanLockBase:
    """Shared acquire/release bookkeeping.  Subclasses set ``reentrant``
    and build ``self._inner``."""

    reentrant = False
    kind = "lock"

    def __init__(self, name: str = ""):
        self.name = _registry.next_name(name or self._default_name())
        self._inner = self._make_inner()

    @staticmethod
    def _make_inner():
        return threading.Lock()

    def _default_name(self) -> str:
        # name by construction site: the first frame outside this module
        for f in reversed(traceback.extract_stack()):
            if f.filename != __file__ and "/common.py" not in \
                    f.filename.replace(os.sep, "/"):
                base = os.path.basename(f.filename)
                return f"{base}:{f.lineno}"
        return self.kind

    # -- bookkeeping ----------------------------------------------------------

    def _entry(self) -> Optional[_HeldEntry]:
        for e in _held():
            if e.lock is self:
                return e
        return None

    def _before_acquire(self, blocking: bool = True) -> None:
        # a try-acquire cannot deadlock, so it contributes neither re-entry
        # findings nor order-graph edges (classic lockdep treats trylock
        # the same way)
        if not blocking:
            return
        entry = self._entry()
        if entry is not None and not self.reentrant:
            _registry.add_finding(
                "reentry",
                f"non-reentrant {self.kind} {self.name} re-acquired by a "
                "thread that already holds it (guaranteed self-deadlock)",
                _stack_text())
        if entry is None:
            _registry.add_edges(_held(), self)

    def _after_acquire(self) -> None:
        held = _held()
        entry = self._entry()
        if entry is not None and self.reentrant:
            entry.count += 1
            return
        held.append(_HeldEntry(self, time.monotonic()))

    def _after_release(self) -> None:
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i].lock is self:
                entry = held[i]
                if self.reentrant and entry.count > 1:
                    entry.count -= 1
                    return
                dur = time.monotonic() - entry.t0
                del held[i]
                if dur > _hold_limit():
                    _registry.add_warning(
                        "long-hold",
                        f"{self.name} held for {dur * 1000:.0f} ms",
                        _stack_text())
                return

    # -- the lock protocol ----------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._before_acquire(blocking)
        t0 = time.monotonic()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            wait = time.monotonic() - t0
            if wait > _wait_limit():
                _registry.add_warning(
                    "slow-acquire",
                    f"{self.name} took {wait * 1000:.0f} ms to acquire "
                    "(contention)", _stack_text())
            self._after_acquire()
        return ok

    def release(self) -> None:
        self._inner.release()
        self._after_release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # threading.Condition probes ownership through these when present;
    # without them it falls back to a try-acquire probe that would
    # pollute the order graph
    def _is_owned(self) -> bool:
        return self._entry() is not None

    def __repr__(self):
        return f"<tsan {self.kind} {self.name} inner={self._inner!r}>"


class TsanLock(_TsanLockBase):
    reentrant = False
    kind = "lock"


class TsanRLock(_TsanLockBase):
    reentrant = True
    kind = "rlock"

    @staticmethod
    def _make_inner():
        return threading.RLock()

    # Condition(wrapped-rlock) uses these to fully release around wait();
    # mirror the bookkeeping so the held-set stays truthful across waits
    def _release_save(self):
        entry = self._entry()
        count = entry.count if entry is not None else 1
        if entry is not None:
            entry.count = 1          # _after_release pops it entirely
        state = self._inner._release_save()
        self._after_release()
        return (state, count)

    def _acquire_restore(self, saved):
        state, count = saved
        self._inner._acquire_restore(state)
        self._before_acquire()
        self._after_acquire()
        entry = self._entry()
        if entry is not None:
            entry.count = count


def instrumented_lock(name: str = "") -> TsanLock:
    return TsanLock(name)


def instrumented_rlock(name: str = "") -> TsanRLock:
    return TsanRLock(name)


# -- reporting ----------------------------------------------------------------


def _cycles() -> List[List[Tuple[int, int]]]:
    """Cycles in the runtime order graph, as edge-key lists.  Each
    2+-lock inversion is reported once (canonicalized on the smallest
    node id in the cycle)."""
    adj: Dict[int, List[int]] = {}
    for (a, b) in _registry.edges:
        adj.setdefault(a, []).append(b)
    out: List[List[Tuple[int, int]]] = []
    seen: Set[Tuple[int, ...]] = set()
    for start in list(adj):
        stack = [(start, [start])]
        while stack:
            cur, path = stack.pop()
            for nxt in adj.get(cur, ()):
                if nxt == start:
                    canon = tuple(sorted(set(path)))
                    if canon in seen:
                        continue
                    seen.add(canon)
                    cyc_nodes = path + [start]
                    out.append([(cyc_nodes[i], cyc_nodes[i + 1])
                                for i in range(len(cyc_nodes) - 1)])
                elif nxt not in path and len(path) < 8:
                    stack.append((nxt, path + [nxt]))
    return out


def findings() -> List[dict]:
    """Sanitizer findings so far: recorded re-entries plus lock-order
    cycles derived from the runtime graph right now."""
    with _registry._mu:
        out = list(_registry.findings)
        edges = dict(_registry.edges)
    for cyc in _cycles():
        names = [edges[k][0] for k in cyc if k in edges]
        if not names:
            continue
        first = cyc[0]
        stacks = "\n".join(
            f"-- {edges[k][0]} -> {edges[k][1]} first seen at:\n{edges[k][2]}"
            for k in cyc if k in edges)
        out.append({
            "kind": "lock-order-cycle",
            "detail": ("runtime lock-order cycle (deadlock candidate): "
                       + " -> ".join(names + [edges[first][0]])),
            "stack": stacks,
            "thread": "",
        })
    return out


def warnings() -> List[dict]:
    with _registry._mu:
        return list(_registry.warnings)


def report() -> dict:
    """The full sanitizer report: findings fail a --tsan run, warnings
    inform it."""
    f = findings()
    w = warnings()
    with _registry._mu:
        n_edges = len(_registry.edges)
    return {"enabled": enabled(), "findings": f, "warnings": w,
            "edges": n_edges}


def render_report(rep: Optional[dict] = None) -> str:
    rep = rep or report()
    lines = [f"tpu-tsan: {len(rep['findings'])} finding(s), "
             f"{len(rep['warnings'])} warning(s), "
             f"{rep['edges']} order edge(s)"]
    for f in rep["findings"]:
        lines.append(f"FINDING [{f['kind']}] {f['detail']}")
        if f.get("thread"):
            lines.append(f"  thread: {f['thread']}")
        if f.get("stack"):
            lines.extend("  " + s for s in f["stack"].splitlines())
    for w in rep["warnings"]:
        lines.append(f"warning [{w['kind']}] {w['detail']}")
    return "\n".join(lines)


def reset() -> None:
    """Drop all recorded state (test isolation)."""
    with _registry._mu:
        _registry.edges.clear()
        _registry.findings.clear()
        _registry.warnings.clear()


def held_locks_by_thread() -> Dict[str, List[str]]:
    """thread name -> names of locks it holds right now (best-effort
    snapshot; read racily by design — this feeds a signal-handler
    diagnostic, it must never block on the sanitizer mutex while a
    wedged thread holds it)."""
    out: Dict[str, List[str]] = {}
    for ident, (name, held) in list(_registry.thread_held.items()):
        names = [e.lock.name for e in list(held)]
        if names:
            out[name] = names
    return out


def render_held_table() -> str:
    table = held_locks_by_thread()
    if not table:
        return "tpu-tsan: no locks held by any thread\n"
    lines = ["tpu-tsan held-lock table:"]
    for tname in sorted(table):
        lines.append(f"  {tname}: " + " -> ".join(table[tname]))
    return "\n".join(lines) + "\n"


# With the sanitizer live, print the report at interpreter exit so a
# chaos soak that simply finishes still surfaces what it saw.  Findings
# go to stderr; a clean run stays quiet unless DRAND_TSAN_VERBOSE=1.
if enabled():                                   # pragma: no cover - atexit
    import atexit
    import sys

    def _exit_report():
        rep = report()
        if rep["findings"] or \
                os.environ.get("DRAND_TSAN_VERBOSE", "") == "1":
            sys.stderr.write(render_report(rep) + "\n")

    atexit.register(_exit_report)
