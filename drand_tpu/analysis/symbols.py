"""Shared AST/symbol pass: every checker reads one `ModuleInfo`.

The pass is done ONCE per file (parse, parent links, import table, class
attribute typing) so five checkers cost roughly one; checkers stay pure
consumers and never re-walk for bookkeeping.  Everything here is plain
`ast` — target files are parsed, never imported, so analyzing the JAX
kernels does not pull in JAX.
"""

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

# attribute kinds recognized by the class-attribute typing pass; the lock
# and store checkers key on these.  The `common.make_*` factories are the
# sanitizer-instrumentable spellings (drand_tpu/common.py): they MUST be
# typed here or converting a runtime module to the factory would silently
# drop it out of the whole lock analysis.
KIND_BY_CALL = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "condition",
    "threading.Event": "event",
    "threading.Thread": "thread",
    "queue.Queue": "queue",
    "queue.LifoQueue": "queue",
    "queue.PriorityQueue": "queue",
    "queue.SimpleQueue": "queue",
    "sqlite3.connect": "sqlite_conn",
    "make_lock": "lock",
    "make_rlock": "rlock",
    "make_condition": "condition",
    "common.make_lock": "lock",
    "common.make_rlock": "rlock",
    "common.make_condition": "condition",
    "drand_tpu.common.make_lock": "lock",
    "drand_tpu.common.make_rlock": "rlock",
    "drand_tpu.common.make_condition": "condition",
}

LOCK_KINDS = ("lock", "rlock", "condition")
# re-entrant acquisitions of these kinds self-deadlock (threading.Lock and
# a default Condition are non-recursive); RLock is re-entrant by design
NON_REENTRANT = ("lock", "condition")


def dotted(node: ast.AST) -> Optional[str]:
    """`self._store._conn` -> "self._store._conn"; None for anything that
    is not a pure Name/Attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class ClassInfo:
    name: str
    node: ast.ClassDef
    # attribute name -> kind (see KIND_BY_CALL) for `self.X = <ctor>()`
    attr_kinds: Dict[str, str] = field(default_factory=dict)
    # attribute name -> the full resolved constructor qualname
    attr_ctors: Dict[str, str] = field(default_factory=dict)
    methods: Dict[str, ast.FunctionDef] = field(default_factory=dict)
    base_names: List[str] = field(default_factory=list)

    def lock_attrs(self) -> List[str]:
        return [a for a, k in self.attr_kinds.items() if k in LOCK_KINDS]


def walk_scope(fn: ast.AST):
    """Walk a function's OWN body without descending into nested function
    definitions — each nested def is its own scope (a jitted nested `run`
    must not be judged by its enclosing factory's rules, a closure's
    returns are not the factory's returns)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


class ModuleInfo:
    """One parsed file + the symbol facts checkers share."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel.replace("\\", "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.parent: Dict[int, ast.AST] = {}
        self.imports: Dict[str, str] = {}
        self.classes: List[ClassInfo] = []
        self.module_defs: set = set()      # top-level def/class/assign names
        self.module_locks: Dict[str, str] = {}   # top-level lock name -> kind
        self._build()

    @property
    def dotted(self) -> str:
        """Module path as a dotted name relative to the scanned root
        ("net/client.py" -> "net.client", "crypto/__init__.py" ->
        "crypto") — the key the project-wide symbol table matches import
        targets against (by suffix, so absolute and relative spellings of
        the same module meet at one entry)."""
        rel = self.rel[:-3] if self.rel.endswith(".py") else self.rel
        if rel.endswith("/__init__"):
            rel = rel[:-len("/__init__")]
        return rel.replace("/", ".")

    def defs_by_qual(self) -> Dict[str, Tuple[Optional[ClassInfo], ast.AST]]:
        """Project-addressable definitions: top-level functions by name,
        class methods as "Class.method".  Nested defs are closures — not
        addressable across modules — and stay out."""
        out: Dict[str, Tuple[Optional[ClassInfo], ast.AST]] = {}
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out[node.name] = (None, node)
        for info in self.classes:
            for mname, fn in info.methods.items():
                out[f"{info.name}.{mname}"] = (info, fn)
        return out

    # -- construction --------------------------------------------------------

    def _build(self) -> None:
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parent[id(child)] = node
        self._collect_imports(self.tree)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                self.classes.append(self._class_info(node))
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                self.module_defs.add(node.name)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.module_defs.add(t.id)
                # module-level locks (`_PACK_LOCK = threading.Lock()`) are
                # lockset members for the interprocedural lock analysis
                if isinstance(node.value, ast.Call):
                    ctor = self.resolve(dotted(node.value.func) or "")
                    kind = KIND_BY_CALL.get(ctor)
                    if kind in LOCK_KINDS:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                self.module_locks[t.id] = kind
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name):
                self.module_defs.add(node.target.id)

    def _collect_imports(self, tree: ast.AST) -> None:
        """Import table covering function-local imports too (this codebase
        defers heavy imports into functions as a matter of style)."""
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    target = alias.name if alias.asname else \
                        alias.name.split(".")[0]
                    self.imports[local] = target
            elif isinstance(node, ast.ImportFrom):
                # relative imports keep their tail ("..beacon.clock" ->
                # "beacon.clock"); checkers match on suffixes
                mod = node.module or ""
                for alias in node.names:
                    local = alias.asname or alias.name
                    self.imports[local] = f"{mod}.{alias.name}" if mod \
                        else alias.name

    def _class_info(self, node: ast.ClassDef) -> ClassInfo:
        info = ClassInfo(name=node.name, node=node)
        for b in node.bases:
            d = dotted(b)
            if d:
                info.base_names.append(d.split(".")[-1])
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info.methods[item.name] = item
        # type `self.X = <ctor>(...)` wherever it appears in the class —
        # threads and queues are routinely created outside __init__.  The
        # ctor qualname is kept for EVERY constructor-shaped assignment
        # (kind or not): `self._reg = Registry()` is how the project-wide
        # resolver follows `self._reg.method()` across modules.
        for fn in info.methods.values():
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Assign):
                    continue
                if not isinstance(sub.value, ast.Call):
                    continue
                ctor = self.resolve(dotted(sub.value.func) or "")
                kind = KIND_BY_CALL.get(ctor)
                for t in sub.targets:
                    d = dotted(t)
                    if d and d.startswith("self.") and d.count(".") == 1:
                        attr = d.split(".", 1)[1]
                        if ctor:
                            info.attr_ctors.setdefault(attr, ctor)
                        if kind is not None:
                            info.attr_kinds[attr] = kind
                            info.attr_ctors[attr] = ctor
        return info

    # -- queries -------------------------------------------------------------

    def resolve(self, name: str) -> str:
        """Rewrite the head of a dotted chain through the import table:
        `_t.monotonic` -> `time.monotonic` after `import time as _t`."""
        if not name:
            return name
        head, _, tail = name.partition(".")
        target = self.imports.get(head)
        if target is None:
            return name
        return f"{target}.{tail}" if tail else target

    def enclosing(self, node: ast.AST, *types) -> Optional[ast.AST]:
        cur = self.parent.get(id(node))
        while cur is not None:
            if isinstance(cur, types):
                return cur
            cur = self.parent.get(id(cur))
        return None

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        return self.enclosing(node, ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)

    def enclosing_class(self, node: ast.AST) -> Optional[ClassInfo]:
        cls = self.enclosing(node, ast.ClassDef)
        if cls is None:
            return None
        for info in self.classes:
            if info.node is cls:
                return info
        return None

    def withs_holding(self, node: ast.AST) -> List[str]:
        """Dotted context-manager expressions of every `with` enclosing
        `node` within its own function (lock-holding analysis)."""
        held: List[str] = []
        fn = self.enclosing_function(node)
        cur = self.parent.get(id(node))
        while cur is not None and cur is not fn:
            if isinstance(cur, (ast.With, ast.AsyncWith)):
                for item in cur.items:
                    d = dotted(item.context_expr)
                    if d:
                        held.append(d)
            cur = self.parent.get(id(cur))
        return held

    def functions(self) -> Iterator[Tuple[Optional[ClassInfo], ast.AST]]:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield self.enclosing_class(node), node
