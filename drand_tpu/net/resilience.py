"""Resilience policy layer for the sync/partials hot path.

The reference drand survives flaky peers by shuffling sync candidates and
restarting idle streams (chain/beacon/sync_manager.go:302) but has no
structured retry, backoff, or peer-health memory: every dial gets the same
60-second timeout and a Byzantine peer is re-tried as eagerly as a healthy
one.  This module centralizes the three missing pieces:

  * `BackoffPolicy` — exponential backoff with full jitter, sampled from an
    injected `random.Random` so chaos tests replay byte-identically.
  * `CircuitBreaker` / `BreakerRegistry` — per-peer closed → open →
    half-open breakers (the Handel-style "stop paying for unresponsive
    peers" scoring, arXiv:1906.05132 §5), with every state change exported
    through `metrics.py` so an operator can watch a peer get quarantined.
  * `Deadline` — one overall budget for a whole sync pass / round, so a
    chain of RPCs shares a single clamp instead of stacking per-call 60s
    timeouts.

All waiting goes through the injected Clock's `wait_until`, never
`time.sleep`: production uses the daemon's RealClock; the chaos harness
(tests/chaos.py) injects an auto-advancing fake clock so retry/cooldown
schedules run instantly and deterministically.
"""

import os
import random
import threading

from ..common import make_lock
from typing import Callable, Dict, Iterable, List, Optional, Sequence


def _default_clock():
    """Deferred import: the net layer must stay importable without
    loading the beacon package (beacon.sync already imports this module;
    an import-time edge back would be one new beacon-side import away
    from a hard cycle)."""
    from ..beacon.clock import RealClock
    return RealClock()


# -- knobs (env-overridable; COMPONENTS.md "Resilience") ---------------------

DEFAULT_MAX_ATTEMPTS = int(os.environ.get("DRAND_RETRY_MAX_ATTEMPTS", "4"))
DEFAULT_BACKOFF_BASE = float(os.environ.get("DRAND_RETRY_BACKOFF_BASE", "0.25"))
DEFAULT_BACKOFF_CAP = float(os.environ.get("DRAND_RETRY_BACKOFF_CAP", "5.0"))
DEFAULT_BREAKER_FAILURES = int(os.environ.get("DRAND_BREAKER_FAILURES", "5"))
DEFAULT_BREAKER_COOLDOWN = float(os.environ.get("DRAND_BREAKER_COOLDOWN", "30"))
DEFAULT_SYNC_BUDGET = float(os.environ.get("DRAND_SYNC_BUDGET", "120"))

# breaker states (exported as the resilience_breaker_state gauge value)
CLOSED, OPEN, HALF_OPEN = 0, 1, 2
_STATE_NAMES = {CLOSED: "closed", OPEN: "open", HALF_OPEN: "half_open"}

# peer-score bounds (the Handel-style reliability rank, arXiv:1906.05132
# §5): every recorded success is +1, every failure -2, clamped so one
# burst can neither whitewash nor permanently bury a peer
SCORE_MAX = 10.0
SCORE_MIN = -10.0
SCORE_SUCCESS = 1.0
SCORE_FAILURE = -2.0


class DeadlineExceeded(Exception):
    """The operation's overall budget is spent."""


class BreakerOpen(Exception):
    """The peer's circuit breaker is open (cooldown not yet elapsed)."""


class Deadline:
    """Absolute expiry on an injected clock; one instance rides through a
    whole multi-RPC operation so retries share the budget."""

    def __init__(self, clock, expires: float):
        self.clock = clock
        self.expires = expires

    @classmethod
    def after(cls, clock, budget: float) -> "Deadline":
        return cls(clock, clock.now() + budget)

    @classmethod
    def at(cls, clock, when: float) -> "Deadline":
        return cls(clock, when)

    def remaining(self) -> float:
        return max(0.0, self.expires - self.clock.now())

    @property
    def expired(self) -> bool:
        return self.clock.now() >= self.expires

    def clamp(self, timeout: Optional[float] = None) -> float:
        """Per-call timeout bounded by what is left of the budget."""
        rem = self.remaining()
        if rem <= 0:
            raise DeadlineExceeded(f"budget spent at {self.expires}")
        return rem if timeout is None else min(timeout, rem)


class BackoffPolicy:
    """Exponential backoff with full jitter (delay ~ U(0, min(cap,
    base·factor^attempt)); the AWS-style scheme that avoids thundering
    herds).  `rng` is injected for deterministic replays."""

    def __init__(self, base: float = DEFAULT_BACKOFF_BASE,
                 factor: float = 2.0, cap: float = DEFAULT_BACKOFF_CAP,
                 jitter: bool = True):
        self.base = base
        self.factor = factor
        self.cap = cap
        self.jitter = jitter

    def delay(self, attempt: int, rng: Optional[random.Random] = None) -> float:
        top = min(self.cap, self.base * (self.factor ** attempt))
        if not self.jitter:
            return top
        return (rng or random).uniform(0.0, top)


class CircuitBreaker:
    """Closed → open after N consecutive failures → half-open probe after a
    cooldown; one successful probe closes it, a failed probe re-opens it.

    State is exported through metrics on every transition (the scrape shows
    `resilience_breaker_state{address=...}` plus a transitions counter)."""

    def __init__(self, key: str, clock=None,
                 failures: int = DEFAULT_BREAKER_FAILURES,
                 cooldown: float = DEFAULT_BREAKER_COOLDOWN,
                 scope: str = "default"):
        self.key = key
        self.clock = clock or _default_clock()
        self.failure_threshold = max(1, failures)
        self.cooldown = cooldown
        self.scope = scope
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False
        self._probe_started = 0.0
        self._score = 0.0
        self._last_transition = self.clock.now()
        self._lock = make_lock()
        self._export_state()

    # -- state ---------------------------------------------------------------

    @property
    def state(self) -> int:
        with self._lock:
            return self._state

    def state_name(self) -> str:
        return _STATE_NAMES[self.state]

    def _set_state(self, new: int) -> None:
        # caller holds the lock
        if new == self._state:
            return
        self._state = new
        self._last_transition = self.clock.now()
        self._export_state()
        from ..metrics import breaker_transitions, registered_label
        breaker_transitions.labels(
            self.scope, registered_label(self.key, ns="peer-address",
                                         limit=256),
            _STATE_NAMES[new]).inc()

    def _export_state(self) -> None:
        from ..metrics import breaker_state, registered_label
        breaker_state.labels(
            self.scope, registered_label(self.key, ns="peer-address",
                                         limit=256)).set(self._state)

    def next_probe_at(self) -> float:
        """Earliest clock time a call could be admitted (now for closed /
        half-open, cooldown expiry for open)."""
        with self._lock:
            if self._state == OPEN:
                return self._opened_at + self.cooldown
            return self.clock.now()

    # -- admission + accounting ----------------------------------------------

    def allow(self) -> bool:
        """True when a call may be attempted now.  An OPEN breaker whose
        cooldown has elapsed transitions to HALF_OPEN and admits exactly one
        probe; concurrent callers are rejected until the probe resolves.

        A probe whose caller never reported back (abandoned stream, caller
        crashed between admission and dial) would otherwise wedge the
        breaker in HALF_OPEN forever — stale probes are reclaimed after one
        cooldown so the breaker always self-heals."""
        with self._lock:
            now = self.clock.now()
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if now < self._opened_at + self.cooldown:
                    return False
                self._set_state(HALF_OPEN)
                self._probe_in_flight = True
                self._probe_started = now
                return True
            # HALF_OPEN: one probe at a time, stale probes reclaimed
            if self._probe_in_flight and \
                    now < self._probe_started + self.cooldown:
                return False
            self._probe_in_flight = True
            self._probe_started = now
            return True

    def force_probe(self) -> None:
        """Last-resort admission: an OPEN breaker transitions to HALF_OPEN
        before its cooldown elapses so the next `allow()` admits a probe.
        Used when EVERY candidate peer is quarantined — a healed partition
        must not idle the caller out for a full cooldown."""
        with self._lock:
            if self._state == OPEN:
                self._probe_in_flight = False
                self._set_state(HALF_OPEN)

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._probe_in_flight = False
            self._score = min(SCORE_MAX, self._score + SCORE_SUCCESS)
            self._set_state(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._probe_in_flight = False
            self._score = max(SCORE_MIN, self._score + SCORE_FAILURE)
            if self._state == HALF_OPEN:
                self._opened_at = self.clock.now()
                self._set_state(OPEN)
                return
            self._consecutive_failures += 1
            if self._state == CLOSED and \
                    self._consecutive_failures >= self.failure_threshold:
                self._opened_at = self.clock.now()
                self._set_state(OPEN)

    @property
    def score(self) -> float:
        with self._lock:
            return self._score

    def snapshot(self) -> dict:
        """Read-only view for consumers that must not reach into breaker
        internals (Handel level scheduling, /health): current score, state
        name, and the clock time of the last state transition."""
        with self._lock:
            return {"score": self._score,
                    "state": _STATE_NAMES[self._state],
                    "last_transition": self._last_transition}


def peer_key(peer) -> str:
    """Stable breaker key for anything the sync/fan-out planes call a peer
    (net.Peer, a bare address string, or a test stand-in)."""
    return getattr(peer, "address", None) or str(peer)


class BreakerRegistry:
    """Per-peer breakers under one scope label, plus the ranking primitive
    the sync path and the client transports share: healthy (closed) peers
    first, probe-ready ones next, quarantined ones last."""

    def __init__(self, clock=None, failures: int = DEFAULT_BREAKER_FAILURES,
                 cooldown: float = DEFAULT_BREAKER_COOLDOWN,
                 scope: str = "default"):
        self.clock = clock or _default_clock()
        self.failures = failures
        self.cooldown = cooldown
        self.scope = scope
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._lock = make_lock()

    def breaker(self, key: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(key)
            if br is None:
                br = CircuitBreaker(key, clock=self.clock,
                                    failures=self.failures,
                                    cooldown=self.cooldown, scope=self.scope)
                self._breakers[key] = br
            return br

    def preference(self, key: str) -> int:
        """0 = closed (or unknown), 1 = probe-eligible, 2 = quarantined."""
        with self._lock:
            br = self._breakers.get(key)
        if br is None:
            return 0
        st = br.state
        if st == CLOSED:
            return 0
        if st == HALF_OPEN or self.clock.now() >= br.next_probe_at():
            return 1
        return 2

    def rank(self, peers: Sequence[object],
             rng: Optional[random.Random] = None,
             key: Callable[[object], str] = peer_key) -> List[object]:
        """Breaker-aware failover order: shuffle (for load spreading), then
        stable-sort by breaker preference so closed-breaker peers lead and
        quarantined ones trail but are never dropped — they are the last
        resort once the healthy set is exhausted."""
        out = list(peers)
        (rng or random).shuffle(out)
        out.sort(key=lambda p: self.preference(key(p)))
        return out

    def next_probe_at(self, keys: Iterable[str]) -> float:
        """Earliest time any of `keys` will admit a call again."""
        now = self.clock.now()
        times = [self.breaker(k).next_probe_at() for k in keys]
        return min(times) if times else now

    def snapshot(self) -> Dict[str, str]:
        with self._lock:
            items = list(self._breakers.items())
        return {k: br.state_name() for k, br in items}

    def score_snapshot(self) -> Dict[str, dict]:
        """Read-only peer-score view — the ONE source of truth shared by
        Handel level scheduling and /health (score + state +
        last-transition per peer key; see CircuitBreaker.snapshot)."""
        with self._lock:
            items = list(self._breakers.items())
        return {k: br.snapshot() for k, br in items}

    def score(self, key: str) -> float:
        """Current score for one peer key (0.0 when unknown — an unseen
        peer ranks level with a neutral one, never below it)."""
        with self._lock:
            br = self._breakers.get(key)
        return 0.0 if br is None else br.score


class ResiliencePolicy:
    """One bundle of clock + backoff + breakers + retry budget, shared by
    every subsystem that talks to the same peer set (so a partial-send
    failure warms the breaker the sync peer-selection consults)."""

    def __init__(self, clock=None, backoff: Optional[BackoffPolicy] = None,
                 breakers: Optional[BreakerRegistry] = None,
                 max_attempts: int = DEFAULT_MAX_ATTEMPTS,
                 scope: str = "default", seed: Optional[int] = None,
                 stop: Optional[threading.Event] = None):
        self.clock = clock or _default_clock()
        self.backoff = backoff or BackoffPolicy()
        self.breakers = breakers or BreakerRegistry(clock=self.clock,
                                                    scope=scope)
        self.max_attempts = max(1, max_attempts)
        self.scope = scope
        self.rng = random.Random(seed)
        self._stop = stop or threading.Event()

    # -- breaker facade ------------------------------------------------------

    def breaker(self, key: str) -> CircuitBreaker:
        return self.breakers.breaker(key)

    def rank(self, peers: Sequence[object],
             key: Callable[[object], str] = peer_key) -> List[object]:
        return self.breakers.rank(peers, rng=self.rng, key=key)

    def peer_scores(self) -> Dict[str, dict]:
        return self.breakers.score_snapshot()

    # -- retry executor ------------------------------------------------------

    def sleep(self, delay: float) -> None:
        if delay > 0:
            self.clock.wait_until(self.clock.now() + delay, self._stop)

    def call(self, fn: Callable[[Optional[float]], object], *,
             key: Optional[str] = None, op: str = "rpc",
             timeout: Optional[float] = None,
             deadline: Optional[Deadline] = None,
             max_attempts: Optional[int] = None):
        """Run `fn(per_attempt_timeout)` with backoff-jittered retries.

        * `key` enables per-peer breaker accounting (None = no breaker, e.g.
          DKG setup signalling where the coordinator is EXPECTED to be down
          at first).
        * `deadline` caps the whole retry chain; each attempt's timeout is
          clamped to the remaining budget and the loop never sleeps past it.
        * raises `BreakerOpen` without dialing when the breaker rejects,
          `DeadlineExceeded` when the budget is spent before an attempt, and
          the last underlying error once attempts are exhausted.
        """
        from ..metrics import deadline_exceeded_total, retries_total
        br = self.breakers.breaker(key) if key is not None else None
        attempts = max_attempts or self.max_attempts
        last_err: Optional[Exception] = None
        for attempt in range(attempts):
            if self._stop.is_set():
                break
            # clamp BEFORE breaker admission: an expired budget must not
            # consume (and then strand) the breaker's half-open probe slot
            try:
                per_call = (deadline.clamp(timeout) if deadline is not None
                            else timeout)
            except DeadlineExceeded:
                deadline_exceeded_total.labels(self.scope, op).inc()
                raise
            if br is not None and not br.allow():
                if last_err is not None:
                    # the breaker was opened by THIS call's own failed
                    # attempt: surface that error, don't mask it as a
                    # client-side rejection (callers treat BreakerOpen as
                    # "nothing was dialed")
                    break
                raise BreakerOpen(f"{self.scope}/{key} open")
            try:
                result = fn(per_call)
            except Exception as e:   # noqa: BLE001 — transport errors vary
                last_err = e
                if br is not None:
                    br.record_failure()
                delay = self.backoff.delay(attempt, self.rng)
                out_of_budget = (deadline is not None
                                 and deadline.remaining() <= delay)
                if attempt + 1 >= attempts or out_of_budget:
                    break
                retries_total.labels(self.scope, op).inc()
                self.sleep(delay)
                continue
            if br is not None:
                br.record_success()
            return result
        if last_err is None:     # stopped before the first attempt completed
            raise DeadlineExceeded(f"{self.scope}/{op} stopped")
        raise last_err
