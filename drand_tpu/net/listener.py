"""gRPC listeners + gateways (net/listener.go:37-209, net/gateway.go:17-105,
net/control.go:23-96).

A `PrivateGateway` is the daemon's composite network face: a serving
listener (Protocol + Public on the node-to-node port) plus the dialing
`ProtocolClient`.  The control plane is a separate localhost listener
serving the `Control` service for the CLI.
"""

import threading
from concurrent import futures
from typing import Optional

import grpc

from . import services
from .client import CertManager, Peer, ProtocolClient


class Listener:
    """One gRPC server bound to an address, serving given (spec, impl)
    pairs.  TLS when cert/key paths are provided (net/listener.go:132-166).

    `admission` (net/admission.py AdmissionController) installs the
    serving-plane interceptor: every RPC is classified critical / normal /
    sheddable and admitted (or shed with RESOURCE_EXHAUSTED + a
    retry-after trailer) BEFORE its service method runs.  The worker pool
    stays deliberately bounded — admission control decides who gets a
    worker; the pool size only caps parallelism."""

    def __init__(self, address: str, handlers, tls_cert: Optional[str] = None,
                 tls_key: Optional[str] = None, max_workers: int = 16,
                 admission=None, identity=None):
        self.address = address
        interceptors = ()
        max_rpcs = None
        if admission is not None:
            from .admission import AdmissionInterceptor
            interceptors = (AdmissionInterceptor(admission),)
            # the TOKENS must be the binding constraint, not the executor:
            # with fewer workers than tokens, a read flood would fill the
            # worker pool and queue critical partials in the executor's
            # unbounded queue BEFORE their interceptor (which would admit
            # them via the reserve) ever runs.  Workers are lazy-spawned,
            # so the headroom costs nothing while idle; maximum_concurrent_
            # rpcs backstops the executor queue itself (gRPC answers the
            # overflow with RESOURCE_EXHAUSTED before accepting the RPC).
            max_workers = max(max_workers, admission.capacity + 8)
            max_rpcs = 2 * admission.capacity
        self.server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            interceptors=interceptors,
            maximum_concurrent_rpcs=max_rpcs)
        self.server.add_generic_rpc_handlers(
            tuple(spec.handler(impl) for spec, impl in handlers))
        if identity is not None:
            # mTLS (net/identity.py, ISSUE 19): hot-reloadable server
            # credentials that REQUIRE a client certificate — the peer's
            # SAN set becomes its authenticated identity downstream.
            self.port = self.server.add_secure_port(
                address, identity.server_credentials())
        elif tls_cert and tls_key:
            with open(tls_key, "rb") as f:
                key = f.read()
            with open(tls_cert, "rb") as f:
                crt = f.read()
            creds = grpc.ssl_server_credentials(((key, crt),))
            self.port = self.server.add_secure_port(address, creds)
        else:
            self.port = self.server.add_insecure_port(address)
        if self.port == 0:
            raise OSError(f"cannot bind {address}")

    def start(self) -> None:
        self.server.start()

    def stop(self, grace: float = 1.0) -> None:
        # bounded: grace covers in-flight RPC drain, the pad covers gRPC's
        # own teardown — a stuck handler must not hang daemon shutdown
        self.server.stop(grace).wait(timeout=grace + 10.0)


class PrivateGateway:
    """Serving + dialing composite for the node-to-node plane
    (net/gateway.go:17-105).  `protocol_impl` and `public_impl` provide the
    snake_case RPC methods of their service specs."""

    def __init__(self, address: str, protocol_impl, public_impl,
                 certs: Optional[CertManager] = None,
                 tls_cert: Optional[str] = None, tls_key: Optional[str] = None,
                 resilience=None, admission=None, identity=None):
        self.listener = Listener(
            address,
            [(services.PROTOCOL, protocol_impl), (services.PUBLIC, public_impl)],
            tls_cert=tls_cert, tls_key=tls_key, admission=admission,
            identity=identity)
        self.client = ProtocolClient(certs=certs, resilience=resilience,
                                     identity=identity)
        host = address.rsplit(":", 1)[0]
        self.listen_addr = f"{host}:{self.listener.port}"

    def start_all(self) -> None:
        self.listener.start()

    def stop_all(self) -> None:
        self.listener.stop()
        self.client.close()


class ControlListener:
    """Localhost control-plane server (net/control.go:23-66)."""

    def __init__(self, control_impl, port: int = 0, host: str = "127.0.0.1",
                 identity=None):
        self.listener = Listener(f"{host}:{port}",
                                 [(services.CONTROL, control_impl)],
                                 identity=identity)
        self.port = self.listener.port

    def start(self) -> None:
        self.listener.start()

    def stop(self) -> None:
        self.listener.stop()


class ControlClient:
    """CLI-side control-plane client (net/control.go:68-96).

    When the daemon runs with an identity plane the control listener also
    requires mTLS; point the client at the same cert dir (explicitly via
    `identity_dir`, or the DRAND_IDENTITY_DIR env the CLI already exports
    for the daemon) so operator subcommands keep working."""

    def __init__(self, port: int, host: str = "127.0.0.1",
                 timeout: float = 10.0, identity_dir: Optional[str] = None):
        target = f"{host}:{port}"
        if identity_dir is None:
            import os
            identity_dir = os.environ.get("DRAND_IDENTITY_DIR") or None
        if identity_dir:
            from .identity import IdentityPlane
            plane = IdentityPlane(identity_dir)
            self.channel = grpc.secure_channel(
                target, plane.channel_credentials(),
                # per-node certs carry localhost SANs, but name the target
                # explicitly so dialing via 127.0.0.1 always verifies
                options=(("grpc.ssl_target_name_override", "localhost"),))
        else:
            self.channel = grpc.insecure_channel(target)
        self.timeout = timeout
        self.stub = services.CONTROL.stub(self.channel,
                                          default_timeout=timeout)

    def close(self) -> None:
        self.channel.close()
