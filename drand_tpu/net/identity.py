"""Identity plane: mTLS peer identity for the node-to-node gRPC planes.

Until this module, TLS on the private plane was server-side only and the
Handel `sender_index` binding fell back to an IP-literal heuristic — the
overlay's Byzantine defenses (demotion, impersonation rejection, breaker
scoring) assumed an identity the transport never actually provided.  The
identity plane closes that gap in three pieces:

  * **Provisioning** (`provision_ca` / `issue_cert` / `provision_fleet`):
    a private CA plus per-node EC-P256 certs whose SANs carry the node's
    roster hosts (DNS name + IP literals + localhost for the control
    plane).  Pure `openssl`-CLI subprocess work — the container has no
    Python `cryptography` package, and key material never transits this
    process beyond the files openssl itself writes (0600).

  * **`IdentityPlane`**: the daemon-side credential holder.  Watches a
    cert dir (`node.key`, `node.crt`, `ca.crt`), reloads atomically on
    mtime change (rate-limited on the daemon clock), and exposes

      - `server_credentials()` — `grpc.dynamic_ssl_server_credentials`
        with client-auth REQUIRED; the per-handshake fetcher picks up
        rotated certs without a listener restart,
      - `channel_credentials()` — client cert + CA roots for outbound
        dials, epoch-tagged so connection pools rebuild after rotation,
      - an expiry state machine: ``fresh`` -> ``grace`` (cert past
        notAfter but within the grace window: metered warning, still
        serving) -> ``expired`` (still serving — a mis-rotated cert
        degrades loudly, it never bricks a live committee).

  * **`PeerIdentity`**: the authenticated identity of an inbound peer,
    extracted from the gRPC auth context (cert SANs + CN).  The Handel
    coordinator binds claimed `sender_index` values to it — cert SAN <->
    roster entry — which makes DNS-named rosters enforceable where the
    old heuristic could only pin IP literals.

Layering: this module must not import core/ or beacon/ — consumers hand
in clocks and rosters; everything here is transport-level.
"""

import os
import ssl
import subprocess
import threading

from ..common import make_lock
from dataclasses import dataclass, field
from typing import Optional, Tuple

import grpc

# cert-dir file layout (one dir per node)
KEY_FILE = "node.key"
CERT_FILE = "node.crt"
CA_FILE = "ca.crt"

DEFAULT_RELOAD_INTERVAL = 5.0       # seconds between cert-dir stat sweeps
DEFAULT_EXPIRY_GRACE = 24 * 3600.0  # warning window past notAfter

STATE_FRESH = "fresh"
STATE_GRACE = "grace"
STATE_EXPIRED = "expired"
_STATE_GAUGE = {STATE_FRESH: 0, STATE_GRACE: 1, STATE_EXPIRED: 2}

OPENSSL = os.environ.get("DRAND_OPENSSL", "openssl")


class IdentityError(RuntimeError):
    """Provisioning or credential-load failure."""


# -- provisioning (openssl CLI; no Python crypto dependency) ------------------

def _run_openssl(args, workdir: Optional[str] = None) -> str:
    proc = subprocess.run([OPENSSL] + args, capture_output=True, text=True,
                          timeout=60, cwd=workdir)
    if proc.returncode != 0:
        raise IdentityError(
            f"openssl {args[0]} failed rc={proc.returncode}: "
            f"{proc.stderr.strip()[:500]}")
    return proc.stdout


def provision_ca(ca_dir: str, cn: str = "drand-identity-ca",
                 days: int = 365) -> str:
    """Create a self-signed CA (EC P-256) under `ca_dir`; returns the dir.
    Idempotent: an existing ca.key/ca.crt pair is left untouched."""
    os.makedirs(ca_dir, exist_ok=True)
    key = os.path.join(ca_dir, "ca.key")
    crt = os.path.join(ca_dir, CA_FILE)
    if os.path.exists(key) and os.path.exists(crt):
        return ca_dir
    _run_openssl(["req", "-x509", "-newkey", "ec", "-pkeyopt",
                  "ec_paramgen_curve:prime256v1", "-nodes",
                  "-keyout", key, "-out", crt,
                  "-subj", f"/CN={cn}", "-days", str(days)])
    os.chmod(key, 0o600)
    return ca_dir


def _san_entries(hosts) -> str:
    parts = []
    for h in hosts:
        h = str(h).strip()
        if not h:
            continue
        is_ip = h.replace(".", "").replace(":", "").isdigit() or ":" in h
        parts.append(f"IP:{h}" if is_ip else f"DNS:{h}")
    if not parts:
        raise IdentityError("cert needs at least one SAN host")
    return ",".join(parts)


def issue_cert(cert_dir: str, name: str, hosts, ca_dir: str,
               days: int = 365) -> str:
    """Issue `cert_dir/node.{key,crt}` for `name` with SANs for every
    entry in `hosts`, signed by `ca_dir`'s CA, and copy ca.crt alongside.
    The cert carries both serverAuth and clientAuth EKUs — one identity
    serves and dials.  Returns cert_dir."""
    os.makedirs(cert_dir, exist_ok=True)
    key = os.path.join(cert_dir, KEY_FILE)
    csr = os.path.join(cert_dir, ".node.csr")
    crt = os.path.join(cert_dir, CERT_FILE)
    ext = os.path.join(cert_dir, ".san.ext")
    _run_openssl(["req", "-new", "-newkey", "ec", "-pkeyopt",
                  "ec_paramgen_curve:prime256v1", "-nodes",
                  "-keyout", key, "-out", csr, "-subj", f"/CN={name}"])
    os.chmod(key, 0o600)
    with open(ext, "w") as f:
        f.write(f"subjectAltName={_san_entries(hosts)}\n"
                "extendedKeyUsage=serverAuth,clientAuth\n")
    _run_openssl(["x509", "-req", "-in", csr,
                  "-CA", os.path.join(ca_dir, CA_FILE),
                  "-CAkey", os.path.join(ca_dir, "ca.key"),
                  "-CAcreateserial", "-out", crt,
                  "-days", str(days), "-extfile", ext])
    # a rotation must land atomically from the plane's point of view:
    # the watcher reads key+crt only after both mtimes settle, and the
    # csr/ext scratch files are removed so the dir holds only the trio
    for scratch in (csr, ext):
        try:
            os.unlink(scratch)
        except OSError:
            pass
    with open(os.path.join(ca_dir, CA_FILE), "rb") as f:
        ca_pem = f.read()
    with open(os.path.join(cert_dir, CA_FILE), "wb") as f:
        f.write(ca_pem)
    return cert_dir


def provision_fleet(root: str, names_to_hosts, days: int = 365) -> dict:
    """Provision a CA at `root/ca` plus one cert dir per roster entry:
    `names_to_hosts` maps node name -> iterable of hosts (the roster
    address hosts; 127.0.0.1/localhost are always appended so the
    control plane and loopback dials verify).  Returns {name: cert_dir}."""
    ca = provision_ca(os.path.join(root, "ca"), days=days)
    out = {}
    for name, hosts in names_to_hosts.items():
        all_hosts = list(hosts)
        for extra in ("127.0.0.1", "localhost"):
            if extra not in all_hosts:
                all_hosts.append(extra)
        out[name] = issue_cert(os.path.join(root, name), name, all_hosts,
                               ca, days=days)
    return out


# -- cert inspection ----------------------------------------------------------

def cert_facts(path: str) -> dict:
    """notAfter (epoch seconds) + SAN names + CN of a PEM cert, without
    the `cryptography` package: the stdlib test decoder first, the
    openssl CLI as fallback.  Unknown fields come back as None/()."""
    not_after, names, cn = None, (), ""
    try:
        info = ssl._ssl._test_decode_cert(path)      # noqa: SLF001
        if info.get("notAfter"):
            not_after = ssl.cert_time_to_seconds(info["notAfter"])
        names = tuple(v for k, v in info.get("subjectAltName", ())
                      if k in ("DNS", "IP Address"))
        for rdn in info.get("subject", ()):
            for k, v in rdn:
                if k == "commonName":
                    cn = v
    except Exception:
        try:
            out = _run_openssl(["x509", "-in", path, "-noout", "-enddate"])
            stamp = out.split("=", 1)[1].strip()
            not_after = ssl.cert_time_to_seconds(stamp)
        except Exception:
            not_after = None
    return {"not_after": not_after, "names": names, "common_name": cn}


# -- authenticated peer identity ----------------------------------------------

@dataclass(frozen=True)
class PeerIdentity:
    """The transport-authenticated identity of an inbound peer: the SAN
    names (DNS + IP) and CN of the client cert the mTLS handshake
    verified.  `matches(host)` is the roster-binding primitive: a claimed
    roster entry is this peer iff its host appears among the cert names."""

    names: Tuple[str, ...] = ()
    common_name: str = ""

    def matches(self, host: str) -> bool:
        if not host:
            return False
        h = host.lower()
        return any(h == n.lower() for n in self.names) \
            or (self.common_name and h == self.common_name.lower())

    @property
    def label(self) -> str:
        """Metrics/trailer label: the stable name of this identity."""
        return self.common_name or (self.names[0] if self.names else "?")


def peer_identity(context) -> Optional[PeerIdentity]:
    """Extract the authenticated PeerIdentity from a gRPC servicer
    context, or None on a plaintext / unauthenticated transport."""
    try:
        auth = context.auth_context()
    except Exception:
        return None
    if not auth or not auth.get("transport_security_type"):
        return None
    sans = tuple(v.decode("utf-8", "replace")
                 for v in auth.get("x509_subject_alternative_name", ()))
    cns = auth.get("x509_common_name", ())
    cn = cns[0].decode("utf-8", "replace") if cns else ""
    if not sans and not cn:
        return None
    return PeerIdentity(names=sans, common_name=cn)


# -- the daemon-side credential plane -----------------------------------------

@dataclass
class _Creds:
    """One loaded credential generation (immutable once published).
    The private key stays out of __repr__ — a generation that surfaces
    in a log line or exception must never carry key material."""
    key_pem: bytes = field(repr=False)
    cert_pem: bytes
    ca_pem: bytes
    not_after: Optional[float]
    names: Tuple[str, ...]
    common_name: str
    stamp: tuple                      # (key mtime_ns, crt mtime_ns, ca ...)
    epoch: int = 0
    channel: Optional[grpc.ChannelCredentials] = field(
        default=None, repr=False)


class IdentityPlane:
    """Hot-reloadable mTLS credentials for one node.

    Reads `node.key` / `node.crt` / `ca.crt` from `cert_dir`; rotation =
    overwrite those files (the issue path above, or any external PKI) —
    the plane picks the new trio up atomically on the next
    `maybe_reload()` sweep (rate-limited on the injected daemon clock;
    the server-credential fetcher and /health both drive it, so a live
    daemon converges within one handshake or health probe).

    Expiry never hard-fails serving: past `notAfter` the plane enters a
    metered ``grace`` state, past `notAfter + expiry_grace` it reports
    ``expired`` — both keep the last-good credentials active, because a
    committee bricked by a calendar is strictly worse than one serving
    on a stale cert while the operator rotates."""

    def __init__(self, cert_dir: str, clock=None,
                 reload_interval: float = DEFAULT_RELOAD_INTERVAL,
                 expiry_grace: float = DEFAULT_EXPIRY_GRACE, log=None):
        self.cert_dir = cert_dir
        self.clock = clock
        self.reload_interval = reload_interval
        self.expiry_grace = expiry_grace
        self.log = log
        self._lock = make_lock()
        self._creds: Optional[_Creds] = None
        self._next_sweep = float("-inf")
        self._reloads = 0
        self._last_state = None
        self._load(initial=True)

    # -- clock ---------------------------------------------------------------

    def _now(self) -> float:
        if self.clock is None:
            from ..beacon.clock import RealClock
            self.clock = RealClock()
        return self.clock.now()

    # -- loading -------------------------------------------------------------

    def _paths(self):
        return (os.path.join(self.cert_dir, KEY_FILE),
                os.path.join(self.cert_dir, CERT_FILE),
                os.path.join(self.cert_dir, CA_FILE))

    def _stamp(self) -> Optional[tuple]:
        try:
            return tuple(os.stat(p).st_mtime_ns for p in self._paths())
        except OSError:
            return None

    def _load(self, initial: bool = False) -> bool:
        """Read the trio into a fresh generation and swap it in.  All
        three files are read BEFORE the swap — a torn rotation (key
        written, crt not yet) fails wholesale and keeps the last-good
        generation."""
        from ..metrics import identity_cert_reloads
        key_p, crt_p, ca_p = self._paths()
        stamp = self._stamp()
        if stamp is None:
            if initial:
                raise IdentityError(
                    f"identity cert dir incomplete: {self.cert_dir} needs "
                    f"{KEY_FILE} + {CERT_FILE} + {CA_FILE}")
            identity_cert_reloads.labels("error").inc()
            return False
        try:
            with open(key_p, "rb") as f:
                key_pem = f.read()
            with open(crt_p, "rb") as f:
                cert_pem = f.read()
            with open(ca_p, "rb") as f:
                ca_pem = f.read()
            facts = cert_facts(crt_p)
        except OSError as e:
            if initial:
                raise IdentityError(f"identity load failed: {e}")
            identity_cert_reloads.labels("error").inc()
            return False
        with self._lock:
            epoch = 0 if self._creds is None else self._creds.epoch + 1
            self._creds = _Creds(
                key_pem=key_pem, cert_pem=cert_pem, ca_pem=ca_pem,
                not_after=facts["not_after"], names=facts["names"],
                common_name=facts["common_name"], stamp=stamp, epoch=epoch)
        if not initial:
            self._reloads += 1
            identity_cert_reloads.labels("ok").inc()
            if self.log is not None:
                self.log.info("identity certs reloaded", epoch=epoch,
                              names=list(facts["names"]))
        return True

    def maybe_reload(self, force: bool = False) -> bool:
        """Rate-limited cert-dir sweep; returns True when a new
        generation was swapped in."""
        now = self._now()
        if not force and now < self._next_sweep:
            return False
        self._next_sweep = now + self.reload_interval
        stamp = self._stamp()
        with self._lock:
            current = self._creds.stamp if self._creds is not None else None
        if stamp is None or stamp == current:
            self._refresh_state_metric()
            return False
        ok = self._load()
        self._refresh_state_metric()
        return ok

    # -- expiry state machine ------------------------------------------------

    def state(self) -> str:
        with self._lock:
            not_after = self._creds.not_after if self._creds else None
        if not_after is None:
            return STATE_FRESH
        now = self._now()
        if now <= not_after:
            return STATE_FRESH
        if now <= not_after + self.expiry_grace:
            return STATE_GRACE
        return STATE_EXPIRED

    def _refresh_state_metric(self) -> None:
        from ..metrics import identity_cert_state
        st = self.state()
        identity_cert_state.set(_STATE_GAUGE[st])
        if st != self._last_state:
            if st != STATE_FRESH and self.log is not None:
                self.log.warning("identity cert past notAfter",
                                 state=st, cert_dir=self.cert_dir)
            self._last_state = st

    # -- credentials -----------------------------------------------------------

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._creds.epoch if self._creds is not None else -1

    def names(self) -> Tuple[str, ...]:
        with self._lock:
            return self._creds.names if self._creds is not None else ()

    def server_credentials(self) -> grpc.ServerCredentials:
        """Dynamic server credentials with REQUIRED client auth.  gRPC
        calls the fetcher on every handshake; it sweeps the cert dir and
        republishes the config only when the generation changed."""
        with self._lock:
            creds = self._creds
        initial = grpc.ssl_server_certificate_configuration(
            ((creds.key_pem, creds.cert_pem),),
            root_certificates=creds.ca_pem)
        served_epoch = [creds.epoch]

        def fetch():
            self.maybe_reload()
            with self._lock:
                cur = self._creds
            if cur.epoch == served_epoch[0]:
                return None                     # keep the current config
            served_epoch[0] = cur.epoch
            return grpc.ssl_server_certificate_configuration(
                ((cur.key_pem, cur.cert_pem),),
                root_certificates=cur.ca_pem)

        return grpc.dynamic_ssl_server_credentials(
            initial, fetch, require_client_authentication=True)

    def channel_credentials(self) -> grpc.ChannelCredentials:
        """Client-side credentials (CA roots + this node's cert/key),
        cached per generation — dial pools key their channels on
        `epoch`, so a rotation rebuilds connections lazily."""
        with self._lock:
            creds = self._creds
            if creds.channel is None:
                creds.channel = grpc.ssl_channel_credentials(
                    root_certificates=creds.ca_pem,
                    private_key=creds.key_pem,
                    certificate_chain=creds.cert_pem)
            return creds.channel

    # -- observability ---------------------------------------------------------

    def status(self) -> dict:
        """/health identity block (also drives the reload sweep, so a
        probed daemon converges on rotated certs without traffic)."""
        self.maybe_reload()
        with self._lock:
            creds = self._creds
        return {
            "cert_dir": self.cert_dir,
            "state": self.state(),
            "not_after": creds.not_after if creds else None,
            "names": list(creds.names) if creds else [],
            "common_name": creds.common_name if creds else "",
            "epoch": creds.epoch if creds else -1,
            "reloads": self._reloads,
            "expiry_grace": self.expiry_grace,
        }
