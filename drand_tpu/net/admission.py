"""Serving-plane admission control: priority classes, load shedding, and
a graceful-degradation ladder (ROADMAP item 5a; the resource-exhaustion
failure class of the beacon-client security review, arXiv:2109.11677).

The daemon's inbound surfaces used to be unprotected: a flood of public
reads could occupy every gRPC worker and starve partial-signature
aggregation — costing live rounds to save a CDN a cache miss.  This
module is ONE passive controller every inbound surface consults before
doing work:

  * **Priority classes.**  `critical` (Protocol partials/DKG RPCs) is
    never shed and has reserved concurrency — `critical_reserve` tokens
    no other class can take.  `normal` (SyncChain catch-up streams) gets
    per-peer fair-share caps and chunk pacing so one hungry peer cannot
    monopolize the pool.  `sheddable` (public gRPC/REST reads) is first
    to go: it never waits for a token, and a shed costs one small write
    before any parsing or routing.
  * **Concurrency tokens + queue-wait signal.**  Admission is decided by
    tokens (`capacity` total, `capacity - critical_reserve` for the
    non-critical classes) plus the p99 of recent admission waits,
    measured on the injected Clock.  When the p99 crosses `shed_wait`
    the controller climbs the degradation ladder; it climbs back down
    hysteretically (`recover_wait` < `shed_wait`, one step per `dwell`
    seconds) so a load spike cannot make it flap.
  * **Degradation ladder.**  Levels, in order:
        0 nominal          — everything admitted
        1 shed-public      — sheddable class rejected outright
        2 pause-background — + the verify service's background lane is
                             paused and scheduled integrity scans defer
                             (requeue-never-fail: the work waits, it is
                             not dropped)
        3 shed-normal      — + normal class rejected; critical only
    Background work is sacrificed BEFORE any normal-class shed: a sync
    peer's catch-up matters more than our own housekeeping.
  * **Cheap, well-formed rejections.**  gRPC callers get
    `RESOURCE_EXHAUSTED` with a `retry-after` trailer (the
    `AdmissionInterceptor` below, wired by net/listener.py); the REST
    edge turns a `Shed` into `429` + `Retry-After` before the request
    line is even parsed (http_server.py).

The controller is deliberately PASSIVE — no threads of its own; levels
are reassessed on every admit/release/snapshot from the injected clock —
so it adds one lock acquisition to the serving path and nothing else.
"""

import os
import threading

from ..common import make_condition
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

CLASS_CRITICAL = "critical"
CLASS_NORMAL = "normal"
CLASS_SHEDDABLE = "sheddable"
CLASSES = (CLASS_CRITICAL, CLASS_NORMAL, CLASS_SHEDDABLE)

LEVEL_NOMINAL = 0
LEVEL_SHED_PUBLIC = 1
LEVEL_PAUSE_BACKGROUND = 2
LEVEL_SHED_NORMAL = 3
LEVEL_NAMES = {LEVEL_NOMINAL: "nominal",
               LEVEL_SHED_PUBLIC: "shed-public",
               LEVEL_PAUSE_BACKGROUND: "pause-background",
               LEVEL_SHED_NORMAL: "shed-normal"}

# Module defaults; Config.admission_* overrides per daemon, the env vars
# override the module defaults (the DRAND_RETRY_* convention of
# net/resilience.py).
DEFAULT_CAPACITY = int(os.environ.get("DRAND_ADMISSION_CAPACITY", "64"))
DEFAULT_CRITICAL_RESERVE = int(
    os.environ.get("DRAND_ADMISSION_RESERVE", "8"))
DEFAULT_MAX_STREAMS_PER_PEER = int(
    os.environ.get("DRAND_ADMISSION_PEER_STREAMS", "2"))
DEFAULT_SHED_WAIT = float(os.environ.get("DRAND_ADMISSION_SHED_WAIT", "0.25"))
DEFAULT_RECOVER_WAIT = float(
    os.environ.get("DRAND_ADMISSION_RECOVER_WAIT", "0.05"))
DEFAULT_DWELL = float(os.environ.get("DRAND_ADMISSION_DWELL", "5"))
DEFAULT_NORMAL_WAIT = float(
    os.environ.get("DRAND_ADMISSION_NORMAL_WAIT", "2"))
DEFAULT_PACE_RATE = float(os.environ.get("DRAND_ADMISSION_PACE_RATE", "4096"))
DEFAULT_PACE_BURST = int(os.environ.get("DRAND_ADMISSION_PACE_BURST", "512"))
DEFAULT_RETRY_AFTER = float(
    os.environ.get("DRAND_ADMISSION_RETRY_AFTER", "1"))

# why a request was shed (the Shed.reason field; tests + the ladder
# assertion distinguish anti-monopoly sheds from pressure sheds)
REASON_LEVEL = "level"          # the degradation ladder said no
REASON_CAPACITY = "capacity"    # no token free (and the class won't wait)
REASON_PEER_CAP = "peer-cap"    # per-peer fair-share stream cap
# multi-tenant sub-budgets (core/tenancy.py, ISSUE 15): every tenant shed
# names the tenant so an over-quota rejection is attributable end to end
REASON_TENANT_PAUSED = "tenant-paused"   # weight 0 / admin pause
REASON_TENANT_RATE = "tenant-rate"       # per-tenant token bucket empty
REASON_TENANT_LEVEL = "tenant-level"     # over-quota: shed one rung early
REASON_TENANT_SHARE = "tenant-share"     # weighted fair share exceeded
REASON_DRAINING = "draining"    # graceful shutdown: only critical admitted


class Shed(Exception):
    """A well-formed rejection: carries the class, the reason, how long
    the caller should back off, and (for tenant-attributed sheds) the
    tenant label.  The transports translate this into HTTP 429 +
    `Retry-After` or gRPC `RESOURCE_EXHAUSTED` + a `retry-after` trailer
    (+ a `tenant` trailer / JSON field when the shed was tenant-scoped)."""

    def __init__(self, cls: str, reason: str, retry_after: float,
                 tenant: Optional[str] = None):
        self.cls = cls
        self.reason = reason
        self.retry_after = max(0.0, retry_after)
        self.tenant = tenant
        label = f" [tenant={tenant}]" if tenant else ""
        super().__init__(
            f"{cls} request shed ({reason}){label}; retry after "
            f"{self.retry_after:g}s")


class Ticket:
    """One admitted request.  Release exactly once (context manager or
    explicit `release()`); normal-class streams additionally call
    `pace(n)` per streamed chunk for the fair-share token bucket."""

    __slots__ = ("controller", "cls", "peer", "stream", "tenant",
                 "_released", "_sent", "_next_ok")

    def __init__(self, controller: "AdmissionController", cls: str,
                 peer: Optional[str], stream: bool,
                 tenant: Optional[str] = None):
        self.controller = controller
        self.cls = cls
        self.peer = peer
        self.stream = stream
        self.tenant = tenant
        self._released = False
        self._sent = 0
        self._next_ok = 0.0

    def __enter__(self) -> "Ticket":
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def release(self) -> None:
        self.controller._release(self)

    def pace(self, n: int = 1) -> float:
        """Fair-share pacing for streams: past the burst allowance, each
        item costs 1/rate seconds where rate is the shared pace budget
        divided by the number of active normal streams.  Uncontended (one
        active stream) pacing is off — a lone catch-up peer gets the full
        pipe.  Returns the seconds this call waited (fake seconds under
        an injected test clock)."""
        return self.controller._pace(self, n)


class AdmissionController:
    """The shared serving-plane admission controller (see module doc).

    All state lives under one condition variable; waits are cv-slices
    bounded in REAL time (the verify-service pattern) so a frozen
    FakeClock can never wedge a serving thread, while measured waits read
    the injected clock so tests are deterministic."""

    # real-seconds ceiling on any single admission/pace wait: the fake
    # deadline may never arrive on a frozen test clock
    WAIT_REAL_CAP = 2.0

    def __init__(self, clock=None, capacity: int = 0,
                 critical_reserve: int = 0,
                 max_streams_per_peer: int = 0,
                 shed_wait: float = 0.0, recover_wait: float = 0.0,
                 dwell: float = 0.0, normal_wait: float = 0.0,
                 pace_rate: float = 0.0, pace_burst: int = 0,
                 retry_after: float = 0.0,
                 background_hook: Optional[Callable[[bool], None]] = None,
                 tenancy=None, authority=None):
        if clock is None:
            # deferred import: net must not hard-depend on beacon at
            # module scope (same softening as net/resilience.py)
            from ..beacon.clock import RealClock
            clock = RealClock()
        self.clock = clock
        self.capacity = capacity or DEFAULT_CAPACITY
        self.critical_reserve = min(
            critical_reserve or DEFAULT_CRITICAL_RESERVE, self.capacity - 1)
        self.max_streams_per_peer = (max_streams_per_peer
                                     or DEFAULT_MAX_STREAMS_PER_PEER)
        self.shed_wait = shed_wait or DEFAULT_SHED_WAIT
        self.recover_wait = recover_wait or DEFAULT_RECOVER_WAIT
        self.dwell = dwell or DEFAULT_DWELL
        self.normal_wait = normal_wait or DEFAULT_NORMAL_WAIT
        self.pace_rate = pace_rate or DEFAULT_PACE_RATE
        self.pace_burst = pace_burst or DEFAULT_PACE_BURST
        self.retry_after_s = retry_after or DEFAULT_RETRY_AFTER
        self.background_hook = background_hook
        # core/tenancy.py TenantRegistry (duck-typed: admission_view /
        # weights / note_decision / resolve_metadata) — None keeps every
        # pre-tenancy call site byte-identical in behavior
        self.tenancy = tenancy
        # core/authz.py TokenAuthority (duck-typed: active / verify) —
        # None (or an authority that never minted) keeps the anonymous
        # chain-name attribution path untouched (ISSUE 19)
        self.authority = authority
        self._cond = make_condition()
        self._inflight: Dict[str, int] = {c: 0 for c in CLASSES}
        self._peer_streams: Dict[str, int] = {}
        self._normal_streams = 0
        # (clock.monotonic() stamp, class, measured wait) rolling window
        self._waits: deque = deque(maxlen=1024)
        self._window = max(4 * self.dwell, 20.0)
        self._level = LEVEL_NOMINAL
        self._level_changed_at = self.clock.monotonic()
        self._transitions: List[Tuple[float, int]] = []
        self._admitted: Dict[str, int] = {c: 0 for c in CLASSES}
        self._shed: Dict[Tuple[str, str], int] = {}
        self._shed_log: List[Tuple[float, str, str]] = []
        self._paced_waits = 0
        # per-tenant sub-budget state: NONCRITICAL tokens each tenant
        # currently holds (the WFQ share check) and the per-tenant rate
        # buckets ([tokens, last-refill stamp], injected clock)
        self._tenant_inflight: Dict[str, int] = {}
        self._tenant_buckets: Dict[str, List[float]] = {}
        # graceful-shutdown drain gate: once set, sheddable/normal shed
        # immediately (REASON_DRAINING) while critical keeps flowing so
        # in-flight partials can finish before the process exits
        self._draining = False

    # -- admission ------------------------------------------------------------

    def admit(self, cls: str, peer: Optional[str] = None,
              stream: bool = False, tenant: Optional[str] = None) -> Ticket:
        """Admit or raise `Shed`.  Critical never sheds (the reserve
        guarantees it a token; even a reserve misconfigured to zero only
        costs accounting, never the partial).  Normal waits up to
        `normal_wait` for a token; sheddable never waits.

        `tenant` (with a registry installed) applies the per-tenant
        sub-budgets inside the class: the paused gate, the rate bucket,
        the over-quota one-rung-early level bump, and the weighted fair
        share on token contention.  `tenant=None` (or no registry) is
        byte-identical to the pre-tenancy behavior."""
        if cls not in self._inflight:
            raise ValueError(f"unknown admission class {cls!r}")
        from ..metrics import (admission_inflight, admission_requests,
                               admission_wait_seconds)
        # resolve the tenant OUTSIDE self._cond (registry holds its own
        # lock; keep the order controller-after-registry impossible).
        # `has_tenants` is a lock-free bool: an empty registry (the
        # single-operator common case) costs zero registry round trips
        # per request
        view = weights = None
        if self.tenancy is not None and tenant is not None \
                and getattr(self.tenancy, "has_tenants", lambda: True)():
            view = self.tenancy.admission_view(tenant)
            weights = self.tenancy.weights()
        now0 = self.clock.monotonic()
        hook = None
        try:
            with self._cond:
                hook = self._reassess_locked(now0)
                if self._draining and cls != CLASS_CRITICAL:
                    self._note_shed_locked(cls, REASON_DRAINING, now0)
                    raise Shed(cls, REASON_DRAINING, self.retry_after_s,
                               tenant=view.name if view else None)
                self._check_tenant_locked(cls, view, now0)
                self._check_level_locked(cls, now0, view=view)
                if cls == CLASS_NORMAL and stream and peer is not None \
                        and self._peer_streams.get(peer, 0) \
                        >= self.max_streams_per_peer:
                    self._note_shed_locked(cls, REASON_PEER_CAP, now0)
                    raise Shed(cls, REASON_PEER_CAP, self.retry_after_s,
                               tenant=view.name if view else None)
                waited = self._acquire_locked(cls, now0, view=view,
                                              weights=weights)
                self._waits.append((self.clock.monotonic(), cls, waited))
                self._inflight[cls] += 1
                self._admitted[cls] += 1
                if view is not None and cls != CLASS_CRITICAL:
                    self._tenant_inflight[view.name] = \
                        self._tenant_inflight.get(view.name, 0) + 1
                if cls == CLASS_NORMAL and stream:
                    self._normal_streams += 1
                    if peer is not None:
                        self._peer_streams[peer] = \
                            self._peer_streams.get(peer, 0) + 1
                hook = self._reassess_locked(self.clock.monotonic()) or hook
        except Shed:
            if view is not None:
                self._note_tenant(view.name, False)
            raise
        finally:
            self._run_hook(hook)
        if view is not None:
            self._note_tenant(view.name, True)
        admission_requests.labels(cls, "admitted").inc()
        admission_wait_seconds.labels(cls).observe(max(0.0, waited))
        admission_inflight.labels(cls).set(self._inflight[cls])
        t = Ticket(self, cls, peer, stream,
                   tenant=view.name if view is not None else None)
        t._next_ok = self.clock.monotonic()
        return t

    def try_admit(self, cls: str, peer: Optional[str] = None,
                  stream: bool = False,
                  tenant: Optional[str] = None) -> Tuple[Optional[Ticket],
                                                         Optional[Shed]]:
        """Non-raising admit for transports that translate the rejection
        themselves (the REST edge's pre-parse shed path)."""
        try:
            return self.admit(cls, peer=peer, stream=stream,
                              tenant=tenant), None
        except Shed as s:
            return None, s

    def attribute(self, ticket: Ticket, tenant: Optional[str]) -> None:
        """Late tenant attribution for tickets admitted BEFORE the
        tenant was knowable — the REST edge admits pre-parse (the cheap
        429 path cannot see the chain-hash segment), so its tokens used
        to be invisible to weighted fair queuing: a REST flood held the
        pool under tenant=None and the share check never engaged.  Once
        the route resolves the chain, the edge attributes the held
        ticket here; `release` already decrements the ledger.  No-op for
        critical, already-attributed, or released tickets, and on
        daemons with no tenants registered."""
        if tenant is None or self.tenancy is None \
                or not getattr(self.tenancy, "has_tenants",
                               lambda: True)():
            return
        with self._cond:
            if ticket._released or ticket.tenant is not None \
                    or ticket.cls == CLASS_CRITICAL:
                return
            ticket.tenant = tenant
            self._tenant_inflight[tenant] = \
                self._tenant_inflight.get(tenant, 0) + 1

    def _note_tenant(self, tenant: str, admitted: bool) -> None:
        """Forward the decision to the registry's per-tenant counters +
        tenant_requests_total (outside self._cond)."""
        try:
            self.tenancy.note_decision(tenant, admitted)
        except Exception:
            pass        # accounting must never cost the request

    # -- per-tenant sub-budgets (core/tenancy.py, ISSUE 15) -------------------

    def _check_tenant_locked(self, cls: str, view, now: float) -> None:
        """The tenant gates that run BEFORE any token work: admin pause
        (weight 0) sheds everything non-critical without touching a
        token, and the per-tenant rate bucket bounds sheddable reads.
        Critical is exempt by construction — a tenant's quota can slow
        its readers, never its chain's liveness.  Caller holds the
        lock."""
        if view is None or cls == CLASS_CRITICAL:
            return
        if view.paused:
            self._note_shed_locked(cls, REASON_TENANT_PAUSED, now)
            raise Shed(cls, REASON_TENANT_PAUSED, self.retry_after_s,
                       tenant=view.name)
        if cls == CLASS_SHEDDABLE and view.rate > 0 \
                and not self._tenant_bucket_ok_locked(view, now):
            self._note_shed_locked(cls, REASON_TENANT_RATE, now)
            raise Shed(cls, REASON_TENANT_RATE, self.retry_after_s,
                       tenant=view.name)

    def _tenant_bucket_ok_locked(self, view, now: float) -> bool:
        """Per-tenant token bucket (rate/burst from the registry entry);
        refilled on the injected clock.  Caller holds the lock."""
        cap = float(view.burst) if view.burst else max(1.0, view.rate)
        b = self._tenant_buckets.get(view.name)
        if b is None:
            b = self._tenant_buckets[view.name] = [cap, now]
        tokens = min(cap, b[0] + max(0.0, now - b[1]) * view.rate)
        if tokens >= 1.0:
            b[0], b[1] = tokens - 1.0, now
            return True
        b[0], b[1] = tokens, now
        return False

    def _tenant_over_share_locked(self, view, weights) -> bool:
        """Weighted fair queuing inside the class: under token
        contention a REGISTERED tenant already holding at least its
        weight-proportional share of the noncritical pool is shed
        instead of waiting (or camping), so compliant tenants' requests
        find the tokens the hog would otherwise absorb.  Every tenant
        keeps a floor of one token.  The implicit default tenant (every
        request on a daemon with no registry entry for its chain) is
        exempt — its "share" would be the whole pool, and shedding it at
        capacity would replace the pre-tenancy wait behavior (and the
        timed-out-wait ladder signal) on single-operator daemons.
        Caller holds the lock."""
        if view is None or not view.known:
            return False
        held = self._tenant_inflight.get(view.name, 0)
        if held == 0:
            return False        # the one-token floor
        limit = self.capacity - self.critical_reserve
        weights = weights or {}
        active = set(self._tenant_inflight) | {view.name}
        total = sum(weights.get(t, 1.0) for t in active) or 1.0
        mine = weights.get(view.name, view.weight or 1.0)
        share = max(1, int(limit * mine / total))
        return held >= share

    def _check_level_locked(self, cls: str, now: float,
                            view=None) -> None:
        """The degradation-ladder gate.  An over-quota tenant (device
        budget spent, core/tenancy.py quota level >= 1) is judged one
        rung HIGHER than the ladder's actual level — over-quota tenants
        shed strictly before compliant ones on every rung."""
        bump = 1 if view is not None and view.over_quota else 0
        level = self._level + bump
        tenant = view.name if view is not None else None
        if cls == CLASS_SHEDDABLE and level >= LEVEL_SHED_PUBLIC:
            reason = REASON_LEVEL if self._level >= LEVEL_SHED_PUBLIC \
                else REASON_TENANT_LEVEL
            self._note_shed_locked(cls, reason, now)
            raise Shed(cls, reason, self._retry_after_locked(now),
                       tenant=tenant)
        if cls == CLASS_NORMAL and level >= LEVEL_SHED_NORMAL:
            reason = REASON_LEVEL if self._level >= LEVEL_SHED_NORMAL \
                else REASON_TENANT_LEVEL
            self._note_shed_locked(cls, reason, now)
            raise Shed(cls, reason, self._retry_after_locked(now),
                       tenant=tenant)

    def _acquire_locked(self, cls: str, now0: float, view=None,
                        weights=None) -> float:
        """Take a token; returns the measured wait (injected-clock
        seconds).  Caller holds the lock."""
        from time import perf_counter
        if cls == CLASS_CRITICAL:
            return 0.0      # the reserve guarantees critical a slot
        limit = self.capacity - self.critical_reserve
        real0 = perf_counter()
        while True:
            noncrit = (self._inflight[CLASS_NORMAL]
                       + self._inflight[CLASS_SHEDDABLE])
            if noncrit < limit:
                return self.clock.monotonic() - now0
            now = self.clock.monotonic()
            waited = now - now0
            if self._tenant_over_share_locked(view, weights):
                # WFQ: the pool is contended and this tenant already
                # holds its weighted share — shed instead of competing
                # for the tokens compliant tenants are waiting on
                self._note_shed_locked(cls, REASON_TENANT_SHARE, now)
                raise Shed(cls, REASON_TENANT_SHARE, self.retry_after_s,
                           tenant=view.name)
            if cls == CLASS_SHEDDABLE:
                # shed immediately and cheaply — public reads retry at
                # the edge, they never queue inside the daemon
                self._note_shed_locked(cls, REASON_CAPACITY, now)
                raise Shed(cls, REASON_CAPACITY, self.retry_after_s,
                           tenant=view.name if view else None)
            if waited >= self.normal_wait \
                    or perf_counter() - real0 >= self.WAIT_REAL_CAP:
                # the timed-out wait IS the overload signal: record it so
                # the p99 crosses the shed threshold and the ladder climbs.
                # tpu-vet: disable=lock  (caller holds self._cond, docstring)
                self._waits.append((now, cls, max(waited, self.normal_wait)))
                self._note_shed_locked(cls, REASON_CAPACITY, now)
                raise Shed(cls, REASON_CAPACITY, self.retry_after_s,
                           tenant=view.name if view else None)
            self._check_level_locked(cls, now, view=view)
            # cv-slice bounded in real time; released tokens notify
            self._cond.wait(0.05)

    # -- graceful drain (SIGTERM path) ----------------------------------------

    def begin_drain(self) -> None:
        """Flip the drain gate: from now on sheddable and normal admits
        shed immediately with REASON_DRAINING; critical keeps being
        admitted so in-flight protocol work (partials) can finish.
        Idempotent."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()

    def is_draining(self) -> bool:
        with self._cond:
            return self._draining

    def drained(self, timeout: float) -> bool:
        """Block until no critical request is in flight, or `timeout`
        REAL seconds elapse (condvar waits are wall-clock; a fake clock
        cannot hang this).  Returns True when the critical lane is dry —
        the caller (graceful_stop) may then tear the services down."""
        slices = max(1, int(timeout / 0.05))
        with self._cond:
            for _ in range(slices):
                if self._inflight[CLASS_CRITICAL] == 0:
                    return True
                self._cond.wait(0.05)
            return self._inflight[CLASS_CRITICAL] == 0

    def _release(self, ticket: Ticket) -> None:
        from ..metrics import admission_inflight
        hook = None
        with self._cond:
            if ticket._released:
                return
            ticket._released = True
            self._inflight[ticket.cls] = max(
                0, self._inflight[ticket.cls] - 1)
            if ticket.tenant is not None and ticket.cls != CLASS_CRITICAL:
                left = self._tenant_inflight.get(ticket.tenant, 1) - 1
                if left <= 0:
                    self._tenant_inflight.pop(ticket.tenant, None)
                else:
                    self._tenant_inflight[ticket.tenant] = left
            if ticket.cls == CLASS_NORMAL and ticket.stream:
                self._normal_streams = max(0, self._normal_streams - 1)
                if ticket.peer is not None:
                    left = self._peer_streams.get(ticket.peer, 1) - 1
                    if left <= 0:
                        self._peer_streams.pop(ticket.peer, None)
                    else:
                        self._peer_streams[ticket.peer] = left
            hook = self._reassess_locked(self.clock.monotonic())
            self._cond.notify_all()
        self._run_hook(hook)
        admission_inflight.labels(ticket.cls).set(self._inflight[ticket.cls])

    def _note_shed_locked(self, cls: str, reason: str, now: float) -> None:
        from ..metrics import admission_requests
        self._shed[(cls, reason)] = self._shed.get((cls, reason), 0) + 1
        self._shed_log.append((now, cls, reason))
        if len(self._shed_log) > 4096:
            del self._shed_log[:2048]
        admission_requests.labels(cls, "shed").inc()

    def _retry_after_locked(self, now: float) -> float:
        """Level-based sheds back callers off until the ladder could next
        step down (the remaining dwell), floored at the static knob."""
        remaining = self.dwell - (now - self._level_changed_at)
        return max(self.retry_after_s, min(remaining, self.dwell))

    # -- the degradation ladder ----------------------------------------------

    def _p99_locked(self, now: float, cls: Optional[str] = None) -> float:
        """p99 of the wait samples inside the window, optionally filtered
        to one class.  Caller holds the lock."""
        cutoff = now - self._window
        recent = sorted(w for t, c, w in self._waits
                        if t >= cutoff and (cls is None or c == cls))
        if not recent:
            return 0.0
        return recent[min(len(recent) - 1,
                          int(round(0.99 * (len(recent) - 1))))]

    def _reassess_locked(self, now: float) -> Optional[Callable]:
        """One ladder step per dwell, driven by the queue-wait p99.
        Returns the background hook invocation to run OUTSIDE the lock
        (the verify service takes its own lock), or None."""
        if now - self._level_changed_at < self.dwell:
            return None
        p99 = self._p99_locked(now)
        new = self._level
        if p99 > self.shed_wait and self._level < LEVEL_SHED_NORMAL:
            new = self._level + 1
        elif p99 < self.recover_wait and self._level > LEVEL_NOMINAL:
            new = self._level - 1
        if new == self._level:
            return None
        crossed_bg = (self._level < LEVEL_PAUSE_BACKGROUND <= new) \
            or (new < LEVEL_PAUSE_BACKGROUND <= self._level)
        self._level = new
        self._level_changed_at = now
        self._transitions.append((now, new))
        from ..metrics import admission_level
        admission_level.set(new)
        if crossed_bg and self.background_hook is not None:
            paused = new >= LEVEL_PAUSE_BACKGROUND
            from ..metrics import admission_background_paused
            admission_background_paused.set(1 if paused else 0)
            hook = self.background_hook
            return lambda: hook(paused)
        return None

    @staticmethod
    def _run_hook(hook: Optional[Callable]) -> None:
        if hook is not None:
            hook()

    # -- stream pacing --------------------------------------------------------

    def _pace(self, ticket: Ticket, n: int) -> float:
        from time import perf_counter
        with self._cond:
            streams = max(1, self._normal_streams)
            if streams < 2:
                # uncontended: a lone catch-up peer gets the full pipe,
                # and the bucket forgives its history so contention later
                # starts from the burst allowance, not from debt
                ticket._sent = 0
                ticket._next_ok = self.clock.monotonic()
                return 0.0
            rate = max(1.0, self.pace_rate / streams)
            ticket._sent += n
            if ticket._sent <= self.pace_burst:
                ticket._next_ok = self.clock.monotonic()
                return 0.0
            ticket._next_ok = max(ticket._next_ok,
                                  self.clock.monotonic()) + n / rate
            until = ticket._next_ok
            self._paced_waits += 1
        t0 = self.clock.monotonic()
        real0 = perf_counter()
        with self._cond:
            while self.clock.monotonic() < until \
                    and perf_counter() - real0 < self.WAIT_REAL_CAP:
                # real-bounded cv-slice: a frozen FakeClock must not wedge
                # a serving stream (the REAL_FLUSH_CAP discipline)
                self._cond.wait(0.02)
        return max(0.0, self.clock.monotonic() - t0)

    # -- observability --------------------------------------------------------

    def level(self) -> int:
        hook = None
        try:
            with self._cond:
                hook = self._reassess_locked(self.clock.monotonic())
                return self._level
        finally:
            self._run_hook(hook)

    def background_paused(self) -> bool:
        """True while the ladder says background work must yield —
        scheduled integrity scans consult this and DEFER (the work waits;
        it is never dropped)."""
        return self.level() >= LEVEL_PAUSE_BACKGROUND

    def check_tenant_read(self, tenant: Optional[str]) -> Optional[Shed]:
        """Post-parse tenant gate for the REST edge: the pre-parse shed
        path cannot see the chain-hash path segment, so the tenant rules
        (pause, rate bucket, over-quota early rung) run here once the
        chain — and therefore the tenant — is known.  No concurrency
        token changes hands (the caller already holds its pre-parse
        ticket); returns the Shed instead of raising so the edge can
        serialize it into a labelled 429."""
        if self.tenancy is None or tenant is None \
                or not getattr(self.tenancy, "has_tenants",
                               lambda: True)():
            return None
        view = self.tenancy.admission_view(tenant)
        weights = self.tenancy.weights()
        now = self.clock.monotonic()
        shed = None
        with self._cond:
            try:
                self._check_tenant_locked(CLASS_SHEDDABLE, view, now)
                self._check_level_locked(CLASS_SHEDDABLE, now, view=view)
                # WFQ for the REST plane: with the noncritical pool
                # contended, a tenant already holding its weighted share
                # (REST tickets count — the edge attributes them before
                # this gate) sheds here like a gRPC admit would
                limit = self.capacity - self.critical_reserve
                noncrit = (self._inflight[CLASS_NORMAL]
                           + self._inflight[CLASS_SHEDDABLE])
                if noncrit >= limit \
                        and self._tenant_over_share_locked(view, weights):
                    self._note_shed_locked(CLASS_SHEDDABLE,
                                           REASON_TENANT_SHARE, now)
                    raise Shed(CLASS_SHEDDABLE, REASON_TENANT_SHARE,
                               self.retry_after_s, tenant=view.name)
            except Shed as s:
                shed = s
        self._note_tenant(view.name, shed is None)
        return shed

    def wait_p99(self, cls: Optional[str] = None) -> float:
        with self._cond:
            return self._p99_locked(self.clock.monotonic(), cls)

    def snapshot(self) -> dict:
        lvl = self.level()      # reassess first
        with self._cond:
            return {
                "level": lvl,
                "level_name": LEVEL_NAMES[lvl],
                "draining": self._draining,
                "inflight": dict(self._inflight),
                "admitted": dict(self._admitted),
                "shed": {f"{c}/{r}": v
                         for (c, r), v in sorted(self._shed.items())},
                "peer_streams": dict(self._peer_streams),
                "tenant_inflight": dict(self._tenant_inflight),
                "paced_waits": self._paced_waits,
                "wait_p99": {c: round(self._p99_locked(
                    self.clock.monotonic(), c), 4) for c in CLASSES},
                "transitions": list(self._transitions),
            }

    def summary(self) -> str:
        """One line for /health."""
        s = self.snapshot()
        i = s["inflight"]
        shed = sum(v for v in self._shed.values())
        return (f"level={s['level_name']} "
                f"inflight={i[CLASS_CRITICAL]}/{i[CLASS_NORMAL]}/"
                f"{i[CLASS_SHEDDABLE]} shed={shed} "
                f"p99={s['wait_p99'][CLASS_NORMAL]:.3f}s")


# -- gRPC wiring ---------------------------------------------------------------


def peer_identity(peer: str) -> str:
    """Fair-share identity for a gRPC peer string: strip the ephemeral
    client port ('ipv4:10.0.0.1:52644' -> 'ipv4:10.0.0.1',
    'ipv6:[::1]:52644' -> 'ipv6:[::1]') so the per-peer stream cap is
    per REMOTE HOST — a hog must not evade `max_streams_per_peer` by
    opening one channel per stream.  Strings without a port component
    (test names, REST client addresses) pass through unchanged."""
    if peer.count(":") >= 2:
        host = peer.rsplit(":", 1)[0]
        # ipv6 literals keep their bracketed form; a bare 'ipv6:[::1]'
        # (no port) must not lose its tail
        if not (peer.startswith("ipv6:") and not host.endswith("]")):
            return host
    return peer


def classify_method(method: str) -> Optional[str]:
    """Wire-path -> admission class.  SyncChain is the one normal-class
    stream; the rest of the node-to-node Protocol plane (partials, DKG,
    identity, status) is critical; the Public API is sheddable.  Control
    (localhost CLI) and anything unknown are exempt (None)."""
    if method == "/drand.Protocol/SyncChain":
        return CLASS_NORMAL
    if method.startswith("/drand.Protocol/"):
        return CLASS_CRITICAL
    if method.startswith("/drand.Public/"):
        return CLASS_SHEDDABLE
    return None


def _shed_abort(context, shed: Shed):
    import grpc
    trailers = [("retry-after", f"{shed.retry_after:g}")]
    if shed.tenant:
        # over-quota rejections carry the tenant label end to end: a
        # multi-tenant client (or its operator) must be able to tell
        # "your quota" from "the daemon is overloaded"
        trailers.append(("tenant", shed.tenant))
    context.set_trailing_metadata(tuple(trailers))
    context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(shed))


def _identity_abort(context, verdict):
    """Reject a bad bearer token BEFORE admission — no quota is spent,
    nothing is attributed to the tenant the token claims, and the
    rejection carries an identity-labelled trailer + metric so theft is
    observable (the StolenIdentityScenario asserts on both)."""
    import grpc
    from ..metrics import identity_rejections
    identity_rejections.labels("grpc", verdict.reason).inc()
    trailers = [("identity-reason", verdict.reason)]
    if verdict.token_id:
        trailers.append(("token-id", verdict.token_id))
    context.set_trailing_metadata(tuple(trailers))
    context.abort(grpc.StatusCode.UNAUTHENTICATED,
                  f"token rejected: {verdict.reason}")


class AdmissionInterceptor:
    """grpc.ServerInterceptor applying the controller to every RPC of a
    listener.  Unary handlers admit/release around the behavior; stream
    handlers hold their ticket for the stream's life and pace each
    response item (the SyncChain fair-share path).  Rejections abort with
    RESOURCE_EXHAUSTED and a `retry-after` trailer before any service
    logic runs."""

    def __init__(self, controller: AdmissionController,
                 classify: Callable[[str], Optional[str]] = classify_method):
        self.controller = controller
        self.classify = classify

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        if handler is None:
            return None
        cls = self.classify(handler_call_details.method)
        if cls is None:
            return handler
        return self._wrap(handler, cls)

    def _wrap(self, handler, cls: str):
        import grpc
        ctrl = self.controller

        def tenant_of(request) -> Optional[str]:
            # the tenant is named by the chain the request addresses —
            # beaconID (or chain hash) in the standard drand metadata;
            # resolution is one dict lookup in the registry
            tenancy = ctrl.tenancy
            if tenancy is None:
                return None
            try:
                return tenancy.resolve_metadata(
                    getattr(request, "metadata", None))
            except Exception:
                return None

        def tenant_for(request, context) -> Optional[str]:
            """Authenticated tenant attribution (core/authz.py): a
            presented bearer token names the tenant directly — verified
            BEFORE any quota spend, with the chain caveat checked against
            the chain the request addresses.  A bad token aborts
            UNAUTHENTICATED here (never reaching `admit`, so nothing is
            attributed to the claimed tenant); no token at all keeps the
            anonymous chain-name path byte-identical."""
            authority = ctrl.authority
            if authority is not None and authority.active():
                from ..core.authz import REASON_READ_ONLY, TokenVerdict, \
                    grpc_bearer
                token = grpc_bearer(context.invocation_metadata())
                if token is not None:
                    meta = getattr(request, "metadata", None)
                    chain = getattr(meta, "beaconID", "") or None
                    verdict = authority.verify(token, chain=chain)
                    if verdict.ok and verdict.read_only \
                            and cls == CLASS_CRITICAL:
                        # a read-only token must not reach the write-ish
                        # node-to-node plane
                        verdict = TokenVerdict(
                            False, verdict.tenant, REASON_READ_ONLY,
                            token_id=verdict.token_id)
                    if not verdict.ok:
                        _identity_abort(context, verdict)
                    return verdict.tenant
            return tenant_of(request)

        if handler.unary_unary is not None:
            inner = handler.unary_unary

            def unary(request, context):
                try:
                    ticket = ctrl.admit(cls, peer=peer_identity(
                        context.peer()), tenant=tenant_for(request, context))
                except Shed as s:
                    _shed_abort(context, s)
                with ticket:
                    return inner(request, context)

            return grpc.unary_unary_rpc_method_handler(
                unary, request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer)

        if handler.unary_stream is not None:
            inner_s = handler.unary_stream

            def stream(request, context):
                try:
                    ticket = ctrl.admit(cls, peer=peer_identity(
                        context.peer()), stream=True,
                        tenant=tenant_for(request, context))
                except Shed as s:
                    _shed_abort(context, s)

                def gen():
                    with ticket:
                        for item in inner_s(request, context):
                            yield item
                            ticket.pace()

                return gen()

            return grpc.unary_stream_rpc_method_handler(
                stream, request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer)

        return handler      # client-streaming RPCs: none in our specs
