"""Minimal gRPC service framework built from message classes.

No grpc protoc plugin ships in this environment, so instead of generated
`*_pb2_grpc.py` stubs each service is declared once as a `ServiceSpec`
(method name, request/response message class, streaming flag) and both
sides are derived from it:

  * `spec.handler(impl)`  -> a `grpc.GenericRpcHandler` for a server; the
    impl object provides one method per RPC, `snake_case(name)(request,
    context)`.
  * `spec.stub(channel)`  -> a client stub exposing the same snake_case
    callables over a `grpc.Channel`.

Wire paths are `/<package.Service>/<Method>` exactly as generated code
would produce, so nodes built on this framework speak standard gRPC
(reference surface: protobuf/drand/protocol.proto:17-37, api.proto:16-28,
control.proto:15-56).
"""

import re
from dataclasses import dataclass
from typing import Sequence

import grpc


def snake(name: str) -> str:
    """CamelCase -> snake_case, acronym-aware: SignalDKGParticipant ->
    signal_dkg_participant, ListBeaconIDs -> list_beacon_ids (a plural 's'
    right after an acronym stays attached to it)."""
    s = re.sub(r"(?<=[a-z0-9])([A-Z])", r"_\1", name)
    return re.sub(r"(?<=[A-Z])([A-Z](?!s\b)[a-z])", r"_\1", s).lower()


@dataclass(frozen=True)
class Method:
    name: str                 # wire method name (CamelCase)
    request: type             # protobuf message class
    response: type            # protobuf message class
    server_stream: bool = False


class ServiceSpec:
    def __init__(self, full_name: str, methods: Sequence[Method]):
        self.full_name = full_name
        self.methods = {m.name: m for m in methods}

    # -- server side ---------------------------------------------------------

    def handler(self, impl) -> grpc.GenericRpcHandler:
        handlers = {}
        for m in self.methods.values():
            fn = getattr(impl, snake(m.name))
            if m.server_stream:
                handlers[m.name] = grpc.unary_stream_rpc_method_handler(
                    fn, request_deserializer=m.request.FromString,
                    response_serializer=m.response.SerializeToString)
            else:
                handlers[m.name] = grpc.unary_unary_rpc_method_handler(
                    fn, request_deserializer=m.request.FromString,
                    response_serializer=m.response.SerializeToString)
        return grpc.method_handlers_generic_handler(self.full_name, handlers)

    # -- client side ---------------------------------------------------------

    def stub(self, channel: grpc.Channel, default_timeout: float = None):
        """Client stub; `default_timeout` (seconds) applies to every call
        that doesn't pass its own `timeout=`.  Streaming calls are exempt
        (a sync/watch stream is legitimately long-lived)."""
        return _Stub(self, channel, default_timeout)


class _Stub:
    def __init__(self, spec: ServiceSpec, channel: grpc.Channel,
                 default_timeout: float = None):
        for m in spec.methods.values():
            path = f"/{spec.full_name}/{m.name}"
            if m.server_stream:
                call = channel.unary_stream(
                    path, request_serializer=m.request.SerializeToString,
                    response_deserializer=m.response.FromString)
            else:
                call = channel.unary_unary(
                    path, request_serializer=m.request.SerializeToString,
                    response_deserializer=m.response.FromString)
                if default_timeout is not None:
                    call = _with_default_timeout(call, default_timeout)
            setattr(self, snake(m.name), call)


def _with_default_timeout(call, default):
    def wrapped(request, timeout=default, **kw):
        return call(request, timeout=timeout, **kw)
    return wrapped


def abort_invalid(context, msg: str):
    context.abort(grpc.StatusCode.INVALID_ARGUMENT, msg)


def abort_not_found(context, msg: str):
    context.abort(grpc.StatusCode.NOT_FOUND, msg)
