"""Userspace TCP chaos proxy: per-link network faults with no root.

The process-fleet harness (tests/fleet.py) routes every inter-node gRPC
connection through one `ChaosLink` — a tiny TCP forwarder owned by the
supervisor process — so link-level faults (partition, delay, throttle,
mid-stream reset) are injected in userspace, which works inside CI
containers where iptables/tc are unavailable.  The daemons themselves are
untouched: they dial the proxy address instead of the real peer via the
`DRAND_DIAL_MAP` indirection in net/client.py.

Topology: one link per ORDERED pair (dialer, target).  A 2|3 partition
is "drop every link crossing the cut, both directions, and reset the
streams already up"; a heal clears the drop and gRPC's own reconnect does
the rest.  Faults are plain attributes toggled by the supervisor thread;
the pump threads read them per chunk, so a fault takes effect mid-stream
without tearing the proxy down.

Everything here is wall-clock by design (it shapes real wire traffic for
real subprocesses; an injected fake clock cannot reach across process
boundaries), and every blocking socket op runs under a short settimeout
so a wedged link can never hang the harness teardown — the fleet run must
die in minutes, not hang CI.
"""

import socket
import threading
import time
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Tuple

# pump granularity: small enough that a fault lands within one chunk's
# forwarding latency, big enough not to syscall-thrash a sync stream
_CHUNK = 16384
_POLL = 0.25        # accept/recv timeout slice; stop latency ceiling


@dataclass
class LinkFault:
    """The live fault state of one directed link; mutated in place by the
    supervisor, read per-chunk by the pumps."""
    drop: bool = False          # partition: refuse new conns, starve pumps
    delay: float = 0.0          # added latency per forwarded chunk (s)
    rate: float = 0.0           # throttle, bytes/s (0 = unlimited)


@dataclass
class LinkStats:
    accepted: int = 0
    refused: int = 0            # connections closed at accept (drop mode)
    resets: int = 0             # streams hard-reset mid-flight
    bytes_forward: int = 0      # dialer -> target
    bytes_backward: int = 0     # target -> dialer


class ChaosLink:
    """One directed proxied link: listens on an ephemeral localhost port,
    forwards byte streams to `upstream`, applying the current `fault`."""

    def __init__(self, upstream: str, name: str = "link",
                 host: str = "127.0.0.1"):
        self.upstream = upstream
        self.name = name
        self.fault = LinkFault()
        self.stats = LinkStats()
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._conns: List[socket.socket] = []
        self._pumps: List[threading.Thread] = []
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, 0))
        self._listener.listen(32)
        self._listener.settimeout(_POLL)
        self.address = "%s:%d" % self._listener.getsockname()[:2]
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name=f"chaos-accept-{name}")
        self._thread.start()

    # -- fault control (supervisor thread) -----------------------------------

    def set_fault(self, drop: Optional[bool] = None,
                  delay: Optional[float] = None,
                  rate: Optional[float] = None) -> None:
        if drop is not None:
            self.fault.drop = drop
        if delay is not None:
            self.fault.delay = delay
        if rate is not None:
            self.fault.rate = rate

    def heal(self) -> None:
        self.fault = LinkFault()

    def reset_streams(self) -> None:
        """Hard-reset every live stream on this link: SO_LINGER(1, 0) turns
        close() into an RST, so the peer sees a mid-stream connection reset
        rather than a clean FIN."""
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                             b"\x01\x00\x00\x00\x00\x00\x00\x00")
                c.close()
                self.stats.resets += 1
            except OSError:
                pass

    def drop_and_reset(self) -> None:
        """Partition this link: refuse new connections AND kill the ones
        already up (a drop alone would let an established gRPC stream keep
        flowing through the cut)."""
        self.set_fault(drop=True)
        self.reset_streams()

    # -- forwarding ----------------------------------------------------------

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return          # listener closed under us: stopping
            if self.fault.drop:
                # partition: complete the TCP handshake (the listener's
                # backlog already did) but reset immediately — the dialer
                # sees UNAVAILABLE and gRPC backs off and retries
                self.stats.refused += 1
                self._abort(conn)
                continue
            try:
                up = socket.create_connection(
                    _split(self.upstream), timeout=2.0)
            except OSError:
                self.stats.refused += 1
                self._abort(conn)
                continue
            self.stats.accepted += 1
            conn.settimeout(_POLL)
            up.settimeout(_POLL)
            with self._lock:
                self._conns.extend((conn, up))
                for src, dst, fwd in ((conn, up, True), (up, conn, False)):
                    t = threading.Thread(
                        target=self._pump, args=(src, dst, fwd), daemon=True,
                        name=f"chaos-pump-{self.name}")
                    self._pumps.append(t)
                    t.start()
                # reap finished pump threads so a long soak's list stays
                # bounded (joined-or-alive, never abandoned)
                self._pumps = [t for t in self._pumps if t.is_alive()]

    def _pump(self, src: socket.socket, dst: socket.socket,
              forward: bool) -> None:
        budget = 0.0            # throttle token debt, seconds
        while not self._stop.is_set():
            if self.fault.drop:
                break           # mid-stream partition: starve + reset below
            try:
                chunk = src.recv(_CHUNK)
            except socket.timeout:
                continue
            except OSError:
                break
            if not chunk:
                break
            delay = self.fault.delay
            if delay:
                time.sleep(delay)
            rate = self.fault.rate
            if rate > 0:
                budget += len(chunk) / rate
                if budget > 0.01:
                    time.sleep(min(budget, 2.0))
                    budget = 0.0
            try:
                dst.sendall(chunk)
            except OSError:
                break
            if forward:
                self.stats.bytes_forward += len(chunk)
            else:
                self.stats.bytes_backward += len(chunk)
        for s in (src, dst):
            self._abort(s)

    @staticmethod
    def _abort(sock: socket.socket) -> None:
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                            b"\x01\x00\x00\x00\x00\x00\x00\x00")
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        self.reset_streams()
        self._thread.join(timeout=2 * _POLL + 1.0)
        with self._lock:
            pumps, self._pumps = self._pumps, []
        for t in pumps:
            t.join(timeout=2 * _POLL + 1.0)


def _split(address: str) -> Tuple[str, int]:
    host, _, port = address.rpartition(":")
    return host, int(port)


class ProxyMesh:
    """All directed links of a fleet: nodes are opaque string keys, every
    (dialer, target) pair gets its own `ChaosLink`, and the per-dialer
    dial map (real target address -> that dialer's proxy address) is what
    `DRAND_DIAL_MAP` points each daemon at."""

    def __init__(self):
        self._links: Dict[Tuple[str, str], ChaosLink] = {}
        self._addrs: Dict[str, str] = {}

    def build(self, addrs: Dict[str, str]) -> None:
        """Create links for every ordered pair of `addrs` (node -> real
        listen address).  Idempotent per pair: rebuilding after a node
        restart keeps existing links (their upstream address is stable
        because restarts re-pin the private port)."""
        self._addrs.update(addrs)
        for src in self._addrs:
            for dst, upstream in self._addrs.items():
                if src == dst or (src, dst) in self._links:
                    continue
                self._links[(src, dst)] = ChaosLink(
                    upstream, name=f"{src}-{dst}")

    def link(self, src: str, dst: str) -> ChaosLink:
        return self._links[(src, dst)]

    def links(self) -> Iterable[Tuple[Tuple[str, str], ChaosLink]]:
        return self._links.items()

    def dial_map_for(self, src: str) -> Dict[str, str]:
        return {self._addrs[dst]: link.address
                for (s, dst), link in self._links.items() if s == src}

    # -- fleet-level faults --------------------------------------------------

    def partition(self, side_a: Iterable[str], side_b: Iterable[str]) -> None:
        """Drop every link crossing the A|B cut, both directions, and
        reset the streams already up."""
        a, b = set(side_a), set(side_b)
        for (src, dst), link in self._links.items():
            if (src in a and dst in b) or (src in b and dst in a):
                link.drop_and_reset()

    def isolate(self, node: str) -> None:
        others = [n for n in self._addrs if n != node]
        self.partition([node], others)

    def heal_all(self) -> None:
        for link in self._links.values():
            link.heal()

    def set_link(self, src: str, dst: str, **fault) -> None:
        self._links[(src, dst)].set_fault(**fault)

    def stats(self) -> Dict[str, dict]:
        return {f"{s}->{d}": vars(link.stats)
                for (s, d), link in self._links.items()}

    def stop(self) -> None:
        for link in self._links.values():
            link.stop()
        self._links.clear()
