"""proto <-> domain codecs (reference: chain/beacon/convert.go:9-24,
key/group.go:371-486, core/drand_beacon_control.go packet plumbing).
"""

from typing import Optional

from ..chain.beacon import Beacon
from ..chain.info import Info
from ..crypto import dkg as D
from ..crypto.schemes import get_scheme_by_id_with_default
from ..key.group import Group, Node
from ..key.keys import DistPublic, Identity
from ..protos import drand_pb2 as pb

def _version_from_env() -> pb.NodeVersion:
    """Advertised protocol version; DRAND_NODE_VERSION=maj.min.patch
    overrides (mixed-version rollout testing, demo/regression/main.go)."""
    import os
    raw = os.environ.get("DRAND_NODE_VERSION", "")
    if raw:
        try:
            maj, mino, pat = (int(x) for x in raw.split("."))
            return pb.NodeVersion(major=maj, minor=mino, patch=pat)
        except ValueError:
            pass
    return pb.NodeVersion(major=2, minor=0, patch=0)


VERSION = _version_from_env()


def metadata(beacon_id: str = "", chain_hash: bytes = b"") -> pb.Metadata:
    return pb.Metadata(node_version=VERSION, beaconID=beacon_id or "default",
                       chain_hash=chain_hash)


def version_compatible(md) -> bool:
    """Reject peers with an incompatible protocol major version
    (core/drand_daemon_interceptors.go:19-89; a zero version — legacy or
    absent metadata — is accepted like the reference's prerelease rule)."""
    if md is None or not md.HasField("node_version"):
        return True
    v = md.node_version
    if v.major == 0 and v.minor == 0:
        return True
    return v.major == VERSION.major


# -- beacons ----------------------------------------------------------------

def beacon_to_proto(b: Beacon, beacon_id: str = "") -> pb.BeaconPacket:
    return pb.BeaconPacket(previous_signature=b.previous_sig or b"",
                           round=b.round, signature=b.signature,
                           metadata=metadata(beacon_id))


def proto_to_beacon(p: pb.BeaconPacket) -> Beacon:
    return Beacon(round=p.round, signature=p.signature,
                  previous_sig=p.previous_signature or None)


def beacon_to_rand(b: Beacon, beacon_id: str = "") -> pb.PublicRandResponse:
    return pb.PublicRandResponse(
        round=b.round, signature=b.signature,
        previous_signature=b.previous_sig or b"",
        randomness=b.randomness(), metadata=metadata(beacon_id))


def rand_to_beacon(p: pb.PublicRandResponse) -> Beacon:
    return Beacon(round=p.round, signature=p.signature,
                  previous_sig=p.previous_signature or None)


# -- identities -------------------------------------------------------------

def identity_to_proto(ident: Identity) -> pb.Identity:
    return pb.Identity(address=ident.addr, key=ident.key, tls=ident.tls,
                       signature=ident.signature or b"")


def proto_to_identity(p, scheme) -> Identity:
    return Identity(key=p.key, addr=p.address, scheme=scheme, tls=p.tls,
                    signature=p.signature or None)


# -- groups -----------------------------------------------------------------

def group_to_proto(g: Group, beacon_id: str = "") -> pb.GroupPacket:
    pkt = pb.GroupPacket(
        threshold=g.threshold, period=g.period,
        genesis_time=g.genesis_time, transition_time=max(g.transition_time, 0),
        genesis_seed=g.get_genesis_seed(),
        catchup_period=g.catchup_period, schemeID=g.scheme.id,
        metadata=metadata(beacon_id or g.beacon_id))
    for n in g.nodes:
        pkt.nodes.append(pb.GroupNode(public=identity_to_proto(n.identity),
                                      index=n.index))
    if g.public_key is not None:
        pkt.dist_key.extend(g.public_key.coefficients)
    return pkt


def proto_to_group(p: pb.GroupPacket) -> Group:
    scheme = get_scheme_by_id_with_default(p.schemeID)
    nodes = [Node(identity=proto_to_identity(gn.public, scheme),
                  index=gn.index) for gn in p.nodes]
    pk = DistPublic(list(p.dist_key)) if p.dist_key else None
    beacon_id = p.metadata.beaconID if p.HasField("metadata") else ""
    return Group(
        threshold=p.threshold, period=p.period, scheme=scheme, nodes=nodes,
        genesis_time=p.genesis_time, beacon_id=beacon_id,
        catchup_period=p.catchup_period,
        genesis_seed=p.genesis_seed or None,
        transition_time=p.transition_time, public_key=pk)


# -- chain info -------------------------------------------------------------

def info_to_proto(info: Info) -> pb.ChainInfoPacket:
    return pb.ChainInfoPacket(
        public_key=info.public_key, period=info.period,
        genesis_time=info.genesis_time, hash=info.hash(),
        group_hash=info.genesis_seed, schemeID=info.scheme,
        metadata=metadata(info.beacon_id))


def proto_to_info(p: pb.ChainInfoPacket) -> Info:
    info = Info(public_key=p.public_key, period=p.period,
                genesis_time=p.genesis_time, genesis_seed=p.group_hash,
                scheme=p.schemeID,
                beacon_id=p.metadata.beaconID if p.HasField("metadata") else "")
    if p.hash and p.hash != info.hash():
        raise ValueError("chain info hash mismatch")
    return info


# -- DKG bundles ------------------------------------------------------------

def dkg_bundle_to_proto(bundle, beacon_id: str = "") -> pb.DKGBundle:
    out = pb.DKGBundle(metadata=metadata(beacon_id))
    if isinstance(bundle, D.DealBundle):
        db = out.deal
        db.dealer_index = bundle.dealer_index
        db.commits.extend(bundle.commits)
        for d in bundle.deals:
            db.deals.append(pb.DealShare(share_index=d.share_index,
                                         encrypted_share=d.encrypted))
        db.session_id, db.signature = bundle.session_id, bundle.signature
    elif isinstance(bundle, D.ResponseBundle):
        rb = out.response
        rb.share_index = bundle.share_index
        for r in bundle.responses:
            rb.responses.append(pb.DealerStatus(
                dealer_index=r.dealer_index,
                status=(r.status == D.STATUS_SUCCESS)))
        rb.session_id, rb.signature = bundle.session_id, bundle.signature
    elif isinstance(bundle, D.JustificationBundle):
        jb = out.justification
        jb.dealer_index = bundle.dealer_index
        for j in bundle.justifications:
            jb.justifications.append(pb.JustificationShare(
                share_index=j.share_index,
                share=j.share.to_bytes(32, "big")))
        jb.session_id, jb.signature = bundle.session_id, bundle.signature
    else:
        raise TypeError(f"not a DKG bundle: {type(bundle)}")
    return out


def proto_to_dkg_bundle(p: pb.DKGBundle):
    which = p.WhichOneof("bundle")
    if which == "deal":
        db = p.deal
        return D.DealBundle(
            dealer_index=db.dealer_index, commits=list(db.commits),
            deals=[D.Deal(share_index=d.share_index,
                          encrypted=d.encrypted_share) for d in db.deals],
            session_id=db.session_id, signature=db.signature)
    if which == "response":
        rb = p.response
        return D.ResponseBundle(
            share_index=rb.share_index,
            responses=[D.Response(
                dealer_index=r.dealer_index,
                status=D.STATUS_SUCCESS if r.status else D.STATUS_COMPLAINT)
                for r in rb.responses],
            session_id=rb.session_id, signature=rb.signature)
    if which == "justification":
        jb = p.justification
        return D.JustificationBundle(
            dealer_index=jb.dealer_index,
            justifications=[D.Justification(
                share_index=j.share_index,
                share=int.from_bytes(j.share, "big"))
                for j in jb.justifications],
            session_id=jb.session_id, signature=jb.signature)
    raise ValueError("empty DKG bundle")
