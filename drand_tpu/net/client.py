"""Node-to-node gRPC client with a lazy per-peer connection pool.

Reference: net/client_grpc.go:31-369 (conn pool :276, SyncChain stream pump
:211-248, 1-minute default timeout :39 overridable via DRAND_DIAL_TIMEOUT).
TLS here means channel credentials from the trusted-cert pool
(net/certs.go:45); plaintext otherwise.
"""

import os
import threading
from dataclasses import dataclass
from typing import Dict, Iterator, Optional, Sequence

import grpc

from ..chain.beacon import Beacon
from ..protos import drand_pb2 as pb
from . import convert, services

DEFAULT_TIMEOUT = float(os.environ.get("DRAND_DIAL_TIMEOUT", "60"))


@dataclass(frozen=True)
class Peer:
    """Reachable node address (net/peer.go)."""
    address: str
    tls: bool = False


class CertManager:
    """Pool of trusted PEM certs for TLS channels (net/certs.go:45)."""

    def __init__(self):
        self._pems = []

    def add(self, pem_path: str) -> None:
        with open(pem_path, "rb") as f:
            self._pems.append(f.read())

    def credentials(self) -> grpc.ChannelCredentials:
        root = b"".join(self._pems) if self._pems else None
        return grpc.ssl_channel_credentials(root_certificates=root)


class _BeaconStream:
    """Iterator over a SyncChain gRPC call that keeps `cancel()` reachable
    (a bare generator would hide the call object in its frame)."""

    def __init__(self, call):
        self._call = call

    def __iter__(self):
        return self

    def __next__(self) -> Beacon:
        return convert.proto_to_beacon(next(self._call))

    def cancel(self) -> None:
        try:
            self._call.cancel()
        except Exception:
            pass


class ProtocolClient:
    """Dial-side of the Protocol + Public services, one channel per peer."""

    def __init__(self, certs: Optional[CertManager] = None,
                 timeout: float = DEFAULT_TIMEOUT):
        self.certs = certs or CertManager()
        self.timeout = timeout
        self._conns: Dict[tuple, grpc.Channel] = {}
        self._lock = threading.Lock()

    # -- pool ----------------------------------------------------------------

    def channel(self, peer: Peer) -> grpc.Channel:
        key = (peer.address, peer.tls)   # a TLS peer must never reuse a
        with self._lock:                 # cached plaintext channel
            ch = self._conns.get(key)
            if ch is None:
                if peer.tls:
                    ch = grpc.secure_channel(peer.address,
                                             self.certs.credentials())
                else:
                    ch = grpc.insecure_channel(peer.address)
                self._conns[key] = ch
            return ch

    def close(self) -> None:
        with self._lock:
            for ch in self._conns.values():
                ch.close()
            self._conns.clear()

    def _protocol(self, peer: Peer):
        return services.PROTOCOL.stub(self.channel(peer))

    def _public(self, peer: Peer):
        return services.PUBLIC.stub(self.channel(peer))

    # -- Protocol service ----------------------------------------------------

    def get_identity(self, peer: Peer, beacon_id: str = "") -> pb.IdentityResponse:
        req = pb.IdentityRequest(metadata=convert.metadata(beacon_id))
        return self._protocol(peer).get_identity(req, timeout=self.timeout)

    def signal_dkg_participant(self, peer: Peer, packet: pb.SignalDKGPacket,
                               timeout: Optional[float] = None) -> None:
        self._protocol(peer).signal_dkg_participant(
            packet, timeout=timeout or self.timeout)

    def push_dkg_info(self, peer: Peer, packet: pb.DKGInfoPacket,
                      timeout: Optional[float] = None) -> None:
        self._protocol(peer).push_dkg_info(packet,
                                           timeout=timeout or self.timeout)

    def broadcast_dkg(self, peer: Peer, packet: pb.DKGPacket) -> None:
        self._protocol(peer).broadcast_dkg(packet, timeout=self.timeout)

    def partial_beacon(self, peer: Peer, packet: pb.PartialBeaconPacket,
                       timeout: Optional[float] = None) -> None:
        self._protocol(peer).partial_beacon(packet,
                                            timeout=timeout or self.timeout)

    def sync_chain(self, peer: Peer, from_round: int,
                   beacon_id: str = "") -> "_BeaconStream":
        """Server-stream of BeaconPackets starting at from_round
        (client_grpc.go:211-248).  The returned iterator forwards
        `cancel()` to the underlying gRPC call so sync watchdogs can tear
        down a black-holed stream."""
        req = pb.SyncRequest(from_round=from_round,
                             metadata=convert.metadata(beacon_id))
        return _BeaconStream(self._protocol(peer).sync_chain(req))

    def status(self, peer: Peer, beacon_id: str = "",
               check_conn: Sequence[Peer] = ()) -> pb.StatusResponse:
        req = pb.StatusRequest(metadata=convert.metadata(beacon_id))
        for p in check_conn:
            req.check_conn.append(pb.StatusAddress(address=p.address,
                                                   tls=p.tls))
        return self._protocol(peer).status(req, timeout=self.timeout)

    def metrics(self, peer: Peer, beacon_id: str = "") -> bytes:
        """Fetch a peer's GroupMetrics snapshot (federation; the reference
        proxies HTTP over the gRPC conn instead, client_grpc.go:352-361)."""
        req = pb.MetricsRequest(metadata=convert.metadata(beacon_id))
        return self._protocol(peer).metrics(req, timeout=self.timeout).metrics

    # -- Public service ------------------------------------------------------

    def public_rand(self, peer: Peer, round_: int = 0,
                    beacon_id: str = "") -> pb.PublicRandResponse:
        req = pb.PublicRandRequest(round=round_,
                                   metadata=convert.metadata(beacon_id))
        return self._public(peer).public_rand(req, timeout=self.timeout)

    def public_rand_stream(self, peer: Peer, round_: int = 0,
                           beacon_id: str = "") -> Iterator[pb.PublicRandResponse]:
        req = pb.PublicRandRequest(round=round_,
                                   metadata=convert.metadata(beacon_id))
        return self._public(peer).public_rand_stream(req)

    def chain_info(self, peer: Peer, beacon_id: str = "") -> pb.ChainInfoPacket:
        req = pb.ChainInfoRequest(metadata=convert.metadata(beacon_id))
        return self._public(peer).chain_info(req, timeout=self.timeout)

    def home(self, peer: Peer, beacon_id: str = "") -> pb.HomeResponse:
        req = pb.HomeRequest(metadata=convert.metadata(beacon_id))
        return self._public(peer).home(req, timeout=self.timeout)
