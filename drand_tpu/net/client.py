"""Node-to-node gRPC client with a lazy per-peer connection pool.

Reference: net/client_grpc.go:31-369 (conn pool :276, SyncChain stream pump
:211-248, 1-minute default timeout :39 overridable via DRAND_DIAL_TIMEOUT).
TLS here means channel credentials from the trusted-cert pool
(net/certs.go:45); plaintext otherwise.

When a `ResiliencePolicy` is attached (net/resilience.py), every unary call
runs through its retry executor — deadline-clamped per-attempt timeouts,
backoff with jitter, per-peer breaker accounting — and the SyncChain stream
feeds the same breakers (a dial failure releases the probe; a half-open
probe is closed by the first delivered beacon; content verdicts stay with
the SyncManager) so one subsystem's failures steer every other subsystem's
peer selection.
"""

import json
import os
import threading

from ..common import make_lock
from dataclasses import dataclass
from typing import Dict, Iterator, Optional, Sequence

import grpc

from ..chain.beacon import Beacon
from ..protos import drand_pb2 as pb
from . import convert, services
from .resilience import (HALF_OPEN, BreakerOpen, Deadline, ResiliencePolicy,
                         peer_key)

DEFAULT_TIMEOUT = float(os.environ.get("DRAND_DIAL_TIMEOUT", "60"))


class DialMap:
    """Dial-time address indirection (the fleet chaos harness's hook,
    net/chaosproxy.py): `DRAND_DIAL_MAP` names a JSON file mapping real
    peer addresses to per-link proxy addresses, and every outbound channel
    is dialed at the rewritten target.  The identity layer is untouched —
    peers still advertise (and sign) their real addresses; only the TCP
    connection detours through the proxy.

    The file is re-read on mtime change so a supervisor can write it
    after the daemon is already up (the fleet wires dial maps between
    ready-file collection and the DKG kickoff); a missing or unparsable
    file means identity — a half-written map must never black-hole the
    dialer, so rewrite errors fail open."""

    def __init__(self, path: str = ""):
        self.path = path or os.environ.get("DRAND_DIAL_MAP", "")
        self._stamp = None
        self._map: Dict[str, str] = {}
        self._lock = make_lock()

    def rewrite(self, address: str) -> str:
        if not self.path:
            return address
        try:
            stamp = os.stat(self.path).st_mtime_ns
        except OSError:
            return address
        with self._lock:
            if stamp != self._stamp:
                try:
                    with open(self.path) as f:
                        loaded = json.load(f)
                    self._map = {str(k): str(v) for k, v in loaded.items()}
                    self._stamp = stamp
                except (OSError, ValueError):
                    return address
            return self._map.get(address, address)


@dataclass(frozen=True)
class Peer:
    """Reachable node address (net/peer.go)."""
    address: str
    tls: bool = False


class CertManager:
    """Pool of trusted PEM certs for TLS channels (net/certs.go:45)."""

    def __init__(self):
        self._pems = []

    def add(self, pem_path: str) -> None:
        with open(pem_path, "rb") as f:
            self._pems.append(f.read())

    def credentials(self) -> grpc.ChannelCredentials:
        root = b"".join(self._pems) if self._pems else None
        return grpc.ssl_channel_credentials(root_certificates=root)


class _BeaconStream:
    """Iterator over a SyncChain gRPC call that keeps `cancel()` reachable
    (a bare generator would hide the call object in its frame).  The
    optional breaker hook closes a HALF_OPEN probe on the first delivered
    beacon — a transport-level reachability verdict.  In CLOSED state
    nothing is recorded here: content verdicts belong to the SyncManager,
    and resetting the failure streak on every delivered chunk would let a
    content-Byzantine peer (working transport, forged signatures) oscillate
    between 0 and 1 consecutive failures and never trip its breaker.
    Failures are likewise NOT recorded here: the SyncManager records one
    per fruitless peer-try — accounting in both layers would double-count
    every transport error and halve the configured failure threshold."""

    def __init__(self, call, breaker=None):
        self._call = call
        self._breaker = breaker
        self._delivered = False

    def __iter__(self):
        return self

    def __next__(self) -> Beacon:
        item = next(self._call)
        if not self._delivered:
            self._delivered = True
            if self._breaker is not None \
                    and self._breaker.state == HALF_OPEN:
                self._breaker.record_success()
        return convert.proto_to_beacon(item)

    def cancel(self) -> None:
        try:
            self._call.cancel()
        except Exception:
            pass


class ProtocolClient:
    """Dial-side of the Protocol + Public services, one channel per peer."""

    def __init__(self, certs: Optional[CertManager] = None,
                 timeout: float = DEFAULT_TIMEOUT,
                 resilience: Optional[ResiliencePolicy] = None,
                 dial_map: Optional[DialMap] = None, identity=None):
        self.certs = certs or CertManager()
        self.timeout = timeout
        self.resilience = resilience
        self.dial_map = dial_map or DialMap()
        self.identity = identity      # net/identity.py IdentityPlane or None
        self._conns: Dict[tuple, grpc.Channel] = {}
        self._lock = make_lock()

    # -- pool ----------------------------------------------------------------

    def channel(self, peer: Peer) -> grpc.Channel:
        # dial indirection: the chaos harness reroutes this peer through
        # its per-link proxy; identity (breakers, peer keys, group
        # addresses) stays keyed on the REAL address
        target = self.dial_map.rewrite(peer.address)
        if self.identity is not None:
            # the mesh speaks mTLS on EVERY dial regardless of the peer's
            # advertised tls flag (group files predating the identity
            # plane carry tls=False); the channel cache is keyed on the
            # cert epoch so a hot rotation re-dials with fresh creds
            # instead of reusing a channel pinned to the old client cert
            self.identity.maybe_reload()
            epoch = self.identity.epoch
            key = (target, True, epoch)
            with self._lock:
                ch = self._conns.get(key)
                if ch is None:
                    ch = grpc.secure_channel(
                        target, self.identity.channel_credentials(),
                        options=(("grpc.ssl_target_name_override",
                                  "localhost"),))
                    self._conns[key] = ch
                    # drop channels pinned to superseded cert epochs
                    for k in [k for k in self._conns
                              if len(k) == 3 and k[2] != epoch]:
                        self._conns.pop(k).close()
                return ch
        key = (target, peer.tls)         # a TLS peer must never reuse a
        with self._lock:                 # cached plaintext channel
            ch = self._conns.get(key)
            if ch is None:
                if peer.tls:
                    ch = grpc.secure_channel(target,
                                             self.certs.credentials())
                else:
                    ch = grpc.insecure_channel(target)
                self._conns[key] = ch
            return ch

    def close(self) -> None:
        with self._lock:
            for ch in self._conns.values():
                ch.close()
            self._conns.clear()

    def _protocol(self, peer: Peer):
        return services.PROTOCOL.stub(self.channel(peer))

    def _public(self, peer: Peer):
        return services.PUBLIC.stub(self.channel(peer))

    # -- resilient unary dispatch -------------------------------------------

    def _unary(self, peer: Peer, op: str, fn, timeout: Optional[float] = None,
               deadline: Optional[Deadline] = None, breaker: bool = True):
        """Run `fn(per_attempt_timeout)` under the attached policy (retry +
        breaker + deadline); without a policy, a bare single attempt with
        the deadline still clamping the static timeout.  `breaker=False`
        keeps retries/deadlines but skips breaker accounting — used by the
        DKG setup plane, where the coordinator is EXPECTED to be down until
        the operator runs InitDKG and quarantining it would deadlock the
        join loop."""
        t = timeout or self.timeout
        if self.resilience is None:
            return fn(deadline.clamp(t) if deadline is not None else t)
        return self.resilience.call(fn,
                                    key=peer.address if breaker else None,
                                    op=op, timeout=t, deadline=deadline)

    # -- Protocol service ----------------------------------------------------

    def get_identity(self, peer: Peer, beacon_id: str = "",
                     deadline: Optional[Deadline] = None
                     ) -> pb.IdentityResponse:
        req = pb.IdentityRequest(metadata=convert.metadata(beacon_id))
        return self._unary(
            peer, "get_identity",
            lambda t: self._protocol(peer).get_identity(req, timeout=t),
            deadline=deadline, breaker=False)

    def signal_dkg_participant(self, peer: Peer, packet: pb.SignalDKGPacket,
                               timeout: Optional[float] = None,
                               deadline: Optional[Deadline] = None) -> None:
        self._unary(
            peer, "signal_dkg_participant",
            lambda t: self._protocol(peer).signal_dkg_participant(
                packet, timeout=t),
            timeout=timeout, deadline=deadline, breaker=False)

    def push_dkg_info(self, peer: Peer, packet: pb.DKGInfoPacket,
                      timeout: Optional[float] = None,
                      deadline: Optional[Deadline] = None) -> None:
        self._unary(
            peer, "push_dkg_info",
            lambda t: self._protocol(peer).push_dkg_info(packet, timeout=t),
            timeout=timeout, deadline=deadline)

    def broadcast_dkg(self, peer: Peer, packet: pb.DKGPacket) -> None:
        self._unary(
            peer, "broadcast_dkg",
            lambda t: self._protocol(peer).broadcast_dkg(packet, timeout=t))

    def partial_beacon(self, peer: Peer, packet: pb.PartialBeaconPacket,
                       timeout: Optional[float] = None,
                       deadline: Optional[Deadline] = None) -> None:
        self._unary(
            peer, "partial_beacon",
            lambda t: self._protocol(peer).partial_beacon(packet, timeout=t),
            timeout=timeout, deadline=deadline)

    def handel_aggregate(self, peer: Peer, packet,
                         timeout: Optional[float] = None,
                         deadline: Optional[Deadline] = None) -> None:
        """One Handel candidate aggregate (beacon/handel.py).  Overlay
        sends are latency-critical and redundant across a level's targets,
        so this is a SINGLE attempt under breaker accounting — the next
        tick re-targets by score anyway, and a backoff chain inside the
        tick thread would stall every later level's sends."""
        fn = lambda t: self._protocol(peer).handel_aggregate(  # noqa: E731
            packet, timeout=t)
        t = timeout or self.timeout
        if self.resilience is None:
            fn(deadline.clamp(t) if deadline is not None else t)
            return
        self.resilience.call(fn, key=peer.address, op="handel_aggregate",
                             timeout=t, deadline=deadline, max_attempts=1)

    def sync_chain(self, peer: Peer, from_round: int,
                   beacon_id: str = "") -> "_BeaconStream":
        """Server-stream of BeaconPackets starting at from_round
        (client_grpc.go:211-248).  The returned iterator forwards
        `cancel()` to the underlying gRPC call so sync watchdogs can tear
        down a black-holed stream.  With a policy attached, an open breaker
        rejects the dial outright and the stream's first-item/error events
        feed the breaker."""
        breaker = None
        if self.resilience is not None:
            breaker = self.resilience.breaker(peer_key(peer))
            if not breaker.allow():
                raise BreakerOpen(f"sync_chain {peer.address} open")
        req = pb.SyncRequest(from_round=from_round,
                             metadata=convert.metadata(beacon_id))
        try:
            call = self._protocol(peer).sync_chain(req)
        except Exception:
            if breaker is not None:
                breaker.record_failure()   # dial failed: release the probe
            raise
        return _BeaconStream(call, breaker=breaker)

    def status(self, peer: Peer, beacon_id: str = "",
               check_conn: Sequence[Peer] = ()) -> pb.StatusResponse:
        req = pb.StatusRequest(metadata=convert.metadata(beacon_id))
        for p in check_conn:
            req.check_conn.append(pb.StatusAddress(address=p.address,
                                                   tls=p.tls))
        return self._unary(
            peer, "status",
            lambda t: self._protocol(peer).status(req, timeout=t))

    def metrics(self, peer: Peer, beacon_id: str = "") -> bytes:
        """Fetch a peer's GroupMetrics snapshot (federation; the reference
        proxies HTTP over the gRPC conn instead, client_grpc.go:352-361)."""
        req = pb.MetricsRequest(metadata=convert.metadata(beacon_id))
        return self._unary(
            peer, "metrics",
            lambda t: self._protocol(peer).metrics(req, timeout=t)).metrics

    # -- Public service ------------------------------------------------------

    def public_rand(self, peer: Peer, round_: int = 0,
                    beacon_id: str = "",
                    token: Optional[str] = None) -> pb.PublicRandResponse:
        md = (("authorization", f"Bearer {token}"),) if token else None
        req = pb.PublicRandRequest(round=round_,
                                   metadata=convert.metadata(beacon_id))
        return self._unary(
            peer, "public_rand",
            lambda t: self._public(peer).public_rand(req, timeout=t,
                                                     metadata=md))

    def public_rand_stream(self, peer: Peer, round_: int = 0,
                           beacon_id: str = "") -> Iterator[pb.PublicRandResponse]:
        req = pb.PublicRandRequest(round=round_,
                                   metadata=convert.metadata(beacon_id))
        return self._public(peer).public_rand_stream(req)

    def chain_info(self, peer: Peer, beacon_id: str = "") -> pb.ChainInfoPacket:
        req = pb.ChainInfoRequest(metadata=convert.metadata(beacon_id))
        return self._unary(
            peer, "chain_info",
            lambda t: self._public(peer).chain_info(req, timeout=t))

    def home(self, peer: Peer, beacon_id: str = "") -> pb.HomeResponse:
        req = pb.HomeRequest(metadata=convert.metadata(beacon_id))
        return self._unary(
            peer, "home",
            lambda t: self._public(peer).home(req, timeout=t))
