"""L5 network plane: gRPC services, client pool, gateways, control plane.

Reference: net/ (SURVEY.md §2.6).  Messages live in drand_tpu/protos;
service specs in services.py; the generic service framework in rpc.py.
"""

from .admission import (AdmissionController, AdmissionInterceptor, Shed,
                        Ticket)
from .chaosproxy import ChaosLink, LinkFault, ProxyMesh
from .client import CertManager, DialMap, Peer, ProtocolClient
from .identity import IdentityPlane, PeerIdentity, issue_cert, provision_fleet
from .listener import (ControlClient, ControlListener, Listener,
                       PrivateGateway)
from .resilience import (BackoffPolicy, BreakerOpen, BreakerRegistry,
                         CircuitBreaker, Deadline, DeadlineExceeded,
                         ResiliencePolicy)
from .services import CONTROL, PROTOCOL, PUBLIC

__all__ = [
    "CertManager", "Peer", "ProtocolClient", "ControlClient",
    "ControlListener", "Listener", "PrivateGateway", "CONTROL", "PROTOCOL",
    "PUBLIC", "BackoffPolicy", "BreakerOpen", "BreakerRegistry",
    "CircuitBreaker", "Deadline", "DeadlineExceeded", "ResiliencePolicy",
    "AdmissionController", "AdmissionInterceptor", "Shed", "Ticket",
    "ChaosLink", "LinkFault", "ProxyMesh", "DialMap",
    "IdentityPlane", "PeerIdentity", "issue_cert", "provision_fleet",
]
