"""The three drand service specs (wire-path compatible with the reference).

  Protocol — node-to-node plane (protobuf/drand/protocol.proto:17-37)
  Public   — public API plane   (protobuf/drand/api.proto:16-28)
  Control  — localhost CLI<->daemon plane (protobuf/drand/control.proto:15-56)
"""

from ..protos import drand_pb2 as pb
from .rpc import Method, ServiceSpec

PROTOCOL = ServiceSpec("drand.Protocol", [
    Method("GetIdentity", pb.IdentityRequest, pb.IdentityResponse),
    Method("SignalDKGParticipant", pb.SignalDKGPacket, pb.Empty),
    Method("PushDKGInfo", pb.DKGInfoPacket, pb.Empty),
    Method("BroadcastDKG", pb.DKGPacket, pb.Empty),
    Method("PartialBeacon", pb.PartialBeaconPacket, pb.Empty),
    # Handel overlay (beacon/handel.py): one candidate aggregate for a
    # tree level.  Rides the Protocol plane, so net/admission.py's
    # classify_method already treats it as critical-class — aggregation
    # traffic is never shed behind public reads.
    Method("HandelAggregate", pb.HandelAggregatePacket, pb.Empty),
    Method("SyncChain", pb.SyncRequest, pb.BeaconPacket, server_stream=True),
    Method("Status", pb.StatusRequest, pb.StatusResponse),
    # Federation: GroupMetrics snapshot over the node-to-node plane
    # (reference serves HTTP-over-gRPC instead: net/listener.go:88).
    Method("Metrics", pb.MetricsRequest, pb.MetricsResponse),
])

PUBLIC = ServiceSpec("drand.Public", [
    Method("PublicRand", pb.PublicRandRequest, pb.PublicRandResponse),
    Method("PublicRandStream", pb.PublicRandRequest, pb.PublicRandResponse,
           server_stream=True),
    Method("ChainInfo", pb.ChainInfoRequest, pb.ChainInfoPacket),
    Method("Home", pb.HomeRequest, pb.HomeResponse),
])

# Relay gossip overlay (lp2p gossipsub equivalent, see drand_tpu/relay.py)
GOSSIP = ServiceSpec("drand.Gossip", [
    Method("Publish", pb.GossipBeaconPacket, pb.Empty),
])

CONTROL = ServiceSpec("drand.Control", [
    Method("PingPong", pb.Ping, pb.Pong),
    Method("Status", pb.StatusRequest, pb.StatusResponse),
    Method("ListSchemes", pb.ListSchemesRequest, pb.ListSchemesResponse),
    Method("ListBeaconIDs", pb.ListBeaconIDsRequest, pb.ListBeaconIDsResponse),
    Method("InitDKG", pb.InitDKGPacket, pb.GroupPacket),
    Method("InitReshare", pb.InitResharePacket, pb.GroupPacket),
    Method("PublicKey", pb.PublicKeyRequest, pb.PublicKeyResponse),
    Method("PrivateKey", pb.PrivateKeyRequest, pb.PrivateKeyResponse),
    Method("ChainInfo", pb.ChainInfoRequest, pb.ChainInfoPacket),
    Method("GroupFile", pb.GroupRequest, pb.GroupPacket),
    Method("Shutdown", pb.ShutdownRequest, pb.ShutdownResponse),
    Method("LoadBeacon", pb.LoadBeaconRequest, pb.LoadBeaconResponse),
    Method("StartFollowChain", pb.StartSyncRequest, pb.SyncProgress,
           server_stream=True),
    Method("StartCheckChain", pb.StartSyncRequest, pb.SyncProgress,
           server_stream=True),
    Method("BackupDatabase", pb.BackupDBRequest, pb.BackupDBResponse),
    Method("RemoteStatus", pb.RemoteStatusRequest, pb.RemoteStatusResponse),
    # Multi-tenant serving (core/tenancy.py, ISSUE 15): tenant
    # add/update/remove without a daemon restart.  Control plane only —
    # tenancy is operator configuration, never a peer-reachable surface.
    Method("TenantSet", pb.TenantConfigPacket, pb.TenantListResponse),
    Method("TenantRemove", pb.TenantRequest, pb.TenantListResponse),
    Method("TenantList", pb.TenantRequest, pb.TenantListResponse),
    # Tenant tokens (core/authz.py, ISSUE 19): macaroon mint/revoke.
    # Control plane only — the root key never leaves the daemon, and the
    # minted token string is returned exactly once.
    Method("TokenMint", pb.TokenMintRequest, pb.TokenMintResponse),
    Method("TokenRevoke", pb.TokenRequest, pb.TokenListResponse),
    Method("TokenList", pb.TokenRequest, pb.TokenListResponse),
])
