"""Shared constants: beacon IDs, versioning (reference common/beacon.go:9-21,
common/version.go)."""

DEFAULT_BEACON_ID = "default"
DEFAULT_CHAIN_HASH = "default"
MULTI_BEACON_FOLDER = "multibeacon"

# Reduce log verbosity in bulk loops: log every LOGS_TO_SKIP steps.
LOGS_TO_SKIP = 300

# Protocol version advertised in packet metadata; peers reject incompatible
# major.minor (core/drand_daemon_interceptors.go:19-89).
VERSION = (2, 0, 0)


def is_default_beacon_id(beacon_id: str) -> bool:
    return beacon_id in ("", DEFAULT_BEACON_ID)


def compare_beacon_ids(id1: str, id2: str) -> bool:
    if is_default_beacon_id(id1) and is_default_beacon_id(id2):
        return True
    return id1 == id2
