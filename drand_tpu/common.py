"""Shared constants: beacon IDs, versioning (reference common/beacon.go:9-21,
common/version.go)."""

DEFAULT_BEACON_ID = "default"
DEFAULT_CHAIN_HASH = "default"
MULTI_BEACON_FOLDER = "multibeacon"

# Reduce log verbosity in bulk loops: log every LOGS_TO_SKIP steps.
LOGS_TO_SKIP = 300

# Protocol version advertised in packet metadata; peers reject incompatible
# major.minor (core/drand_daemon_interceptors.go:19-89).
VERSION = (2, 0, 0)


def is_default_beacon_id(beacon_id: str) -> bool:
    return beacon_id in ("", DEFAULT_BEACON_ID)


def compare_beacon_ids(id1: str, id2: str) -> bool:
    if is_default_beacon_id(id1) and is_default_beacon_id(id2):
        return True
    return id1 == id2


# -- lock factories -----------------------------------------------------------
#
# Every lock in the serving plane is built through these so that
# `DRAND_TSAN=1` can swap in the runtime lock-order sanitizer
# (analysis/tsan.py).  With the env unset — the only configuration that
# ever serves traffic — each factory is a two-line passthrough returning
# the stock threading primitive: no wrapper object, no sanitizer import,
# no overhead beyond one os.environ read at construction time (lock
# construction is startup-path, never hot-path).  The static lock
# checker types these spellings in analysis/symbols.py; keep the names
# in sync.

def _tsan_on() -> bool:
    import os
    return os.environ.get("DRAND_TSAN", "") not in ("", "0")


def make_lock(name: str = ""):
    """A mutex: `threading.Lock()`, or an instrumented equivalent under
    DRAND_TSAN=1.  `name` labels the lock in sanitizer reports."""
    import threading
    if not _tsan_on():
        return threading.Lock()
    from .analysis import tsan
    return tsan.instrumented_lock(name)


def make_rlock(name: str = ""):
    """A re-entrant mutex (see make_lock)."""
    import threading
    if not _tsan_on():
        return threading.RLock()
    from .analysis import tsan
    return tsan.instrumented_rlock(name)


def make_condition(lock=None, name: str = ""):
    """A condition variable.  Under DRAND_TSAN=1 the underlying lock is
    instrumented and the stock Condition's own release/re-acquire in
    wait() flows through it, so held-sets stay correct across cv
    waits."""
    import threading
    if not _tsan_on():
        return threading.Condition(lock)
    if lock is None:
        from .analysis import tsan
        lock = tsan.instrumented_rlock(name or "cv")
    return threading.Condition(lock)
