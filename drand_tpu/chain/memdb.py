"""In-memory ring-buffer store (chain/memdb/store.go:15-198).

Keeps at most `buffer_size` newest beacons, sorted by round; duplicate
rounds are ignored.  Used for stateless nodes that bootstrap their chain
head from peers at startup (core/drand_beacon.go:484-529).
"""

import bisect
import threading

from ..common import make_rlock
from typing import Optional

from .beacon import Beacon
from .errors import ErrNoBeaconSaved, ErrNoBeaconStored
from .store import Cursor, Store


class MemDBStore(Store):
    DURABILITY = "volatile"
    MIN_BUFFER = 10

    def __init__(self, buffer_size: int = 2000):
        if buffer_size < self.MIN_BUFFER:
            raise ValueError(
                f"in-memory buffer size cannot be smaller than {self.MIN_BUFFER},"
                f" got {buffer_size} (recommended at least 2000)")
        self._lock = make_rlock()
        self._rounds: list = []     # sorted round numbers
        self._beacons: list = []    # parallel list of Beacons
        self._buffer_size = buffer_size

    def __len__(self) -> int:
        with self._lock:
            return len(self._beacons)

    def put(self, beacon: Beacon) -> None:
        with self._lock:
            i = bisect.bisect_left(self._rounds, beacon.round)
            if i < len(self._rounds) and self._rounds[i] == beacon.round:
                return  # duplicate rounds are a no-op (store.go:53-57)
            self._rounds.insert(i, beacon.round)
            self._beacons.insert(i, beacon)
            if len(self._beacons) > self._buffer_size:
                trim = len(self._beacons) - self._buffer_size
                del self._rounds[:trim]
                del self._beacons[:trim]

    def last(self) -> Beacon:
        with self._lock:
            if not self._beacons:
                raise ErrNoBeaconStored()
            return self._beacons[-1]

    def get(self, round_: int) -> Beacon:
        with self._lock:
            i = bisect.bisect_left(self._rounds, round_)
            if i < len(self._rounds) and self._rounds[i] == round_:
                return self._beacons[i]
            raise ErrNoBeaconSaved()

    def delete(self, round_: int) -> None:
        with self._lock:
            i = bisect.bisect_left(self._rounds, round_)
            if i < len(self._rounds) and self._rounds[i] == round_:
                del self._rounds[i]
                del self._beacons[i]

    def close(self) -> None:
        pass

    def cursor(self) -> Cursor:
        return _MemCursor(self)


class _MemCursor(Cursor):
    def __init__(self, store: MemDBStore):
        self._store = store
        self._pos = -1

    def _snapshot(self):
        with self._store._lock:
            return list(self._store._beacons)

    def first(self) -> Optional[Beacon]:
        self._pos = 0
        return self._at()

    def next(self) -> Optional[Beacon]:
        self._pos += 1
        return self._at()

    def last(self) -> Optional[Beacon]:
        snap = self._snapshot()
        self._pos = len(snap) - 1
        return snap[-1] if snap else None

    def seek(self, round_: int) -> Optional[Beacon]:
        with self._store._lock:
            self._pos = bisect.bisect_left(self._store._rounds, round_)
        return self._at()

    def _at(self) -> Optional[Beacon]:
        snap = self._snapshot()
        if 0 <= self._pos < len(snap):
            return snap[self._pos]
        return None
