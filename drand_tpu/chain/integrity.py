"""Chain-integrity scan + quarantine: the chain-doctor core.

No code path in the seed ever re-read a stored beacon after writing it —
a node could gossip correctly while serving corrupted local state (the
beacon-client failure class of arxiv 2109.11677).  This module makes the
stored chain re-verifiable:

  * **linkage mode** — structural host-only pass: round gaps, malformed
    signature encodings, and chained `previous_sig` linkage where the
    store materializes it.  O(n) dict/bytes work, no crypto.
  * **full mode** — linkage + batched signature verification.  The
    verifier is pluggable: `crypto.batch.BatchBeaconVerifier` runs whole
    chunks as one device RLC pairing check with bisect-to-culprit on
    failure (the TPU path that makes a full-chain scan cheap enough for
    startup), `crypto.hostverify.HostBatchVerifier` is the jax-free
    fallback.

The scanner walks the RAW store through a cursor and carries the linkage
anchor itself (the previous row's stored signature), so it works on
trimmed-format stores (sqlite/postgres persist only (round, signature))
and on full-beacon stores (memdb) alike.  Findings feed `quarantine`
(delete the bad rows, count them in metrics) and the repair path
(`beacon.sync.SyncManager.heal` re-fetches from breaker-ranked peers
under the sync budget; `tools/chain_doctor.py` drives the same loop
offline).
"""

import hashlib
import json
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

from .beacon import Beacon
from .errors import ErrNoBeaconSaved, ErrNoBeaconStored

# finding kinds (the `kind` label on chain_integrity_corrupt_found_total)
MISSING = "missing"              # round absent from the store
INVALID_SIG = "invalid_signature"  # stored signature fails verification
UNLINKED = "unlinked"            # stored previous_sig breaks the chain walk
MALFORMED = "malformed"          # signature is not a valid point encoding

MODE_LINKAGE = "linkage"
MODE_FULL = "full"

DEFAULT_CHUNK = 512

# initial state of the rolling scan digest (a fixed domain-separation
# constant, so an empty-prefix checkpoint is distinguishable from junk)
_DIGEST_SEED = hashlib.sha256(b"drand-tpu-scan-digest-v1").hexdigest()


def _roll_digest(digest_hex: str, round_: int, sig: bytes) -> str:
    return hashlib.sha256(bytes.fromhex(digest_hex)
                          + round_.to_bytes(8, "big")
                          + bytes(sig)).hexdigest()


@dataclass(frozen=True)
class ScanCheckpoint:
    """Resumability watermark (ROADMAP item 6): the highest round R such
    that every round 1..R scanned CLEAN, plus a rolling digest over those
    rounds' (round, signature) pairs and the checkpoint row's own
    signature hash.  A scheduled scan resumes at R+1 after re-reading row
    R and matching `sig_sha` — the ONLY check a resume performs:
    re-verifying the whole prefix would cost the O(chain) pass
    resumability exists to skip, so a resume trusts the prefix on the
    strength of that one row.  A truncated, restored-from-backup, or
    row-R-rewritten store fails the match and triggers a full rescan; a
    prefix rewritten UNDER an intact row R is caught by the next
    full-walk trigger (the startup pass never resumes).  The rolling
    `digest` is carried forward as an audit fingerprint of the vouched
    prefix — comparable across scans, replicas, and backups by
    operators/tooling — and is deliberately NOT re-derived on resume.
    `mode` records what the prefix was proven AT: a full-crypto scan may
    resume from a full checkpoint only (a linkage checkpoint never had
    its signatures verified); a linkage scan resumes from either."""

    round: int
    digest: str      # rolling sha256 hex over the clean prefix
    sig_sha: str     # sha256 hex of row `round`'s signature bytes
    mode: str = MODE_FULL

    def to_json(self) -> str:
        return json.dumps({"round": self.round, "digest": self.digest,
                           "sig_sha": self.sig_sha, "mode": self.mode},
                          sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "ScanCheckpoint":
        d = json.loads(text)
        return cls(round=int(d["round"]), digest=str(d["digest"]),
                   sig_sha=str(d["sig_sha"]),
                   mode=str(d.get("mode", MODE_FULL)))

    def covers(self, mode: str) -> bool:
        return self.mode == MODE_FULL or self.mode == mode


@dataclass(frozen=True)
class Finding:
    round: int
    kind: str
    detail: str = ""


@dataclass
class ScanReport:
    mode: str
    upto: int = 0
    scanned: int = 0
    verifier: str = "none"
    findings: List[Finding] = field(default_factory=list)
    # resumability: where this scan started (0 = genesis) and the new
    # watermark for the next scan (None when no clean prefix exists)
    resumed_from: int = 0
    checkpoint: Optional[ScanCheckpoint] = None

    @property
    def clean(self) -> bool:
        return not self.findings

    @property
    def faulty_rounds(self) -> List[int]:
        return sorted({f.round for f in self.findings})

    def rounds(self, kind: str) -> List[int]:
        return sorted({f.round for f in self.findings if f.kind == kind})

    @property
    def quarantinable_rounds(self) -> List[int]:
        """Rounds with a bad row on disk (missing rounds have nothing to
        delete, but still need re-fetching)."""
        return sorted({f.round for f in self.findings if f.kind != MISSING})

    def to_dict(self) -> dict:
        return {
            "mode": self.mode, "upto": self.upto, "scanned": self.scanned,
            "verifier": self.verifier, "clean": self.clean,
            "resumed_from": self.resumed_from,
            "findings": [{"round": f.round, "kind": f.kind,
                          "detail": f.detail} for f in self.findings],
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    def summary(self) -> str:
        if self.clean:
            return (f"clean: {self.scanned} beacons scanned up to round "
                    f"{self.upto} ({self.mode}/{self.verifier})")
        kinds = {}
        for f in self.findings:
            kinds[f.kind] = kinds.get(f.kind, 0) + 1
        parts = ", ".join(f"{k}={v}" for k, v in sorted(kinds.items()))
        return (f"{len(self.findings)} findings over {self.scanned} scanned "
                f"up to round {self.upto} ({parts})")


def verifier_kind(verifier) -> str:
    """host|device|none label for the metrics series.  Verifier classes
    self-describe via a `kind` attribute; anything unknown counts as host
    (it runs on this process's CPU by definition)."""
    if verifier is None:
        return "none"
    return getattr(verifier, "kind", "host")


class IntegrityScanner:
    """Scan one store against one chain identity (scheme + genesis seed).

    `verifier` must expose `verify_batch(rounds, sigs, prev_sigs) ->
    bool array` (BatchBeaconVerifier or HostBatchVerifier); it is only
    required for full-mode scans."""

    def __init__(self, store, scheme, verifier=None,
                 genesis_seed: Optional[bytes] = None,
                 chunk: int = DEFAULT_CHUNK, beacon_id: str = "default",
                 trigger: str = "startup"):
        self.store = store
        self.scheme = scheme
        self.verifier = verifier
        self.genesis_seed = genesis_seed
        self.chunk = max(1, chunk)
        self.beacon_id = beacon_id
        # metrics label: what started this scan (startup | scheduled |
        # manual) — a daemon rerunning the pass on integrity_scan_interval
        # or an operator's check-chain RPC must be distinguishable from
        # the boot-time pass in one scrape
        self.trigger = trigger

    # -- scanning ------------------------------------------------------------

    def scan(self, mode: str = MODE_FULL, upto: Optional[int] = None,
             progress: Optional[Callable[[int, int], None]] = None,
             resume: Optional[ScanCheckpoint] = None) -> ScanReport:
        """Walk rounds 1..upto (default: the store head) and report every
        integrity violation.  Emits per-chunk `progress(done, upto)` and
        the chain_integrity_* metrics counters.

        `resume` skips the already-proven clean prefix: the checkpoint
        row is re-read and its signature hash must match, else the scan
        silently falls back to a full walk (`report.resumed_from` says
        which happened).  Every scan emits a fresh `report.checkpoint`
        advancing the watermark over the rounds that scanned clean."""
        from ..metrics import integrity_beacons_scanned, integrity_corrupt_found
        if mode not in (MODE_LINKAGE, MODE_FULL):
            raise ValueError(f"unknown scan mode {mode!r}")
        if mode == MODE_FULL and self.verifier is None:
            raise ValueError("full-mode scan needs a verifier")
        vfy_kind = (verifier_kind(self.verifier)
                    if mode == MODE_FULL else "none")
        report = ScanReport(mode=mode, verifier=vfy_kind)

        try:
            head = self.store.last().round
        except ErrNoBeaconStored:
            # An empty store is only trivially clean when the caller did
            # not name a target: with an explicit `upto`, zero rows means
            # rounds 1..upto are MISSING (a fully truncated chain is the
            # at-rest disaster this scanner exists for) — fall through so
            # the tail-gap loop below flags them.
            head = 0
        report.upto = upto if upto is not None else head

        sig_len = self.scheme.sig_group.point_len
        anchor = self._anchor()                 # signature of round 0
        prev_sig: Optional[bytes] = anchor
        prev_round = 0
        digest = _DIGEST_SEED
        start_round = 1
        if resume is not None and resume.covers(mode) \
                and 1 <= resume.round <= report.upto:
            row = self._checkpoint_row(resume, sig_len)
            if row is not None:
                # clean prefix re-anchored: resume right after it
                prev_sig = row.signature
                prev_round = resume.round
                digest = resume.digest
                start_round = resume.round + 1
                report.resumed_from = resume.round
        buf: List[Beacon] = []
        buf_prevs: List[Optional[bytes]] = []
        unverified = set()      # rounds whose signature never reached verify
        unflushed = 0           # rounds examined since the last flush —
                                # counts malformed/unlinked rows too, which
                                # never enter the verify buffer

        def flush(done_round: int) -> None:
            nonlocal unflushed
            if buf:
                self._verify_chunk(report, buf, buf_prevs, mode)
                buf.clear()
                buf_prevs.clear()
            if unflushed:
                integrity_beacons_scanned.labels(
                    self.beacon_id, vfy_kind, self.trigger).inc(unflushed)
                unflushed = 0
            # watermark: commit only while the scan is STILL clean — the
            # first finding freezes the checkpoint at the previous flush,
            # so the next resume re-examines everything from there on
            if not report.findings and prev_round >= 1 \
                    and prev_sig is not None:
                report.checkpoint = ScanCheckpoint(
                    prev_round, digest,
                    hashlib.sha256(prev_sig).hexdigest(), mode)
            if progress is not None:
                progress(done_round, report.upto)

        cur = self.store.cursor()
        b = _cursor_seek(cur, start_round)
        while b is not None and b.round <= report.upto:
            r = b.round
            if r > prev_round + 1:
                for gap in range(prev_round + 1, r):
                    report.findings.append(Finding(gap, MISSING))
                # the walk anchor is lost across a hole; fall back to the
                # store's own previous_sig below when it has one
                prev_sig = None
            report.scanned += 1
            unflushed += 1
            sig = b.signature
            well_formed = len(sig) == sig_len
            if not well_formed:
                # torn write: the row exists but is not a point encoding
                unverified.add(r)
                report.findings.append(Finding(
                    r, MALFORMED,
                    f"signature is {len(sig)} bytes, want {sig_len}"))
            elif self.scheme.chained:
                if b.previous_sig is not None and prev_sig is not None \
                        and r == prev_round + 1 and b.previous_sig != prev_sig:
                    report.findings.append(Finding(
                        r, UNLINKED,
                        "stored previous_sig does not match round "
                        f"{r - 1}'s stored signature"))
                use_prev = prev_sig if prev_sig is not None else b.previous_sig
                if use_prev is None:
                    # hole below on a trimmed store: the digest cannot be
                    # rebuilt, so the round cannot be proven valid — flag
                    # it for re-fetch rather than vouch for it blindly
                    unverified.add(r)
                    report.findings.append(Finding(
                        r, UNLINKED,
                        "previous signature unavailable (hole below)"))
                else:
                    buf.append(b)
                    buf_prevs.append(use_prev)
            else:
                buf.append(b)
                buf_prevs.append(None)
            # a torn row can't anchor the next round's linkage
            prev_sig = sig if well_formed else None
            prev_round = r
            if well_formed:
                digest = _roll_digest(digest, r, sig)
            if len(buf) >= self.chunk:
                flush(r)
            b = cur.next()
        for gap in range(prev_round + 1, report.upto + 1):
            report.findings.append(Finding(gap, MISSING))
        flush(report.upto)

        self._reclassify_corrupt_anchors(report, unverified)
        for f in report.findings:
            integrity_corrupt_found.labels(self.beacon_id, f.kind,
                                           self.trigger).inc()
        report.findings.sort(key=lambda f: (f.round, f.kind))
        return report

    def _reclassify_corrupt_anchors(self, report: ScanReport,
                                    unverified: set) -> None:
        """A chained round that failed verification against an anchor that
        is itself corrupt or unproven is not PROVABLY invalid — its own
        bytes may be intact and only the round below rotted.  Report it as
        UNLINKED (unprovable; re-fetch to decide) instead of INVALID_SIG.
        Failures cascade upward only until the first passing round: a
        round that verifies against its stored anchor vouches for that
        anchor (the group signed exactly that digest)."""
        if not self.scheme.chained:
            return
        # rounds whose stored signature is corrupt or was never proven —
        # precomputed, so the INVALID_SIG→UNLINKED rewrite below doesn't
        # stop the cascade at the rewritten round
        unreliable = unverified | {
            f.round for f in report.findings if f.kind == INVALID_SIG}
        for i, f in enumerate(report.findings):
            if f.kind == INVALID_SIG and f.round - 1 in unreliable:
                report.findings[i] = Finding(
                    f.round, UNLINKED,
                    f"failed verification against round {f.round - 1}'s "
                    "signature, which is itself corrupt/unproven — not "
                    "provably invalid; re-fetch to decide")

    def _checkpoint_row(self, resume: ScanCheckpoint,
                        sig_len: int) -> Optional[Beacon]:
        """Re-read the checkpoint row and demand its signature hash still
        matches; None (= full rescan) when the row vanished, changed, or
        is malformed.  One point read buys skipping the whole prefix."""
        try:
            row = self.store.get(resume.round)
        except Exception:
            return None
        if row is None or len(row.signature) != sig_len:
            return None
        if hashlib.sha256(row.signature).hexdigest() != resume.sig_sha:
            return None
        return row

    def _anchor(self) -> Optional[bytes]:
        """Round 1's previous signature: the stored genesis beacon (round
        0 carries the genesis seed as its signature) or the configured
        genesis seed."""
        if not self.scheme.chained:
            return None
        try:
            return self.store.get(0).signature
        except Exception:
            return self.genesis_seed

    def _verify_chunk(self, report: ScanReport, chunk: Sequence[Beacon],
                      prevs: Sequence[Optional[bytes]], mode: str) -> None:
        if mode != MODE_FULL or not chunk:
            return
        ok = self.verifier.verify_batch(
            [b.round for b in chunk],
            [b.signature for b in chunk],
            list(prevs))
        for b, good in zip(chunk, ok):
            if not good:
                report.findings.append(Finding(b.round, INVALID_SIG))

    # -- quarantine ----------------------------------------------------------

    def quarantine(self, report_or_rounds) -> List[int]:
        """Remove the corrupt rows from serving; returns the rounds
        acted on.  Two-phase (ROADMAP item 6): rows are TOMBSTONED to the
        store's quarantine side table when the backend supports it — the
        bytes survive, so an intact-but-unprovable successor can be
        promoted back once its anchor is restored (`SyncManager.heal`'s
        promote pass) instead of re-downloaded.  Backends without a side
        table fall back to the old destructive delete.  Missing rounds
        are skipped (nothing on disk); the repair path re-fetches the
        union of quarantined + missing."""
        from ..metrics import integrity_quarantined
        if isinstance(report_or_rounds, ScanReport):
            rounds = report_or_rounds.quarantinable_rounds
        else:
            rounds = sorted(set(report_or_rounds))
        deleted = []
        tomb = getattr(self.store, "tombstone", None)
        for r in rounds:
            if tomb is not None:
                try:
                    if tomb(r):
                        deleted.append(r)
                        continue
                except Exception:
                    pass    # side table unavailable: destructive fallback
            try:
                self.store.get(r)
            except (ErrNoBeaconSaved, ErrNoBeaconStored):
                continue    # no row on disk (engines no-op missing
                            # deletes, which would inflate the metric)
            except Exception:
                pass        # row exists but won't materialize (e.g.
                            # ErrMissingPrevious on a strict store): delete
            try:
                self.store.delete(r)
                deleted.append(r)
            except Exception:
                pass
        if deleted:
            integrity_quarantined.labels(self.beacon_id).inc(len(deleted))
        return deleted


def _cursor_seek(cur, round_: int):
    """seek(1) that tolerates a stored genesis row at round 0."""
    b = cur.seek(round_)
    while b is not None and b.round < round_:
        b = cur.next()
    return b
