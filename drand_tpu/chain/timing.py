"""Round <-> time math with overflow guards (chain/time.go:18-63).

All times are UNIX seconds (ints); periods are positive int seconds.
Round 1 happens exactly at genesis; round 0 is the genesis beacon itself.
"""

import math

_MAX_INT64 = (1 << 63) - 1
_TIME_BUFFER = 1 << 36  # headroom below int64 max (time.go:9-11)
TIME_OF_ROUND_ERROR = _MAX_INT64 - _TIME_BUFFER


def time_of_round(period: int, genesis: int, round_: int) -> int:
    """UNIX time the given round should happen (time.go:18-39)."""
    if round_ == 0:
        return genesis
    if period < 0:
        return TIME_OF_ROUND_ERROR
    period_bits = math.log2(period + 1)
    if round_ >= ((1 << 64) - 1) >> (int(period_bits) + 2):
        return TIME_OF_ROUND_ERROR
    val = genesis + (round_ - 1) * period
    if val > _MAX_INT64 - _TIME_BUFFER:
        return TIME_OF_ROUND_ERROR
    return val


def next_round(now: int, period: int, genesis: int):
    """(next upcoming round, its UNIX time) (time.go:52-63)."""
    if now < genesis:
        return 1, genesis
    from_genesis = now - genesis
    next_r = from_genesis // period + 1
    next_t = genesis + (next_r * period)
    return next_r + 1, next_t


def current_round(now: int, period: int, genesis: int) -> int:
    """The round active at `now` (time.go:41-48)."""
    next_r, _ = next_round(now, period, genesis)
    if next_r <= 1:
        return next_r
    return next_r - 1
