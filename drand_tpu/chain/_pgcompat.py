"""Embedded DBAPI shim with the psycopg2 surface PostgresStore uses.

psycopg2 (and any C postgres driver) is absent from this environment, so
the reference's storage-matrix strategy (the same suite over bolt/memdb/
postgres, Makefile:61-75) would leave the postgres store code forever
unexecuted.  This shim implements the exact psycopg2 API subset the store
consumes — connect/autocommit/context-managers/%s placeholders — over
sqlite3, translating the few postgres-isms in the store's SQL.  Tests
inject it via `PostgresStore(driver=...)`; against a real server the store
uses psycopg2 unchanged, since the shim mimics psycopg2, not the reverse.
"""

import re
import sqlite3
import threading

from ..common import make_rlock

# Postgres string literal, including doubled-quote escapes ('it''s ok?' is
# ONE literal).  Shared with tests/test_pg_dialect.py so the dialect guard
# and the test pinning it cannot drift.
LITERAL_RE = r"'(?:[^']|'')*'"


def _translate(sql: str) -> str:
    # Dialect guard (VERDICT r3 #8): the store must emit PORTABLE postgres
    # SQL — psycopg2 placeholders only ('?' would pass here but fail on a
    # live server), and only upsert forms valid in BOTH dialects (postgres
    # requires a conflict target for DO UPDATE; bare DO NOTHING is fine).
    # strip string literals first before scanning for '?'
    if "?" in re.sub(LITERAL_RE, "", sql):
        raise AssertionError(
            "store SQL uses sqlite-style '?' placeholders; psycopg2 needs %s")
    if re.search(r"ON CONFLICT DO UPDATE", sql, re.IGNORECASE):
        raise AssertionError(
            "postgres requires a conflict target for ON CONFLICT DO UPDATE")
    sql = sql.replace("%s", "?")
    sql = re.sub(r"\bSERIAL PRIMARY KEY\b",
                 "INTEGER PRIMARY KEY AUTOINCREMENT", sql)
    sql = re.sub(r"\bBYTEA\b", "BLOB", sql)
    return sql


def _pgrow(row):
    """psycopg2 returns bytea columns as memoryview, not bytes — mimic it
    so store code that forgets a bytes() wrap fails HERE, in the matrix,
    instead of on a live server."""
    if row is None:
        return None
    return tuple(memoryview(v) if isinstance(v, bytes) else v for v in row)


class _Cursor:
    def __init__(self, conn: "_Connection"):
        self._conn = conn
        self._cur = conn._db.cursor()

    def execute(self, sql, args=()):
        sql = _translate(sql)
        with self._conn._lock:
            if args == () and sql.count(";") > 1:
                self._cur.executescript(sql)
            else:
                self._cur.execute(sql, tuple(args))
        return self

    def executemany(self, sql, seq_of_args):
        sql = _translate(sql)
        with self._conn._lock:
            self._cur.executemany(sql, [tuple(a) for a in seq_of_args])
        return self

    def fetchone(self):
        return _pgrow(self._cur.fetchone())

    def fetchall(self):
        return [_pgrow(r) for r in self._cur.fetchall()]

    def close(self):
        self._cur.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class _Connection:
    def __init__(self, dsn: str):
        # the "dsn" is a sqlite path here; ":memory:" or a file path both work
        path = dsn or ":memory:"
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._lock = make_rlock()
        self.autocommit = False

    def cursor(self):
        return _Cursor(self)

    def commit(self):
        # same lock as _Cursor.execute: a commit racing another thread's
        # half-finished executemany would otherwise sweep that thread's
        # rows into this transaction (psycopg2 connections promise
        # statement-level serialization; the shim must too)
        with self._lock:
            self._db.commit()

    def rollback(self):
        with self._lock:
            self._db.rollback()

    def close(self):
        with self._lock:
            self._db.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *exc):
        if exc_type is None:
            self.commit()
        else:
            self.rollback()
        return False


def connect(dsn: str) -> _Connection:
    return _Connection(dsn)
