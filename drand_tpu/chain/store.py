"""Store interface + cursor (chain/store.go:16-56,82-92).

Stores hold the beacon chain ordered by round.  All methods are synchronous;
engines guard their own state (the beacon engine calls them from multiple
threads).
"""

import struct
from abc import ABC, abstractmethod
from typing import Iterator, Optional

from .beacon import Beacon


def round_to_bytes(r: int) -> bytes:
    """8-byte fixed-length big-endian round key (store.go:82)."""
    return struct.pack(">Q", r)


def bytes_to_round(b: bytes) -> int:
    return struct.unpack(">Q", b)[0]


class Cursor(ABC):
    """Iterates beacons in ascending round order."""

    @abstractmethod
    def first(self) -> Optional[Beacon]: ...

    @abstractmethod
    def next(self) -> Optional[Beacon]: ...

    @abstractmethod
    def seek(self, round_: int) -> Optional[Beacon]: ...

    @abstractmethod
    def last(self) -> Optional[Beacon]: ...

    def __iter__(self) -> Iterator[Beacon]:
        b = self.first()
        while b is not None:
            yield b
            b = self.next()


class Store(ABC):
    """Beacon chain storage (chain/store.go:16-24)."""

    @abstractmethod
    def __len__(self) -> int: ...

    @abstractmethod
    def put(self, beacon: Beacon) -> None: ...

    @abstractmethod
    def last(self) -> Beacon:
        """Raises ErrNoBeaconStored when empty."""

    @abstractmethod
    def get(self, round_: int) -> Beacon:
        """Raises ErrNoBeaconSaved when absent."""

    @abstractmethod
    def cursor(self) -> Cursor: ...

    @abstractmethod
    def close(self) -> None: ...

    @abstractmethod
    def delete(self, round_: int) -> None: ...

    def save_to(self, fileobj) -> None:
        """Stream a backup of the full store (chain/store.go:24).

        Default: hexjson lines in round order (engines may override with a
        native snapshot)."""
        cur = self.cursor()
        for b in cur:
            fileobj.write(b.to_json() + b"\n")
