"""Store interface + cursor (chain/store.go:16-56,82-92).

Stores hold the beacon chain ordered by round.  All methods are synchronous;
engines guard their own state (the beacon engine calls them from multiple
threads).

Durability / consistency contract (every backend declares where it sits
via the `DURABILITY` class attribute; tests/test_chain.py pins the matrix):

  * ``volatile``   — contents die with the process (memdb).  `put` is
    atomic w.r.t. concurrent readers but nothing survives a crash.
  * ``crash-safe`` — a `put`/`put_many`/`delete` that returned has been
    committed through a journal and survives a PROCESS crash (sqlitedb
    under WAL).  With `synchronous=NORMAL` an OS/power failure may lose a
    tail of recently-committed transactions but can never tear one: the
    store reopens to some clean prefix of commit order, which the
    integrity scan + peer repair path re-fills.
  * ``server``     — durability is delegated to an external database's
    own guarantees (postgresdb).

Shared semantics all backends must honour (the cross-backend contract
suite enforces them):

  * `put` of an already-stored round is a no-op or an equal-content
    overwrite — never an error.  Callers that need replace-with-different
    -content (the repair path) must `delete` first.
  * `get`/`last` raise the Err* types below; they never return torn or
    half-written rows.
  * `put_many` writes the batch in ONE transaction where the engine has
    transactions: after a crash either none or a prefix-in-commit-order
    of the batch is visible, never an interleaving.  Caveat (memdb): the
    ring buffer has no transactions, so its `put_many` is per-put atomic
    only — a CONCURRENT READER can observe a partially-applied batch
    (crash atomicity is moot: the store is volatile).  Irrelevant for
    the append path (the ring ingests one head at a time) but a repair
    writer + an iterating reader on memdb can see a half-healed chain;
    re-scan after repair, as `heal` does, rather than assuming batch
    visibility.
  * Trimmed-format engines (sqlite, postgres) reconstruct `previous_sig`
    from round-1 when `require_previous=True`; if that prior row is
    absent they raise `ErrMissingPrevious` instead of fabricating a
    beacon that cannot re-verify.  Round 1 is exempt — its anchor is the
    genesis seed (chain metadata), not a stored row.
  * **Two-phase quarantine** (`tombstone`/`tombstoned`/`drop_tombstone`):
    a row flagged by the integrity scan is MOVED to a quarantine side
    table, not destroyed — it disappears from every normal read
    (`get`/`last`/cursors/`len`) but its bytes are retained, so an
    intact-but-unPROVABLE row (UNLINKED: its anchor rotted, not its own
    bytes) can be promoted back once the anchor is restored, instead of
    re-downloaded from peers.  Durable engines keep the side table on
    disk; the base implementation keeps it in process memory (volatile
    backends lose tombstones with the process, which costs at most a
    re-fetch).  `tombstone` of an absent round returns False;
    `drop_tombstone` is idempotent.
"""

import struct
from abc import ABC, abstractmethod
from typing import Iterator, Optional

from .beacon import Beacon


def round_to_bytes(r: int) -> bytes:
    """8-byte fixed-length big-endian round key (store.go:82)."""
    return struct.pack(">Q", r)


def bytes_to_round(b: bytes) -> int:
    return struct.unpack(">Q", b)[0]


class Cursor(ABC):
    """Iterates beacons in ascending round order."""

    @abstractmethod
    def first(self) -> Optional[Beacon]: ...

    @abstractmethod
    def next(self) -> Optional[Beacon]: ...

    @abstractmethod
    def seek(self, round_: int) -> Optional[Beacon]: ...

    @abstractmethod
    def last(self) -> Optional[Beacon]: ...

    def __iter__(self) -> Iterator[Beacon]:
        b = self.first()
        while b is not None:
            yield b
            b = self.next()


class Store(ABC):
    """Beacon chain storage (chain/store.go:16-24).

    See the module docstring for the durability/consistency contract that
    `DURABILITY` and `put_many` are part of."""

    DURABILITY = "volatile"

    @abstractmethod
    def __len__(self) -> int: ...

    @abstractmethod
    def put(self, beacon: Beacon) -> None: ...

    def put_many(self, beacons) -> None:
        """Store a batch of beacons; engines with transactions override
        this with a single-transaction write (see the module contract)."""
        for b in beacons:
            self.put(b)

    @abstractmethod
    def last(self) -> Beacon:
        """Raises ErrNoBeaconStored when empty."""

    @abstractmethod
    def get(self, round_: int) -> Beacon:
        """Raises ErrNoBeaconSaved when absent."""

    @abstractmethod
    def cursor(self) -> Cursor: ...

    @abstractmethod
    def close(self) -> None: ...

    @abstractmethod
    def delete(self, round_: int) -> None: ...

    # -- two-phase quarantine (see the module contract) ----------------------

    def tombstone(self, round_: int) -> bool:
        """Move `round_` to the quarantine side table; True when a row
        was moved.  Base implementation: in-memory side dict over
        get+delete (durable engines override with a real side table that
        also captures rows a strict `get` refuses to materialize)."""
        try:
            b = self.get(round_)
        except Exception:
            return False
        self.delete(round_)
        self._tombs()[round_] = Beacon(round=b.round, signature=b.signature,
                                       previous_sig=b.previous_sig)
        return True

    def tombstoned(self, round_: int) -> Optional[Beacon]:
        """The quarantined row's retained bytes, or None."""
        return self._tombs().get(round_)

    def drop_tombstone(self, round_: int) -> None:
        self._tombs().pop(round_, None)

    def _tombs(self) -> dict:
        # lazily attached: Store is an ABC whose subclasses don't all
        # call super().__init__()
        t = getattr(self, "_tombstone_rows", None)
        if t is None:
            t = self._tombstone_rows = {}
        return t

    def save_to(self, fileobj) -> None:
        """Stream a backup of the full store (chain/store.go:24).

        Default: hexjson lines in round order (engines may override with a
        native snapshot)."""
        cur = self.cursor()
        for b in cur:
            fileobj.write(b.to_json() + b"\n")
