"""Chain core: beacon type, chain info, round/time math, stores.

Mirrors the capability surface of the reference's `chain/` package root
(SURVEY.md §2.3) plus the embedded storage backends (§2.4), redesigned for
this framework: beacons are immutable dataclasses, stores are plain Python
classes with an abstract interface, and the durable engine is sqlite (the
in-tree analogue of the reference's boltdb single-bucket store).
"""

from .beacon import Beacon, genesis_beacon
from .errors import ErrMissingPrevious, ErrNoBeaconStored, ErrNoBeaconSaved
from .info import Info
from .integrity import (Finding, IntegrityScanner, ScanReport,
                        MODE_FULL, MODE_LINKAGE)
from .timing import (TIME_OF_ROUND_ERROR, current_round, next_round,
                     time_of_round)
from .store import Cursor, Store, round_to_bytes, bytes_to_round
from .memdb import MemDBStore
from .sqlitedb import SqliteStore

__all__ = [
    "Beacon", "genesis_beacon", "Info",
    "ErrNoBeaconStored", "ErrNoBeaconSaved", "ErrMissingPrevious",
    "Finding", "IntegrityScanner", "ScanReport", "MODE_FULL", "MODE_LINKAGE",
    "TIME_OF_ROUND_ERROR", "time_of_round", "current_round", "next_round",
    "Store", "Cursor", "round_to_bytes", "bytes_to_round",
    "MemDBStore", "SqliteStore",
]
