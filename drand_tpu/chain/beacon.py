"""Beacon: the unit of the randomness chain.

Reference: chain/beacon.go:15-65 (type + hexjson codec + randomness),
chain/store.go:95-101 (genesis beacon).
"""

import json
from dataclasses import dataclass, field
from typing import Optional

from ..crypto.schemes import randomness_from_signature


@dataclass(frozen=True)
class Beacon:
    """`{previous_sig, round, signature}`; signature is the BLS signature
    over the scheme's digest of (round, previous_sig)."""

    round: int
    signature: bytes
    previous_sig: Optional[bytes] = field(default=None)

    def randomness(self) -> bytes:
        """SHA-256 of the signature (chain/beacon.go:43)."""
        return randomness_from_signature(self.signature)

    # -- hexjson codec (storage value format, chain/beacon.go:32-39) --------

    def to_json(self) -> bytes:
        obj = {
            "PreviousSig": self.previous_sig.hex() if self.previous_sig else None,
            "Round": self.round,
            "Signature": self.signature.hex() if self.signature else None,
        }
        return json.dumps(obj, separators=(",", ":")).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "Beacon":
        obj = json.loads(data)
        prev = obj.get("PreviousSig")
        sig = obj.get("Signature")
        return cls(
            round=int(obj["Round"]),
            signature=bytes.fromhex(sig) if sig else b"",
            previous_sig=bytes.fromhex(prev) if prev else None,
        )

    def __str__(self):
        short = lambda b: b[:3].hex() if b else "nil"
        return (f"{{ round: {self.round}, sig: {short(self.signature)}, "
                f"prevSig: {short(self.previous_sig)} }}")


def genesis_beacon(genesis_seed: bytes) -> Beacon:
    """Round-0 beacon carrying the genesis seed as its signature
    (chain/store.go:95-101)."""
    return Beacon(round=0, signature=genesis_seed)
