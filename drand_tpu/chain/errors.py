"""Chain error types (chain/errors/errors.go)."""


class ErrNoBeaconStored(Exception):
    """Sync called too early: no beacon stored above the requested round."""


class ErrNoBeaconSaved(Exception):
    """Beacon not found in the database."""
