"""Chain error types (chain/errors/errors.go)."""


class ErrNoBeaconStored(Exception):
    """Sync called too early: no beacon stored above the requested round."""


class ErrNoBeaconSaved(Exception):
    """Beacon not found in the database."""


class ErrMissingPrevious(Exception):
    """A trimmed-format store was asked to reconstruct `previous_sig`
    (require_previous=True) but the prior round's row is absent — the chain
    on disk has a hole right below the requested round.  Raised instead of
    silently returning a beacon with a fabricated empty previous_sig, so
    callers (integrity scan, sync linkage checks) see the gap instead of a
    beacon that cannot possibly re-verify.  Round 1 is exempt: it anchors
    on the genesis SEED, which is chain metadata, not a stored row."""

    def __init__(self, round_: int):
        super().__init__(
            f"cannot reconstruct previous_sig for round {round_}: "
            f"round {round_ - 1} is missing from the store")
        self.round = round_
