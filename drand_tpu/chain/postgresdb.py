"""PostgreSQL chain store (reference: chain/postgresdb/pgdb/pgdb.go,
schema/schema.sql:1-29).

Gated dependency: psycopg2 is not part of this environment's baked-in set,
so the constructor raises a clear error when it's absent — the sqlite and
memdb backends cover the embedded cases (SURVEY.md §2.4).  The schema
mirrors the reference's trimmed format: `previous_sig` is not stored and is
reconstructed from round-1 on read for chained schemes (the migration-1.04
behavior, pgdb.go / chain/beacon.go:90-97).
"""

import threading

from ..common import make_rlock
from typing import Optional

from .beacon import Beacon
from .errors import ErrMissingPrevious, ErrNoBeaconSaved, ErrNoBeaconStored
from .store import Cursor, Store

_SCHEMA = """
CREATE TABLE IF NOT EXISTS beacons (
    beacon_id INT NOT NULL,
    round     BIGINT NOT NULL,
    signature BYTEA NOT NULL,
    PRIMARY KEY (beacon_id, round)
);
CREATE TABLE IF NOT EXISTS beacon_ids (
    id   SERIAL PRIMARY KEY,
    name TEXT UNIQUE NOT NULL
);
CREATE TABLE IF NOT EXISTS beacons_quarantine (
    beacon_id INT NOT NULL,
    round     BIGINT NOT NULL,
    signature BYTEA NOT NULL,
    PRIMARY KEY (beacon_id, round)
);
"""


class PostgresStore(Store):
    DURABILITY = "server"

    def __init__(self, dsn: str, beacon_id: str = "default",
                 require_previous: bool = False, driver=None):
        """`driver` is any module exposing psycopg2's `connect` (tests
        inject chain/_pgcompat.py so this store's CRUD/cursor code runs in
        the storage matrix without a postgres server)."""
        if driver is None:
            try:
                import psycopg2 as driver  # noqa: F811
            except ImportError as e:
                raise RuntimeError(
                    "PostgresStore requires psycopg2, which is not available "
                    "in this environment; use the sqlite or memdb backends "
                    "(core.Config.db_engine), or inject a DBAPI driver") from e
        self.conn = driver.connect(dsn)
        # reads must not pin an open transaction (VACUUM blockage /
        # idle_in_transaction timeouts on long-lived daemons)
        self.conn.autocommit = True
        # serializes writers: put_many drops the shared connection out of
        # autocommit for its batch transaction, and an unguarded put()
        # from another thread (beacon engine vs. repair thread) would be
        # swallowed into — and rolled back with — that batch
        self._write_lock = make_rlock()
        self.require_previous = require_previous
        with self.conn, self.conn.cursor() as cur:
            cur.execute(_SCHEMA)
            cur.execute(
                "INSERT INTO beacon_ids (name) VALUES (%s) "
                "ON CONFLICT (name) DO NOTHING", (beacon_id,))
            cur.execute("SELECT id FROM beacon_ids WHERE name = %s",
                        (beacon_id,))
            self.bid = cur.fetchone()[0]

    def __len__(self) -> int:
        with self.conn.cursor() as cur:
            cur.execute("SELECT count(*) FROM beacons WHERE beacon_id=%s",
                        (self.bid,))
            return cur.fetchone()[0]

    def put(self, beacon: Beacon) -> None:
        with self._write_lock, self.conn, self.conn.cursor() as cur:
            cur.execute(
                "INSERT INTO beacons (beacon_id, round, signature) "
                "VALUES (%s, %s, %s) ON CONFLICT DO NOTHING",
                (self.bid, beacon.round, beacon.signature))

    def put_many(self, beacons) -> None:
        """Batched insert in one transaction — same all-or-nothing
        TRANSACTIONAL contract as sqlite.  Conflict semantics differ
        within the store contract: ON CONFLICT DO NOTHING keeps the
        existing row (sqlite's REPLACE overwrites) — callers replacing
        content must delete first, as chain/store.py requires.
        The connection normally runs autocommit
        (see __init__); it is dropped into transactional mode for the
        batch so `with self.conn` really commits/rolls back atomically
        on a live server, not one row at a time."""
        with self._write_lock:
            auto = self.conn.autocommit
            self.conn.autocommit = False
            try:
                with self.conn, self.conn.cursor() as cur:
                    cur.executemany(
                        "INSERT INTO beacons (beacon_id, round, signature) "
                        "VALUES (%s, %s, %s) ON CONFLICT DO NOTHING",
                        [(self.bid, b.round, b.signature) for b in beacons])
            finally:
                self.conn.autocommit = auto

    def _fill_previous(self, round_: int, signature: bytes) -> Beacon:
        prev = None
        if self.require_previous and round_ > 0:
            with self.conn.cursor() as cur:
                cur.execute(
                    "SELECT signature FROM beacons "
                    "WHERE beacon_id=%s AND round=%s", (self.bid, round_ - 1))
                row = cur.fetchone()
            if row is None:
                # same round-1 carve-out as sqlite: the genesis seed is
                # not a stored row; deeper holes must raise
                if round_ > 1:
                    raise ErrMissingPrevious(round_)
            else:
                prev = bytes(row[0])
        return Beacon(round=round_, signature=signature, previous_sig=prev)

    def last(self) -> Beacon:
        with self.conn.cursor() as cur:
            cur.execute(
                "SELECT round, signature FROM beacons WHERE beacon_id=%s "
                "ORDER BY round DESC LIMIT 1", (self.bid,))
            row = cur.fetchone()
        if row is None:
            raise ErrNoBeaconStored("empty postgres store")
        return self._fill_previous(row[0], bytes(row[1]))

    def get(self, round_: int) -> Beacon:
        with self.conn.cursor() as cur:
            cur.execute(
                "SELECT signature FROM beacons "
                "WHERE beacon_id=%s AND round=%s", (self.bid, round_))
            row = cur.fetchone()
        if row is None:
            raise ErrNoBeaconSaved(f"round {round_} not in postgres store")
        return self._fill_previous(round_, bytes(row[0]))

    def delete(self, round_: int) -> None:
        with self._write_lock, self.conn, self.conn.cursor() as cur:
            cur.execute("DELETE FROM beacons WHERE beacon_id=%s AND round=%s",
                        (self.bid, round_))

    def tombstone(self, round_: int) -> bool:
        """Two-phase quarantine (chain/store.py contract): move the row
        to the side table so its bytes survive for a later promotion.
        The move runs in ONE real transaction — the connection normally
        runs autocommit (see __init__), under which `with self.conn` is
        a no-op, so like put_many it is dropped into transactional mode:
        a crash mid-move must never leave the corrupt row BOTH served
        from beacons and parked in quarantine."""
        with self._write_lock:
            auto = self.conn.autocommit
            self.conn.autocommit = False
            try:
                with self.conn, self.conn.cursor() as cur:
                    cur.execute("SELECT 1 FROM beacons WHERE beacon_id=%s "
                                "AND round=%s", (self.bid, round_))
                    if cur.fetchone() is None:
                        return False
                    # replace, not keep: a stale side-table row from an
                    # earlier quarantine must not shadow the bytes being
                    # moved now (sqlite's INSERT OR REPLACE, portably)
                    cur.execute(
                        "DELETE FROM beacons_quarantine"
                        " WHERE beacon_id=%s AND round=%s",
                        (self.bid, round_))
                    cur.execute(
                        "INSERT INTO beacons_quarantine"
                        " (beacon_id, round, signature)"
                        " SELECT beacon_id, round, signature FROM beacons"
                        " WHERE beacon_id=%s AND round=%s",
                        (self.bid, round_))
                    cur.execute("DELETE FROM beacons WHERE beacon_id=%s "
                                "AND round=%s", (self.bid, round_))
                    return True
            finally:
                self.conn.autocommit = auto

    def tombstoned(self, round_: int) -> Optional[Beacon]:
        with self.conn.cursor() as cur:
            cur.execute(
                "SELECT signature FROM beacons_quarantine"
                " WHERE beacon_id=%s AND round=%s", (self.bid, round_))
            row = cur.fetchone()
        if row is None:
            return None
        return Beacon(round=round_, signature=bytes(row[0]),
                      previous_sig=None)

    def drop_tombstone(self, round_: int) -> None:
        with self._write_lock, self.conn, self.conn.cursor() as cur:
            cur.execute(
                "DELETE FROM beacons_quarantine"
                " WHERE beacon_id=%s AND round=%s", (self.bid, round_))

    def close(self) -> None:
        self.conn.close()

    def cursor(self) -> Cursor:
        return _PgCursor(self)


class _PgCursor(Cursor):
    def __init__(self, store: PostgresStore):
        self.store = store
        self._round: Optional[int] = None

    def _row(self, sql, args):
        with self.store.conn.cursor() as cur:
            cur.execute(sql, args)
            row = cur.fetchone()
        if row is None:
            return None
        self._round = row[0]
        return self.store._fill_previous(row[0], bytes(row[1]))

    def first(self):
        return self._row(
            "SELECT round, signature FROM beacons WHERE beacon_id=%s "
            "ORDER BY round ASC LIMIT 1", (self.store.bid,))

    def next(self):
        if self._round is None:
            return self.first()
        return self._row(
            "SELECT round, signature FROM beacons WHERE beacon_id=%s AND "
            "round > %s ORDER BY round ASC LIMIT 1",
            (self.store.bid, self._round))

    def seek(self, round_: int):
        return self._row(
            "SELECT round, signature FROM beacons WHERE beacon_id=%s AND "
            "round >= %s ORDER BY round ASC LIMIT 1",
            (self.store.bid, round_))

    def last(self):
        return self._row(
            "SELECT round, signature FROM beacons WHERE beacon_id=%s "
            "ORDER BY round DESC LIMIT 1", (self.store.bid,))
