"""Chain info: the public root of trust clients pin (chain/info.go:19-72).

`hash()` is the canonical *chain hash*: SHA256(be32(period) || be64(genesis)
|| pubkey_bytes || genesis_seed [|| beacon_id if non-default]).  It is
constant for the life of a chain, across reshares.
"""

import hashlib
import json
import struct
from dataclasses import dataclass, field
from typing import Optional

from ..common import compare_beacon_ids, is_default_beacon_id


@dataclass(frozen=True)
class Info:
    public_key: bytes          # compressed point on the scheme's key group
    period: int                # seconds
    genesis_time: int          # UNIX seconds
    genesis_seed: bytes
    scheme: str
    beacon_id: str = field(default="")

    def hash(self) -> bytes:
        h = hashlib.sha256()
        h.update(struct.pack(">I", self.period))
        h.update(struct.pack(">q", self.genesis_time))
        h.update(self.public_key)
        h.update(self.genesis_seed)
        if not is_default_beacon_id(self.beacon_id):
            h.update(self.beacon_id.encode())
        return h.digest()

    def hash_string(self) -> str:
        return self.hash().hex()

    def equal(self, other: "Info") -> bool:
        return (self.genesis_time == other.genesis_time
                and self.period == other.period
                and self.public_key == other.public_key
                and self.genesis_seed == other.genesis_seed
                and compare_beacon_ids(self.beacon_id, other.beacon_id))

    # -- JSON codec (public REST /info format) ------------------------------

    def to_json(self) -> bytes:
        obj = {
            "public_key": self.public_key.hex(),
            "period": self.period,
            "genesis_time": self.genesis_time,
            "hash": self.hash_string(),
            "groupHash": self.genesis_seed.hex(),
            "schemeID": self.scheme,
            "metadata": {"beaconID": self.beacon_id or "default"},
        }
        return json.dumps(obj, separators=(",", ":")).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "Info":
        obj = json.loads(data)
        info = cls(
            public_key=bytes.fromhex(obj["public_key"]),
            period=int(obj["period"]),
            genesis_time=int(obj["genesis_time"]),
            genesis_seed=bytes.fromhex(obj["groupHash"]),
            scheme=obj.get("schemeID", "pedersen-bls-chained"),
            beacon_id=obj.get("metadata", {}).get("beaconID", ""),
        )
        want = obj.get("hash")
        if want and want != info.hash_string():
            raise ValueError("chain info hash mismatch")
        return info
