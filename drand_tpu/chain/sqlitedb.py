"""Durable embedded store on sqlite — the boltdb-equivalent engine.

Uses the reference's *trimmed* format (chain/boltdb/trimmed.go:20-322): only
(round, signature) is persisted; `previous_sig` is reconstructed from round-1
on read when the caller asks for it (chained schemes need it to re-derive the
digest; unchained schemes never do).  One table keyed by round — the direct
analogue of boltdb's single `beacons` bucket keyed by be64(round)
(chain/boltdb/store.go:24-329).
"""

import sqlite3
import threading

from ..common import make_rlock
from typing import Optional

from .beacon import Beacon
from .errors import ErrMissingPrevious, ErrNoBeaconSaved, ErrNoBeaconStored
from .store import Cursor, Store

# how long a writer waits on a competing writer's lock before SQLITE_BUSY
# surfaces as an exception (a second process — the doctor CLI — may hold
# the db while the daemon runs)
BUSY_TIMEOUT_MS = 5_000


class SqliteStore(Store):
    DURABILITY = "crash-safe"

    def __init__(self, path: str, require_previous: bool = False):
        """`require_previous`: reconstruct previous_sig on reads (set for
        chained schemes; chain/beacon.go:90-97 context flag).  When the
        prior round is absent, reads raise ErrMissingPrevious — see the
        chain/store.py contract.

        Durability discipline: WAL journal (readers never block the
        writer, a crash mid-commit rolls back to the last complete
        transaction) + `synchronous=NORMAL` (fsync on WAL checkpoints,
        not on every commit — a process crash loses nothing, an OS crash
        may lose a tail of recent commits but never tears one)."""
        self._conn = sqlite3.connect(path, check_same_thread=False,
                                     timeout=BUSY_TIMEOUT_MS / 1000.0)
        self._lock = make_rlock()
        self.require_previous = require_previous
        with self._lock:
            # pragmas first: the table create below should already ride WAL
            self._conn.execute(f"PRAGMA busy_timeout={BUSY_TIMEOUT_MS}")
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA synchronous=NORMAL")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS beacons ("
                " round INTEGER PRIMARY KEY,"
                " signature BLOB NOT NULL)")
            # two-phase quarantine side table (chain/store.py contract):
            # corrupt rows are MOVED here, not destroyed, so an
            # unprovable-but-intact row can be promoted back once its
            # anchor is restored instead of re-downloaded
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS quarantine ("
                " round INTEGER PRIMARY KEY,"
                " signature BLOB NOT NULL)")
            self._conn.commit()

    def __len__(self) -> int:
        with self._lock:
            (n,) = self._conn.execute("SELECT COUNT(*) FROM beacons").fetchone()
            return n

    def put(self, beacon: Beacon) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO beacons (round, signature) VALUES (?, ?)",
                (beacon.round, beacon.signature))
            self._conn.commit()

    def put_many(self, beacons) -> None:
        """Batched insert in ONE transaction: either the whole batch
        commits or none of it does (sync stores a verified chunk at a
        time — a crash must not leave half a chunk)."""
        with self._lock:
            try:
                self._conn.executemany(
                    "INSERT OR REPLACE INTO beacons (round, signature)"
                    " VALUES (?, ?)",
                    [(b.round, b.signature) for b in beacons])
            except BaseException:
                self._conn.rollback()
                raise
            self._conn.commit()

    def _fill_previous(self, round_: int, signature: bytes) -> Beacon:
        prev = None
        if self.require_previous and round_ > 0:
            # caller holds self._lock: get/last and the cursor all enter
            # with it held; this helper is never called bare
            # tpu-vet: disable=store
            row = self._conn.execute(
                "SELECT signature FROM beacons WHERE round = ?",
                (round_ - 1,)).fetchone()
            if row is None:
                # Round 1 anchors on the genesis SEED, which lives outside
                # the store — an absent round-0 row is normal, and the
                # caller supplies the seed.  Any other absent prior row is
                # a hole: raise instead of fabricating a beacon that can
                # never re-verify (chain/store.py contract).
                if round_ > 1:
                    raise ErrMissingPrevious(round_)
            else:
                prev = bytes(row[0])
        return Beacon(round=round_, signature=bytes(signature), previous_sig=prev)

    def last(self) -> Beacon:
        with self._lock:
            row = self._conn.execute(
                "SELECT round, signature FROM beacons"
                " ORDER BY round DESC LIMIT 1").fetchone()
            if row is None:
                raise ErrNoBeaconStored()
            return self._fill_previous(row[0], row[1])

    def get(self, round_: int) -> Beacon:
        with self._lock:
            row = self._conn.execute(
                "SELECT signature FROM beacons WHERE round = ?",
                (round_,)).fetchone()
            if row is None:
                raise ErrNoBeaconSaved()
            return self._fill_previous(round_, row[0])

    def delete(self, round_: int) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM beacons WHERE round = ?", (round_,))
            self._conn.commit()

    def tombstone(self, round_: int) -> bool:
        """Move the row to the quarantine table in ONE transaction — raw
        SQL on purpose: a strict-previous get() would refuse to
        materialize exactly the torn rows quarantine exists for."""
        with self._lock:
            try:
                cur = self._conn.execute(
                    "INSERT OR REPLACE INTO quarantine (round, signature)"
                    " SELECT round, signature FROM beacons WHERE round = ?",
                    (round_,))
                moved = cur.rowcount > 0
                if moved:
                    self._conn.execute(
                        "DELETE FROM beacons WHERE round = ?", (round_,))
            except BaseException:
                self._conn.rollback()
                raise
            self._conn.commit()
            return moved

    def tombstoned(self, round_: int) -> Optional[Beacon]:
        with self._lock:
            row = self._conn.execute(
                "SELECT signature FROM quarantine WHERE round = ?",
                (round_,)).fetchone()
        if row is None:
            return None
        return Beacon(round=round_, signature=bytes(row[0]),
                      previous_sig=None)

    def drop_tombstone(self, round_: int) -> None:
        with self._lock:
            self._conn.execute(
                "DELETE FROM quarantine WHERE round = ?", (round_,))
            self._conn.commit()

    def close(self) -> None:
        with self._lock:
            self._conn.close()

    def cursor(self) -> Cursor:
        return _SqliteCursor(self)

    def save_to(self, fileobj) -> None:
        """Native snapshot: the serialized sqlite image (BackupDatabase RPC,
        chain/store.go:24 SaveTo analogue).  Connection.serialize() needs
        Python 3.11; older runtimes snapshot through the online backup API
        into a temp file — same bytes, one extra disk round trip."""
        with self._lock:
            if hasattr(self._conn, "serialize"):
                # fold the WAL into the main image first, or commits since
                # the last checkpoint would be missing from the snapshot
                self._conn.execute("PRAGMA wal_checkpoint(TRUNCATE)")
                fileobj.write(self._conn.serialize())
                return
            import os
            import sqlite3
            import tempfile
            fd, tmp = tempfile.mkstemp(suffix=".db")
            os.close(fd)
            try:
                dst = sqlite3.connect(tmp)
                try:
                    self._conn.backup(dst)
                    dst.commit()
                finally:
                    dst.close()
                with open(tmp, "rb") as f:
                    fileobj.write(f.read())
            finally:
                os.unlink(tmp)


class _SqliteCursor(Cursor):
    def __init__(self, store: SqliteStore):
        self._store = store
        self._round: Optional[int] = None

    def _row_to_beacon(self, row) -> Optional[Beacon]:
        if row is None:
            self._round = None
            return None
        self._round = row[0]
        with self._store._lock:
            return self._store._fill_previous(row[0], row[1])

    def _query(self, sql, args=()):
        with self._store._lock:
            return self._store._conn.execute(sql, args).fetchone()

    def first(self) -> Optional[Beacon]:
        return self._row_to_beacon(self._query(
            "SELECT round, signature FROM beacons ORDER BY round ASC LIMIT 1"))

    def next(self) -> Optional[Beacon]:
        if self._round is None:
            return None
        return self._row_to_beacon(self._query(
            "SELECT round, signature FROM beacons WHERE round > ?"
            " ORDER BY round ASC LIMIT 1", (self._round,)))

    def seek(self, round_: int) -> Optional[Beacon]:
        return self._row_to_beacon(self._query(
            "SELECT round, signature FROM beacons WHERE round >= ?"
            " ORDER BY round ASC LIMIT 1", (round_,)))

    def last(self) -> Optional[Beacon]:
        return self._row_to_beacon(self._query(
            "SELECT round, signature FROM beacons ORDER BY round DESC LIMIT 1"))
