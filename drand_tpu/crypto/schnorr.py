"""Schnorr signatures over a scheme's key group (DKG packet auth).

The reference authenticates DKG packets with kyber/sign/schnorr over the
scheme's key group (crypto/schemes.go:81-87,103).  Scalar-only host math —
this path is control-plane, never batched.

sig = R_bytes || be32(s)  where  R = g^k,  c = SHA256(R || pub || msg) mod r,
s = k + c·x mod r.
"""

import hashlib
import secrets

from .host.params import R


def _challenge(group, R_bytes: bytes, pub_bytes: bytes, msg: bytes) -> int:
    h = hashlib.sha256()
    h.update(R_bytes)
    h.update(pub_bytes)
    h.update(msg)
    return int.from_bytes(h.digest(), "big") % R


def sign(group, secret: int, msg: bytes) -> bytes:
    g = group.curve
    k = secrets.randbelow(R - 1) + 1
    R_pt = g.mul(g.gen, k)
    R_bytes = group.to_bytes(R_pt)
    pub_bytes = group.to_bytes(g.mul(g.gen, secret))
    c = _challenge(group, R_bytes, pub_bytes, msg)
    s = (k + c * secret) % R
    return R_bytes + s.to_bytes(32, "big")


def verify(group, pub_bytes: bytes, msg: bytes, sig: bytes) -> bool:
    g = group.curve
    plen = group.point_len
    if len(sig) != plen + 32:
        return False
    R_bytes, s_bytes = sig[:plen], sig[plen:]
    try:
        R_pt = group.from_bytes(R_bytes)
        pub = group.from_bytes(pub_bytes)
    except (ValueError, AssertionError):
        return False
    s = int.from_bytes(s_bytes, "big")
    if s >= R:
        return False
    c = _challenge(group, R_bytes, pub_bytes, msg)
    # g^s == R + c·pub
    lhs = g.mul(g.gen, s)
    rhs = g.add(R_pt, g.mul(pub, c))
    return lhs == rhs
