"""Pedersen DKG + resharing: the distributed key generation state machine.

Re-creates the capability surface of the reference's `kyber/share/dkg`
protocol as used by drand (SURVEY.md §2.9; core/drand_beacon_control.go:333-529
builds `dkg.Config{FastSync: true, Nonce, Auth: DKGAuthScheme}` and drives it
over an echo-broadcast board).  The design is fresh and synchronous-first:

  * `DistKeyGenerator` is a **pure state machine** — `generate_deals()`,
    `process_deal_bundles()`, `process_response_bundles()`,
    `process_justification_bundles()` — with no threads, no clocks and no
    transport.  The phaser/board live above it (core/dkg orchestration),
    which makes the protocol deterministically testable on the fake-clock
    harness (the mitigation SURVEY.md §7 "hard part 5" prescribes).
  * FastSync semantics (dkg.Config.FastSync in the reference): every share
    holder responds with a status for EVERY dealer, success or complaint, so
    one response round suffices when nobody misbehaves.
  * Packets are authenticated with Schnorr over the scheme's key group
    (crypto/schemes.go:81-87,103), bound to the session nonce.
  * Deal shares are encrypted to the recipient with a static-DH stream
    cipher + HMAC (the reference uses ECIES from kyber; the wire format here
    is our own — there is no cross-implementation DKG interop requirement,
    only capability parity).

Resharing (core/drand_beacon_control.go:425-529): old-group members deal a
fresh polynomial whose constant term is their OLD share; the new share of
node i is the Lagrange combination (at 0, over the qualified old dealers) of
the dealt evaluations, so the collective public key — and therefore the
chain — is preserved while membership/threshold change.
"""

import hashlib
import hmac as _hmac
import secrets
import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from . import schnorr
from .host.params import R
from .schemes import Scheme
from .tbls import PriPoly, PriShare, PubPoly, _lagrange_coeff

_TAG_DEAL = b"drand-tpu:dkg:deal:v1"
_TAG_RESP = b"drand-tpu:dkg:resp:v1"
_TAG_JUST = b"drand-tpu:dkg:just:v1"
_TAG_ENC = b"drand-tpu:dkg:enc:v1"

STATUS_SUCCESS = 0
STATUS_COMPLAINT = 1


@dataclass(frozen=True)
class DkgNode:
    """One participant: DKG index + long-term public key on key_group."""
    index: int
    public: bytes


@dataclass
class DkgConfig:
    """Mirror of dkg.Config (drand_beacon_control.go:339-350 usage).

    Fresh DKG: leave old_nodes None; every new node is also a dealer.
    Reshare:   old_nodes holds the previous group (dealers), `share` the
               dealer's old PriShare, `public_coeffs` the previous public
               polynomial (required by everyone to pin dealer key shares).
    """
    scheme: Scheme
    longterm: int                      # our long-term secret scalar
    nonce: bytes                       # session binding (getNonce, control.go:1084)
    new_nodes: List[DkgNode]
    threshold: int
    old_nodes: Optional[List[DkgNode]] = None
    old_threshold: int = 0
    share: Optional[PriShare] = None             # reshare: our old share
    public_coeffs: Optional[List[bytes]] = None  # reshare: old PubPoly bytes


# ---------------------------------------------------------------------------
# Bundles (wire forms mirror protobuf/crypto/dkg/dkg.proto's Packet surface)
# ---------------------------------------------------------------------------

@dataclass
class Deal:
    share_index: int      # recipient's NEW-group index
    encrypted: bytes      # ciphertext || 32-byte HMAC


@dataclass
class DealBundle:
    dealer_index: int
    commits: List[bytes]  # commitments of the dealt polynomial (key_group)
    deals: List[Deal]
    session_id: bytes = b""
    signature: bytes = b""

    def hash(self, nonce: bytes) -> bytes:
        h = hashlib.sha256(_TAG_DEAL)
        h.update(nonce)
        h.update(struct.pack(">I", self.dealer_index))
        for c in self.commits:
            h.update(c)
        for d in sorted(self.deals, key=lambda d: d.share_index):
            h.update(struct.pack(">I", d.share_index))
            h.update(d.encrypted)
        return h.digest()


@dataclass
class Response:
    dealer_index: int
    status: int           # STATUS_SUCCESS | STATUS_COMPLAINT


@dataclass
class ResponseBundle:
    share_index: int      # responder's NEW-group index
    responses: List[Response]
    session_id: bytes = b""
    signature: bytes = b""

    def hash(self, nonce: bytes) -> bytes:
        h = hashlib.sha256(_TAG_RESP)
        h.update(nonce)
        h.update(struct.pack(">I", self.share_index))
        for r in sorted(self.responses, key=lambda r: r.dealer_index):
            h.update(struct.pack(">IB", r.dealer_index, r.status))
        return h.digest()


@dataclass
class Justification:
    share_index: int
    share: int            # the revealed plaintext share scalar


@dataclass
class JustificationBundle:
    dealer_index: int
    justifications: List[Justification]
    session_id: bytes = b""
    signature: bytes = b""

    def hash(self, nonce: bytes) -> bytes:
        h = hashlib.sha256(_TAG_JUST)
        h.update(nonce)
        h.update(struct.pack(">I", self.dealer_index))
        for j in sorted(self.justifications, key=lambda j: j.share_index):
            h.update(struct.pack(">I", j.share_index))
            h.update(j.share.to_bytes(32, "big"))
        return h.digest()


@dataclass
class DkgOutput:
    """Protocol result (kyber dkg.Result analogue, WaitDKG drand_beacon.go:182)."""
    qual: List[int]                 # qualified DEALER indices
    commits: List[bytes]            # final public polynomial (key_group points)
    share: Optional[PriShare]       # None for old nodes leaving at reshare

    def public_key(self) -> bytes:
        return self.commits[0]


# ---------------------------------------------------------------------------
# Deal-share encryption: static-DH stream cipher + HMAC
# ---------------------------------------------------------------------------

def _dh_key(scheme: Scheme, my_secret: int, their_pub: bytes,
            dealer_idx: int, holder_idx: int, nonce: bytes) -> bytes:
    g = scheme.key_group
    shared = g.curve.mul(g.from_bytes(their_pub), my_secret)
    h = hashlib.sha256(_TAG_ENC)
    h.update(g.to_bytes(shared))
    h.update(struct.pack(">II", dealer_idx, holder_idx))
    h.update(nonce)
    return h.digest()


def _stream_xor(key: bytes, data: bytes) -> bytes:
    out = bytearray()
    counter = 0
    while len(out) < len(data):
        out += hashlib.sha256(key + struct.pack(">I", counter)).digest()
        counter += 1
    return bytes(a ^ b for a, b in zip(data, out))


def _encrypt_share(scheme, dealer_secret, holder_pub, dealer_idx, holder_idx,
                   nonce, share: int) -> bytes:
    key = _dh_key(scheme, dealer_secret, holder_pub, dealer_idx, holder_idx, nonce)
    ct = _stream_xor(key, share.to_bytes(32, "big"))
    return ct + _hmac.new(key, ct, hashlib.sha256).digest()


def _decrypt_share(scheme, holder_secret, dealer_pub, dealer_idx, holder_idx,
                   nonce, blob: bytes) -> Optional[int]:
    if len(blob) != 64:
        return None
    ct, mac = blob[:32], blob[32:]
    key = _dh_key(scheme, holder_secret, dealer_pub, dealer_idx, holder_idx, nonce)
    if not _hmac.compare_digest(mac, _hmac.new(key, ct, hashlib.sha256).digest()):
        return None
    return int.from_bytes(_stream_xor(key, ct), "big") % R


# ---------------------------------------------------------------------------
# The state machine
# ---------------------------------------------------------------------------

class DkgError(Exception):
    pass


class DistKeyGenerator:
    """One node's view of a DKG/reshare session.

    Drive it: generate_deals() → (exchange) → process_deal_bundles() →
    (exchange) → process_response_bundles() → finished, or → (exchange
    justifications) → process_justification_bundles().
    """

    def __init__(self, cfg: DkgConfig):
        self.cfg = cfg
        self.scheme = cfg.scheme
        self.is_resharing = cfg.old_nodes is not None
        self.dealers = cfg.old_nodes if self.is_resharing else cfg.new_nodes
        self.holders = cfg.new_nodes
        g = self.scheme.key_group
        my_pub = g.to_bytes(g.curve.mul(g.curve.gen, cfg.longterm))
        self.dealer_index = next(
            (n.index for n in self.dealers if n.public == my_pub), None)
        self.holder_index = next(
            (n.index for n in self.holders if n.public == my_pub), None)
        if self.dealer_index is None and self.holder_index is None:
            raise DkgError("our key is in neither the dealer nor holder set")
        if self.is_resharing:
            if not cfg.public_coeffs:
                raise DkgError("resharing requires the old public polynomial")
            self.old_pub = PubPoly.from_bytes(g, b"".join(cfg.public_coeffs))
            if self.dealer_index is not None and cfg.share is None:
                raise DkgError("resharing dealer requires its old share")
        else:
            self.old_pub = None
        # dealer state
        self._poly: Optional[PriPoly] = None
        self._my_bundle: Optional[DealBundle] = None
        # received state
        self._deal_bundles: Dict[int, DealBundle] = {}
        self._my_shares: Dict[int, int] = {}      # dealer idx -> plaintext share
        self._valid_dealers: set = set()           # produced a verifiable bundle
        self._complaints: Dict[int, set] = {}      # dealer idx -> {holder idx}
        self._responses_seen: set = set()
        self.output: Optional[DkgOutput] = None

    # -- phase 1: deals ------------------------------------------------------

    def generate_deals(self) -> Optional[DealBundle]:
        """Deal our polynomial to every share holder (None if not a dealer)."""
        if self.dealer_index is None:
            return None
        if self.is_resharing:
            # constant term = our old share ⇒ public key is preserved
            self._poly = PriPoly.random(self.cfg.threshold,
                                        secret=self.cfg.share.value)
        else:
            self._poly = PriPoly.random(self.cfg.threshold)
        pub = self._poly.commit(self.scheme.key_group)
        commits = [self.scheme.key_group.to_bytes(c) for c in pub.commits]
        deals = []
        for n in self.holders:
            share = self._poly.eval(n.index).value
            deals.append(Deal(n.index, _encrypt_share(
                self.scheme, self.cfg.longterm, n.public,
                self.dealer_index, n.index, self.cfg.nonce, share)))
        bundle = DealBundle(self.dealer_index, commits, deals,
                            session_id=self.cfg.nonce)
        bundle.signature = schnorr.sign(self.scheme.key_group,
                                        self.cfg.longterm,
                                        bundle.hash(self.cfg.nonce))
        self._my_bundle = bundle
        return bundle

    def _dealer(self, idx: int) -> Optional[DkgNode]:
        return next((n for n in self.dealers if n.index == idx), None)

    def _check_bundle_sig(self, bundle, sender: DkgNode) -> bool:
        return schnorr.verify(self.scheme.key_group, sender.public,
                              bundle.hash(self.cfg.nonce), bundle.signature)

    def process_deal_bundles(self, bundles: Sequence[DealBundle]
                             ) -> Optional[ResponseBundle]:
        """Verify every dealer's bundle; produce our FastSync response bundle
        (a status per dealer).  Returns None if we hold no share.

        Committee scale: the two O(n·t) scalar-mul loops — the reshare
        constant-term pin and the share-vs-commitment check — run as ONE
        batched device dispatch each once the session crosses
        `dkg_device.MIN_N` lanes (verdicts bit-identical to the host
        loops, which remain the fallback)."""
        staged = []     # (bundle, dealer, pub) past the cheap checks
        staged_dealers = set()      # in-batch dedup: the FIRST bundle per
        # dealer wins, exactly as when insertion happened inside the loop
        # (an equivocating dealer must not get bundle B stored while the
        # share was decrypted from bundle A)
        for b in bundles:
            dealer = self._dealer(b.dealer_index)
            if dealer is None or b.dealer_index in self._deal_bundles \
                    or b.dealer_index in staged_dealers:
                continue
            if len(b.commits) != self.cfg.threshold:
                continue
            if not self._check_bundle_sig(b, dealer):
                continue
            try:
                pub = PubPoly.from_bytes(self.scheme.key_group,
                                         b"".join(b.commits))
            except (ValueError, AssertionError):
                continue
            staged.append((b, dealer, pub))
            staged_dealers.add(b.dealer_index)
        if self.is_resharing and staged:
            # dealer's constant-term commitment must equal its public old
            # share g^{s_d} = oldPubPoly.eval(d) — otherwise it is trying
            # to change the collective key
            ok = self._constant_terms_ok(staged)
            staged = [entry for entry, good in zip(staged, ok) if good]
        candidates = []     # (bundle, pub, decrypted share)
        for b, dealer, pub in staged:
            self._deal_bundles[b.dealer_index] = b
            self._valid_dealers.add(b.dealer_index)
            if self.holder_index is not None:
                share = self._decrypt_own(b, dealer)
                if share is not None:
                    candidates.append((b, pub, share))
        self._adopt_matching_shares(candidates)
        if self.holder_index is None:
            return None
        responses = []
        for d in self.dealers:
            ok = d.index in self._my_shares
            responses.append(Response(
                d.index, STATUS_SUCCESS if ok else STATUS_COMPLAINT))
        rb = ResponseBundle(self.holder_index, responses,
                            session_id=self.cfg.nonce)
        rb.signature = schnorr.sign(self.scheme.key_group, self.cfg.longterm,
                                    rb.hash(self.cfg.nonce))
        return rb

    def _constant_terms_ok(self, staged) -> list:
        """Per-bundle reshare pin verdicts; one device dispatch above the
        lane threshold, else the host loop."""
        from . import dkg_device
        g = self.scheme.key_group
        if dkg_device.use_device(len(staged)):
            claimed = [g.from_bytes(b.commits[0]) for b, _, _ in staged]
            return dkg_device.constant_terms_match(
                g, list(self.old_pub.commits),
                [b.dealer_index for b, _, _ in staged], claimed)
        return [g.to_bytes(self.old_pub.eval(b.dealer_index)) == b.commits[0]
                for b, _, _ in staged]

    def _decrypt_own(self, b: DealBundle, dealer: DkgNode) -> Optional[int]:
        deal = next((d for d in b.deals if d.share_index == self.holder_index),
                    None)
        if deal is None:
            return None
        return _decrypt_share(self.scheme, self.cfg.longterm, dealer.public,
                              b.dealer_index, self.holder_index,
                              self.cfg.nonce, deal.encrypted)

    def _adopt_matching_shares(self, candidates) -> None:
        """Adopt every decrypted share that matches its dealer's
        commitments — the O(n·t) hot loop of a large DKG, batched to one
        dispatch for all n dealers' bundles on the device path."""
        if not candidates:
            return
        from . import dkg_device
        if dkg_device.use_device(len(candidates)):
            ok = dkg_device.verify_shares(
                self.scheme.key_group,
                [list(pub.commits) for _, pub, _ in candidates],
                self.holder_index, [s for _, _, s in candidates])
        else:
            ok = [self._share_matches(pub, self.holder_index, s)
                  for _, pub, s in candidates]
        for (b, _, share), good in zip(candidates, ok):
            if good:
                self._my_shares[b.dealer_index] = share

    def _share_matches(self, pub: PubPoly, holder_idx: int, share: int) -> bool:
        g = self.scheme.key_group.curve
        return g.mul(g.gen, share) == pub.eval(holder_idx)

    # -- phase 2: responses --------------------------------------------------

    def process_response_bundles(self, bundles: Sequence[ResponseBundle]
                                 ) -> Tuple[Optional[DkgOutput],
                                            Optional[JustificationBundle]]:
        """Tally complaints.  If none (and enough dealers) the DKG finishes
        here (FastSync happy path); otherwise dealers under complaint emit a
        justification bundle revealing the disputed plaintext shares."""
        holder_ids = {n.index for n in self.holders}
        for rb in bundles:
            if rb.share_index not in holder_ids:
                continue
            if rb.share_index in self._responses_seen:
                continue
            holder = next(n for n in self.holders
                          if n.index == rb.share_index)
            if not self._check_bundle_sig(rb, holder):
                continue
            self._responses_seen.add(rb.share_index)
            for r in rb.responses:
                if r.status == STATUS_COMPLAINT:
                    self._complaints.setdefault(r.dealer_index,
                                                set()).add(rb.share_index)
        # dealers that never produced a valid bundle can't be justified; only
        # complaints against valid dealers keep the justification phase alive
        pending = {d: hs for d, hs in self._complaints.items()
                   if d in self._valid_dealers and hs}
        if not pending:
            self.output = self._finalize()
            return self.output, None
        just = None
        if self.dealer_index is not None and self.dealer_index in pending:
            justs = [Justification(h, self._poly.eval(h).value)
                     for h in sorted(pending[self.dealer_index])]
            just = JustificationBundle(self.dealer_index, justs,
                                       session_id=self.cfg.nonce)
            just.signature = schnorr.sign(self.scheme.key_group,
                                          self.cfg.longterm,
                                          just.hash(self.cfg.nonce))
        return None, just

    # -- phase 3: justifications --------------------------------------------

    def process_justification_bundles(self, bundles: Sequence[JustificationBundle]
                                      ) -> DkgOutput:
        """Resolve complaints: a revealed share that matches the dealer's
        commitments dismisses the complaint (and the complainer adopts it);
        anything else disqualifies the dealer."""
        for jb in bundles:
            dealer = self._dealer(jb.dealer_index)
            if dealer is None or jb.dealer_index not in self._valid_dealers:
                continue
            if not self._check_bundle_sig(jb, dealer):
                continue
            b = self._deal_bundles[jb.dealer_index]
            pub = PubPoly.from_bytes(self.scheme.key_group, b"".join(b.commits))
            open_complaints = self._complaints.get(jb.dealer_index, set())
            for j in jb.justifications:
                if j.share_index not in open_complaints:
                    continue
                if self._share_matches(pub, j.share_index, j.share % R):
                    open_complaints.discard(j.share_index)
                    if j.share_index == self.holder_index:
                        self._my_shares[jb.dealer_index] = j.share % R
        self.output = self._finalize()
        return self.output

    # -- finalization --------------------------------------------------------

    def _qual(self) -> List[int]:
        return sorted(d for d in self._valid_dealers
                      if not self._complaints.get(d))

    def _finalize(self) -> DkgOutput:
        qual = self._qual()
        need = self.cfg.old_threshold if self.is_resharing else self.cfg.threshold
        if len(qual) < need:
            raise DkgError(f"too few qualified dealers: {len(qual)} < {need}")
        g = self.scheme.key_group
        curve = g.curve
        from . import dkg_device
        if self.is_resharing:
            # Lagrange-combine the dealt polynomials at the OLD indices so
            # the constant term interpolates back to the collective secret;
            # every node truncates the sorted QUAL the same way, so all
            # nodes combine the same dealer subset.
            qual = qual[:need]
            lams = {d: _lagrange_coeff(qual, d) for d in qual}
            if dkg_device.use_device(len(qual)):
                # batched Lagrange recovery of the public polynomial:
                # ONE dispatch over |qual| x t lanes instead of the
                # host's |qual|·t sequential scalar muls
                matrix = [[g.from_bytes(c)
                           for c in self._deal_bundles[d].commits]
                          for d in qual]
                combined = dkg_device.combine_commits(
                    g, matrix, [lams[d] for d in qual])
                commits = [g.to_bytes(c) for c in combined]
            else:
                commits = []
                for j in range(self.cfg.threshold):
                    acc = None
                    for d in qual:
                        c = g.from_bytes(self._deal_bundles[d].commits[j])
                        acc = curve.add(acc, curve.mul(c, lams[d]))
                    commits.append(g.to_bytes(acc))
            share = None
            if self.holder_index is not None:
                missing = [d for d in qual if d not in self._my_shares]
                if missing:
                    raise DkgError(f"missing shares from dealers {missing}")
                val = sum(lams[d] * self._my_shares[d] for d in qual) % R
                share = PriShare(self.holder_index, val)
        else:
            if dkg_device.use_device(len(qual)):
                matrix = [[g.from_bytes(c)
                           for c in self._deal_bundles[d].commits]
                          for d in qual]
                commits_pts = dkg_device.combine_commits(g, matrix)
            else:
                commits_pts = [None] * self.cfg.threshold
                for d in qual:
                    for j, c in enumerate(self._deal_bundles[d].commits):
                        commits_pts[j] = curve.add(commits_pts[j],
                                                   g.from_bytes(c))
            commits = [g.to_bytes(c) for c in commits_pts]
            share = None
            if self.holder_index is not None:
                missing = [d for d in qual if d not in self._my_shares]
                if missing:
                    raise DkgError(f"missing shares from dealers {missing}")
                val = sum(self._my_shares[d] for d in qual) % R
                share = PriShare(self.holder_index, val)
        return DkgOutput(qual=qual, commits=commits, share=share)
