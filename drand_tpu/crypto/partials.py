"""Batched threshold-partial verification on TPU (BASELINE config 3).

The reference verifies each incoming partial with two pairings on the CPU
(`tbls.VerifyPartial`, chain/beacon/node.go:150) — O(n) pairings per round
per node, its hottest call site.  Here a whole (rounds x slots) block is
collapsed into ONE Miller product via a per-signer random linear combination:

    forall (r,j):  e(-g1, S_rj) · e(pk_idx(rj), H_r) == 1
    ==>  e(-g1, sum_rj c_rj·S_rj) · prod_i e(pk_i, T_i) == 1
         with  T_i = sum over slots with idx==i of c_rj·H_r

sound except with probability ~2^-SECURITY_BITS.  pk_i = PubPoly.eval(i) is
evaluated once per group on the host (the polynomial is tiny); the Miller
product has (#distinct signers + 1) pairs.  On RLC failure, exact per-slot
pairing checks locate invalid partials.

Occupancy fast path (ISSUE 10, ported from the r4 G1/G2 verify machinery):

  * the host no longer decompresses partials point by point — wire bytes
    are split into x-limb arrays with pure numpy (`batch._wire_parse`) and
    the y recovery rides the SAME single sqrt_ratio pow scan as the two
    SSWU hash maps (`ops/h2c.g2_decompress_and_hash`; scans cost per
    step, not per lane — the G1/G2 free lunch, now on partials);
  * the RLC MSM uses the split-sampled GLV coefficients: ψ-split 4-way on
    G2 (32-step joint ladder) and φ-split 2-way on G1 (64-step), exactly
    like crypto/batch.py's verify pipelines, instead of a 128-step
    per-bit ladder.  Soundness is unchanged: coefficients are sampled
    directly in split form (injective; see batch._rlc_scalars).

Slot layout: callers pass ragged per-round partial lists (wire format:
be16(index) || sig); rows are padded to the widest row and masked.
"""

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from . import tbls as HT
from .batch import (_NEG_G1, _NEG_G2, _count_dispatch, _device_rlc_bits,
                    _gen_sub, _rlc_keys, _wire_parse, _GEN_JAC_G1,
                    _GEN_JAC_G2, _GEN_SIGN_G1, _GEN_SIGN_G2, _GEN_X_G1,
                    _GEN_X_G2, FRONT_DIGEST, FRONT_FIELDS, _h2f_front,
                    h2f_device_default)
from .schemes import Scheme, GroupG2
from ..ops import curve as DC
from ..ops import h2c as DH
from ..ops import limbs as L
from ..ops import pairing as DP
from ..ops import sha256 as SHA


def _tile_rounds(tree_pt, k):
    """(r, ...) point -> (r*k, ...): slot (r, j) sees round r's value."""
    return jax.tree.map(lambda t: jnp.repeat(t, k, axis=0), tree_pt)


def _masked_sums(curve, pts, onehot):
    """Per-signer sums: T_i = sum over slots with onehot[i]==1 (complete
    adds; masked-out slots become infinity).  Returns a stacked point
    tree with leading axis n_nodes.

    One `lax.scan` over the signer axis: the compiled graph contains a
    SINGLE masked sum tree instead of n_nodes unrolled copies.  The
    unrolled form made this the slowest-compiling program in the whole
    framework (>40 min cold XLA:CPU at 13 signers — it blew the bench's
    per-config watchdog on an idle core); the scan form is numerically
    identical and costs one extra sequential step per signer at runtime."""
    inf = curve.infinity((onehot.shape[1],))

    def body(carry, row):
        sel = curve._select(row == 1, pts, inf)
        return carry, curve.sum_points(sel)

    _, ts = jax.lax.scan(body, 0, onehot)
    return ts


def _prepend_point(single, stacked):
    """Prepend one unbatched point to a (k, ...)-stacked point tree."""
    return jax.tree.map(lambda s, t: jnp.concatenate([s[None], t], 0),
                        single, stacked)


def _partials_verdict(sub_ok, ok, valid):
    """Fused device scalar: RLC ok AND every valid slot's decompression +
    subgroup check ok (a slot that failed device decompression has a
    generator substitute and a live coefficient, so the RLC itself also
    fails — the fallback then localizes it)."""
    return ok & jnp.all(sub_ok | ~valid.astype(bool))


# lane concatenation shares ops/curve's helper (the psi-lane layout there
# is exactly this operation)
_cat = DC._cat_lanes


def _rlc_partials_run_g2sig(sig_x, sign, u0, u1, keys, valid, onehot,
                            pk_sel, neg_g1_aff):
    """sigs on G2, pks on G1.  sig_x: ((rk,24),(rk,24)) wire x limbs;
    sign: (rk,) flags; u0/u1: (r,) fp2; keys: (2, 2) threefry keys;
    valid: (rk,) slot mask; onehot: (p, rk); pk_sel: ((p,24),(p,24)) G1
    affine.  Front end: ONE Fp2 sqrt_ratio scan fuses slot decompression
    + both SSWU maps; MSM: ψ-split 4-way GLV over [S, ψS, H, ψH] lanes
    (32-step joint ladder, coefficients sampled as base-x quarters)."""
    rk = onehot.shape[1]
    r = u0[0].shape[0]
    k = rk // r
    sig_jac, parse_ok, hm_r = DH.g2_decompress_and_hash(
        sig_x[0], sig_x[1], sign, u0, u1)
    sig_jac = _gen_sub(DC.G2_DEV, _GEN_JAC_G2, sig_jac, parse_ok)
    sub_ok = DC.g2_in_subgroup(sig_jac) & parse_ok
    hm = _tile_rounds(hm_r, k)
    b0, b1, b2, b3 = _device_rlc_bits(keys, valid, split=4)
    # lane order [S, ψS, H, ψH]: the same coefficient c_rj multiplies
    # S_rj and H_r (the RLC identity), so both halves share the quarters
    base = _cat(sig_jac, DC.g2_psi(sig_jac), hm, DC.g2_psi(hm))
    bl = jnp.concatenate([b0, b1, b0, b1], axis=1)
    bh = jnp.concatenate([b2, b3, b2, b3], axis=1)
    mult = DC.g2_glv_msm_terms(base, bl, bh)
    s_sum = DC.G2_DEV.sum_points(jax.tree.map(lambda t: t[:2 * rk], mult))
    ch = jax.tree.map(lambda t: t[2 * rk:], mult)
    onehot2 = jnp.concatenate([onehot, onehot], axis=1)
    ts = _masked_sums(DC.G2_DEV, ch, onehot2)
    qx_all, qy_all, _ = DC.G2_DEV.to_affine(_prepend_point(s_sum, ts))
    px = jnp.concatenate([neg_g1_aff[0][None], pk_sel[0]], axis=0)
    py = jnp.concatenate([neg_g1_aff[1][None], pk_sel[1]], axis=0)
    ok = DP.paired_product_is_one(px, py, (qx_all, qy_all),
                                  onehot.shape[0] + 1)
    return sub_ok, _partials_verdict(sub_ok, ok, valid)


def _rlc_partials_run_g1sig(sig_x, sign, u0, u1, keys, valid, onehot,
                            pk_sel, neg_g2_aff):
    """sigs on G1, pks on G2 (short-sig scheme): fused decompression via
    the shared (p-3)/4 scan + φ-split 2-way GLV (64-step joint ladder)."""
    rk = onehot.shape[1]
    r = u0.shape[0]
    k = rk // r
    sig_jac, parse_ok, hm_r = DH.g1_decompress_and_hash(sig_x, sign, u0, u1)
    sig_jac = _gen_sub(DC.G1_DEV, _GEN_JAC_G1, sig_jac, parse_ok)
    sub_ok = DC.g1_in_subgroup(sig_jac) & parse_ok
    hm = _tile_rounds(hm_r, k)
    b0, b1 = _device_rlc_bits(keys, valid, split=2)
    both = _cat(sig_jac, hm)
    bits0 = jnp.concatenate([b0, b0], axis=1)
    bits1 = jnp.concatenate([b1, b1], axis=1)
    mult = DC.g1_glv_msm_terms(both, bits0, bits1)
    s_sum = DC.G1_DEV.sum_points(jax.tree.map(lambda t: t[:rk], mult))
    ch = jax.tree.map(lambda t: t[rk:], mult)
    ts = _masked_sums(DC.G1_DEV, ch, onehot)
    px_all, py_all, _ = DC.G1_DEV.to_affine(_prepend_point(s_sum, ts))
    qx = jax.tree.map(lambda a, b: jnp.concatenate([a[None], b], axis=0),
                      neg_g2_aff[0], pk_sel[0])
    qy = jax.tree.map(lambda a, b: jnp.concatenate([a[None], b], axis=0),
                      neg_g2_aff[1], pk_sel[1])
    ok = DP.paired_product_is_one(px_all, py_all, (qx, qy),
                                  onehot.shape[0] + 1)
    return sub_ok, _partials_verdict(sub_ok, ok, valid)


def _exact_partials_run_g2sig(sig_x, sign, u0, u1, pk_slot, neg_g1_aff):
    """Per-slot exact checks with per-slot pubkeys (fallback path); the
    decompression rides the same fused front end as the RLC pass."""
    rk = sig_x[0].shape[0]
    r = u0[0].shape[0]
    k = rk // r
    sig_jac, parse_ok, hm_r = DH.g2_decompress_and_hash(
        sig_x[0], sig_x[1], sign, u0, u1)
    sig_jac = _gen_sub(DC.G2_DEV, _GEN_JAC_G2, sig_jac, parse_ok)
    sub_ok = DC.g2_in_subgroup(sig_jac) & parse_ok
    hm = _tile_rounds(hm_r, k)
    sx, sy, s_inf = DC.G2_DEV.to_affine(sig_jac)
    hx, hy, _ = DC.G2_DEV.to_affine(hm)
    px = jnp.stack([jnp.broadcast_to(neg_g1_aff[0], (rk, L.NLIMB)), pk_slot[0]])
    py = jnp.stack([jnp.broadcast_to(neg_g1_aff[1], (rk, L.NLIMB)), pk_slot[1]])
    qx = jax.tree.map(lambda a, b: jnp.stack([a, b]), sx, hx)
    qy = jax.tree.map(lambda a, b: jnp.stack([a, b]), sy, hy)
    ok = DP.paired_product_is_one(px, py, (qx, qy), 2)
    return sub_ok & ~s_inf & ok


def _exact_partials_run_g1sig(sig_x, sign, u0, u1, pk_slot, neg_g2_aff):
    rk = sig_x.shape[0]
    r = u0.shape[0]
    k = rk // r
    sig_jac, parse_ok, hm_r = DH.g1_decompress_and_hash(sig_x, sign, u0, u1)
    sig_jac = _gen_sub(DC.G1_DEV, _GEN_JAC_G1, sig_jac, parse_ok)
    sub_ok = DC.g1_in_subgroup(sig_jac) & parse_ok
    hm = _tile_rounds(hm_r, k)
    sx, sy, s_inf = DC.G1_DEV.to_affine(sig_jac)
    hx, hy, _ = DC.G1_DEV.to_affine(hm)
    px = jnp.stack([sx, hx])
    py = jnp.stack([sy, hy])
    bc = lambda c: jnp.broadcast_to(c, (rk, L.NLIMB))
    qx = jax.tree.map(lambda a, b: jnp.stack([bc(a), b]), neg_g2_aff[0], pk_slot[0])
    qy = jax.tree.map(lambda a, b: jnp.stack([bc(a), b]), neg_g2_aff[1], pk_slot[1])
    ok = DP.paired_product_is_one(px, py, (qx, qy), 2)
    return sub_ok & ~s_inf & ok


@lru_cache(maxsize=None)
def _rlc_pipeline(g2sig: bool, front: str = FRONT_FIELDS, dst: bytes = b""):
    # front resolver shared with the beacon pipelines (batch._h2f_front):
    # "fields" passes the host-expanded (u0, u1) through, "digest" ships
    # the per-round 32-byte digests as words and runs expand_message_xmd
    # + hash_to_field ON DEVICE inside the same dispatch (ISSUE 14)
    core = _rlc_partials_run_g2sig if g2sig else _rlc_partials_run_g1sig
    h2f = _h2f_front(g2sig, front, dst)

    def run(sig_x, sign, msg, keys, valid, onehot, pk_sel, fixed_aff):
        u0, u1 = h2f(msg)
        return core(sig_x, sign, u0, u1, keys, valid, onehot, pk_sel,
                    fixed_aff)

    return jax.jit(run)


@lru_cache(maxsize=None)
def _exact_pipeline(g2sig: bool, front: str = FRONT_FIELDS,
                    dst: bytes = b""):
    core = _exact_partials_run_g2sig if g2sig else _exact_partials_run_g1sig
    h2f = _h2f_front(g2sig, front, dst)

    def run(sig_x, sign, msg, pk_slot, fixed_aff):
        u0, u1 = h2f(msg)
        return core(sig_x, sign, u0, u1, pk_slot, fixed_aff)

    return jax.jit(run)


class BatchPartialVerifier:
    """Verifies (round, slot) blocks of threshold partials for one group."""

    def __init__(self, scheme: Scheme, pub_poly: HT.PubPoly, n_nodes: int):
        self.scheme = scheme
        self.g2sig = scheme.sig_group is GroupG2
        self.n_nodes = n_nodes
        # every node's public share, once per group: ONE device dispatch
        # at committee scale (crypto/dkg_device.eval_all primes the
        # PubPoly memo so the evals below are lookups), host Horner below
        # the lane threshold — where n·t scalar muls are cheaper than a
        # dispatch
        from . import dkg_device
        if dkg_device.use_device(n_nodes):
            dkg_device.prime_public_shares(pub_poly, n_nodes)
        self.pub_points = [pub_poly.eval(i) for i in range(n_nodes)]
        if self.g2sig:
            # pks on G1
            self.pk_x = np.stack([np.asarray(L.encode_mont(p[0])) for p in self.pub_points])
            self.pk_y = np.stack([np.asarray(L.encode_mont(p[1])) for p in self.pub_points])
            self.fixed_aff = (L.encode_mont(_NEG_G1[0]), L.encode_mont(_NEG_G1[1]))
        else:
            # pks on G2: nested ((x0,x1),(y0,y1)) limb stacks
            enc = lambda sel: np.stack([np.asarray(L.encode_mont(sel(p))) for p in self.pub_points])
            self.pk_x = (enc(lambda p: p[0][0]), enc(lambda p: p[0][1]))
            self.pk_y = (enc(lambda p: p[1][0]), enc(lambda p: p[1][1]))
            self.fixed_aff = ((L.encode_mont(_NEG_G2[0][0]), L.encode_mont(_NEG_G2[0][1])),
                              (L.encode_mont(_NEG_G2[1][0]), L.encode_mont(_NEG_G2[1][1])))

    # -- host-side packing ---------------------------------------------------

    def _parse(self, rows, k):
        """-> (x limb array, sign flags, slot indices (r,k), valid (r,k)),
        all pure numpy — NO per-point host decompression (the y recovery
        runs on device inside the fused pipelines).  Host-detectable
        badness (missing slot, wrong length, bad flags, x >= p, signer
        index out of range) lands in the valid mask; slots whose x has no
        y on the curve are caught by the device parse_ok and localized by
        the exact fallback."""
        nb = 96 if self.g2sig else 48
        sig_bytes, idxs, idx_ok = [], [], []
        for row in rows:
            for j in range(k):
                p = bytes(row[j]) if j < len(row) and row[j] is not None \
                    else b""
                idx = HT.index_of(p) if len(p) >= 2 else 0
                if len(p) != nb + 2 or not (0 <= idx < self.n_nodes):
                    sig_bytes.append(b"")       # wrong length -> wire bad
                    idxs.append(0)
                    idx_ok.append(False)
                    continue
                sig_bytes.append(p[2:])
                idxs.append(idx)
                idx_ok.append(True)
        xw, sign, bad = _wire_parse(sig_bytes, self.g2sig)
        bad |= ~np.asarray(idx_ok)
        # substitute the generator encoding into bad slots: inert (zero
        # RLC coefficient, verdict carried by the valid mask)
        gx = _GEN_X_G2 if self.g2sig else _GEN_X_G1
        gsign = _GEN_SIGN_G2 if self.g2sig else _GEN_SIGN_G1
        xw[bad] = gx
        sign[bad] = gsign
        idxa = np.array(idxs)
        idxa[bad] = 0
        shape = (len(rows), k)
        return xw, sign, idxa.reshape(shape), (~bad).reshape(shape)

    def _sig_x(self, xw):
        if self.g2sig:
            return (jnp.asarray(xw[:, 0]), jnp.asarray(xw[:, 1]))
        return jnp.asarray(xw)

    def _msg_enc(self, msgs):
        """(front, msg pytree) for a round-digest list: above the h2f
        threshold the 32-byte digests ship as raw words and expand on
        device (the caller computed them once per ROUND, not per slot —
        the per-message xmd loop is what moves off-host); below it the
        host hash-to-field oracle runs unchanged."""
        if h2f_device_default(len(msgs)) \
                and all(len(m) == 32 for m in msgs):
            return FRONT_DIGEST, (jnp.asarray(
                SHA.pack_msgs_to_words(msgs, 32)),)
        if self.g2sig:
            return FRONT_FIELDS, DH.hash_msgs_to_field_g2(msgs,
                                                          self.scheme.dst)
        return FRONT_FIELDS, DH.hash_msgs_to_field_g1(msgs,
                                                      self.scheme.dst)

    def _pk_sel(self, signer_list):
        ix = np.asarray(signer_list)
        if self.g2sig:
            return (jnp.asarray(self.pk_x[ix]), jnp.asarray(self.pk_y[ix]))
        sel = lambda pair: (jnp.asarray(pair[0][ix]), jnp.asarray(pair[1][ix]))
        return (sel(self.pk_x), sel(self.pk_y))

    # -- verification --------------------------------------------------------

    def verify_partials(self, msgs, partial_rows) -> np.ndarray:
        """msgs: one digest per round; partial_rows: ragged per-round lists of
        wire partials (be16(index) || sig).  Returns an (r, kmax) validity
        mask (padded slots are False)."""
        r = len(msgs)
        if r == 0:
            return np.zeros((0, 0), dtype=bool)
        k = max((len(row) for row in partial_rows), default=0)
        if k == 0:
            return np.zeros((r, 0), dtype=bool)
        xw, sign, idxs, valid = self._parse(partial_rows, k)
        if not valid.any():
            return valid  # nothing parsed — no device work to do
        sig_x = self._sig_x(xw)
        sign_d = jnp.asarray(sign)
        front, msg = self._msg_enc(msgs)

        flat_valid = valid.reshape(-1)
        flat_idx = idxs.reshape(-1)
        signers = sorted(set(flat_idx[flat_valid]))
        onehot = np.zeros((len(signers), r * k), dtype=np.uint32)
        for i, s in enumerate(signers):
            onehot[i] = (flat_idx == s) & flat_valid
        # per-slot randomizers are sampled on device from a fresh 128-bit
        # key (batch._device_rlc_bits); invalid slots get zero coefficients
        _count_dispatch()
        _, all_ok = _rlc_pipeline(self.g2sig, front, self.scheme.dst)(
            sig_x, sign_d, msg, jnp.asarray(_rlc_keys()),
            jnp.asarray(flat_valid.astype(np.uint32)), jnp.asarray(onehot),
            self._pk_sel(signers), self.fixed_aff)
        if bool(all_ok):
            return valid

        # exact fallback: per-slot pairings with per-slot public shares
        pk_slot = self._pk_sel(idxs.reshape(-1))
        _count_dispatch()
        got = np.asarray(_exact_pipeline(self.g2sig, front,
                                         self.scheme.dst)(
            sig_x, sign_d, msg, pk_slot, self.fixed_aff))
        return got.reshape(r, k) & valid
