"""Batched threshold-partial verification on TPU (BASELINE config 3).

The reference verifies each incoming partial with two pairings on the CPU
(`tbls.VerifyPartial`, chain/beacon/node.go:150) — O(n) pairings per round
per node, its hottest call site.  Here a whole (rounds x slots) block is
collapsed into ONE Miller product via a per-signer random linear combination:

    forall (r,j):  e(-g1, S_rj) · e(pk_idx(rj), H_r) == 1
    ==>  e(-g1, sum_rj c_rj·S_rj) · prod_i e(pk_i, T_i) == 1
         with  T_i = sum over slots with idx==i of c_rj·H_r

sound except with probability ~2^-SECURITY_BITS.  pk_i = PubPoly.eval(i) is
evaluated once per group on the host (the polynomial is tiny); the Miller
product has (#distinct signers + 1) pairs.  On RLC failure, exact per-slot
pairing checks locate invalid partials.

Slot layout: callers pass ragged per-round partial lists (wire format:
be16(index) || sig); rows are padded to the widest row and masked.
"""

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from . import tbls as HT
from .batch import _NEG_G1, _NEG_G2, _device_rlc_bits, _rlc_keys
from .host.params import G1_GEN, G2_GEN
from .schemes import Scheme, GroupG2
from ..ops import curve as DC
from ..ops import h2c as DH
from ..ops import limbs as L
from ..ops import pairing as DP


def _tile_rounds(tree_pt, k):
    """(r, ...) point -> (r*k, ...): slot (r, j) sees round r's value."""
    return jax.tree.map(lambda t: jnp.repeat(t, k, axis=0), tree_pt)


def _masked_sums(curve, pts, onehot):
    """Per-signer sums: T_i = sum over slots with onehot[i]==1 (complete
    adds; masked-out slots become infinity).  Returns a stacked point
    tree with leading axis n_nodes.

    One `lax.scan` over the signer axis: the compiled graph contains a
    SINGLE masked sum tree instead of n_nodes unrolled copies.  The
    unrolled form made this the slowest-compiling program in the whole
    framework (>40 min cold XLA:CPU at 13 signers — it blew the bench's
    per-config watchdog on an idle core); the scan form is numerically
    identical and costs one extra sequential step per signer at runtime."""
    inf = curve.infinity((onehot.shape[1],))

    def body(carry, row):
        sel = curve._select(row == 1, pts, inf)
        return carry, curve.sum_points(sel)

    _, ts = jax.lax.scan(body, 0, onehot)
    return ts


def _prepend_point(single, stacked):
    """Prepend one unbatched point to a (k, ...)-stacked point tree."""
    return jax.tree.map(lambda s, t: jnp.concatenate([s[None], t], 0),
                        single, stacked)


def _partials_bits(keys, valid):
    """(SB, 2rk) randomizer planes on device: one coefficient per slot
    (zero where invalid), duplicated for the tiled-hm half (the same c_rj
    multiplies S_rj and H_r — the RLC identity needs equal coefficients)."""
    b, = _device_rlc_bits(keys, valid, split=1)
    return jnp.concatenate([b, b], axis=1)


def _partials_verdict(sub_ok, ok, valid):
    """Fused device scalar: RLC ok AND every valid slot's subgroup check."""
    return ok & jnp.all(sub_ok | ~valid.astype(bool))


def _rlc_partials_run_g2sig(sig_jac, u0, u1, keys, valid, onehot, pk_sel,
                            neg_g1_aff):
    """sigs on G2, pks on G1.  sig_jac: (rk,) G2 jac; u0/u1: (r,) fp2;
    keys: (2, 2) threefry keys; valid: (rk,) slot mask; onehot: (p, rk);
    pk_sel: ((p,24),(p,24)) G1 affine."""
    rk = onehot.shape[1]
    r = u0[0].shape[0]
    k = rk // r
    bits = _partials_bits(keys, valid)
    sub_ok = DC.g2_in_subgroup(sig_jac)
    hm = _tile_rounds(DH.hash_to_g2_jac(u0, u1), k)
    both = jax.tree.map(lambda a, b: jnp.concatenate([a, b], 0), sig_jac, hm)
    mult = DC.G2_DEV.scalar_mul_bits(both, bits)
    s_sum = DC.G2_DEV.sum_points(jax.tree.map(lambda t: t[:rk], mult))
    ch = jax.tree.map(lambda t: t[rk:], mult)
    ts = _masked_sums(DC.G2_DEV, ch, onehot)
    qx_all, qy_all, _ = DC.G2_DEV.to_affine(_prepend_point(s_sum, ts))
    px = jnp.concatenate([neg_g1_aff[0][None], pk_sel[0]], axis=0)
    py = jnp.concatenate([neg_g1_aff[1][None], pk_sel[1]], axis=0)
    ok = DP.paired_product_is_one(px, py, (qx_all, qy_all),
                                  onehot.shape[0] + 1)
    return sub_ok, _partials_verdict(sub_ok, ok, valid)


def _rlc_partials_run_g1sig(sig_jac, u0, u1, keys, valid, onehot, pk_sel,
                            neg_g2_aff):
    """sigs on G1, pks on G2 (short-sig scheme)."""
    rk = onehot.shape[1]
    r = u0.shape[0]
    k = rk // r
    bits = _partials_bits(keys, valid)
    sub_ok = DC.g1_in_subgroup(sig_jac)
    hm = _tile_rounds(DH.hash_to_g1_jac(u0, u1), k)
    both = jax.tree.map(lambda a, b: jnp.concatenate([a, b], 0), sig_jac, hm)
    mult = DC.G1_DEV.scalar_mul_bits(both, bits)
    s_sum = DC.G1_DEV.sum_points(jax.tree.map(lambda t: t[:rk], mult))
    ch = jax.tree.map(lambda t: t[rk:], mult)
    ts = _masked_sums(DC.G1_DEV, ch, onehot)
    px_all, py_all, _ = DC.G1_DEV.to_affine(_prepend_point(s_sum, ts))
    qx = jax.tree.map(lambda a, b: jnp.concatenate([a[None], b], axis=0),
                      neg_g2_aff[0], pk_sel[0])
    qy = jax.tree.map(lambda a, b: jnp.concatenate([a[None], b], axis=0),
                      neg_g2_aff[1], pk_sel[1])
    ok = DP.paired_product_is_one(px_all, py_all, (qx, qy),
                                  onehot.shape[0] + 1)
    return sub_ok, _partials_verdict(sub_ok, ok, valid)


def _exact_partials_run_g2sig(sig_jac, u0, u1, k, pk_slot, neg_g1_aff):
    """Per-slot exact checks with per-slot pubkeys (fallback path)."""
    sub_ok = DC.g2_in_subgroup(sig_jac)
    hm = _tile_rounds(DH.hash_to_g2_jac(u0, u1), k)
    sx, sy, s_inf = DC.G2_DEV.to_affine(sig_jac)
    hx, hy, _ = DC.G2_DEV.to_affine(hm)
    rk = pk_slot[0].shape[0]
    px = jnp.stack([jnp.broadcast_to(neg_g1_aff[0], (rk, L.NLIMB)), pk_slot[0]])
    py = jnp.stack([jnp.broadcast_to(neg_g1_aff[1], (rk, L.NLIMB)), pk_slot[1]])
    qx = jax.tree.map(lambda a, b: jnp.stack([a, b]), sx, hx)
    qy = jax.tree.map(lambda a, b: jnp.stack([a, b]), sy, hy)
    ok = DP.paired_product_is_one(px, py, (qx, qy), 2)
    return sub_ok & ~s_inf & ok


def _exact_partials_run_g1sig(sig_jac, u0, u1, k, pk_slot, neg_g2_aff):
    sub_ok = DC.g1_in_subgroup(sig_jac)
    hm = _tile_rounds(DH.hash_to_g1_jac(u0, u1), k)
    sx, sy, s_inf = DC.G1_DEV.to_affine(sig_jac)
    hx, hy, _ = DC.G1_DEV.to_affine(hm)
    rk = sx.shape[0]
    px = jnp.stack([sx, hx])
    py = jnp.stack([sy, hy])
    bc = lambda c: jnp.broadcast_to(c, (rk, L.NLIMB))
    qx = jax.tree.map(lambda a, b: jnp.stack([bc(a), b]), neg_g2_aff[0], pk_slot[0])
    qy = jax.tree.map(lambda a, b: jnp.stack([bc(a), b]), neg_g2_aff[1], pk_slot[1])
    ok = DP.paired_product_is_one(px, py, (qx, qy), 2)
    return sub_ok & ~s_inf & ok


@lru_cache(maxsize=None)
def _rlc_pipeline(g2sig: bool):
    return jax.jit(_rlc_partials_run_g2sig if g2sig else _rlc_partials_run_g1sig)


@lru_cache(maxsize=None)
def _exact_pipeline(g2sig: bool):
    return jax.jit(_exact_partials_run_g2sig if g2sig else _exact_partials_run_g1sig,
                   static_argnums=(3,))


class BatchPartialVerifier:
    """Verifies (round, slot) blocks of threshold partials for one group."""

    def __init__(self, scheme: Scheme, pub_poly: HT.PubPoly, n_nodes: int):
        self.scheme = scheme
        self.g2sig = scheme.sig_group is GroupG2
        self.n_nodes = n_nodes
        # host: evaluate every node's public share once per group
        self.pub_points = [pub_poly.eval(i) for i in range(n_nodes)]
        if self.g2sig:
            # pks on G1
            self.pk_x = np.stack([np.asarray(L.encode_mont(p[0])) for p in self.pub_points])
            self.pk_y = np.stack([np.asarray(L.encode_mont(p[1])) for p in self.pub_points])
            self.fixed_aff = (L.encode_mont(_NEG_G1[0]), L.encode_mont(_NEG_G1[1]))
        else:
            # pks on G2: nested ((x0,x1),(y0,y1)) limb stacks
            enc = lambda sel: np.stack([np.asarray(L.encode_mont(sel(p))) for p in self.pub_points])
            self.pk_x = (enc(lambda p: p[0][0]), enc(lambda p: p[0][1]))
            self.pk_y = (enc(lambda p: p[1][0]), enc(lambda p: p[1][1]))
            self.fixed_aff = ((L.encode_mont(_NEG_G2[0][0]), L.encode_mont(_NEG_G2[0][1])),
                              (L.encode_mont(_NEG_G2[1][0]), L.encode_mont(_NEG_G2[1][1])))

    # -- host-side packing ---------------------------------------------------

    def _parse(self, rows, k):
        """-> (slot points, slot indices (r,k), valid mask (r,k))."""
        gen = G2_GEN if self.g2sig else G1_GEN
        from_bytes = (self.scheme.sig_group.from_bytes)
        pts, idxs, valid = [], [], []
        for row in rows:
            for j in range(k):
                if j >= len(row) or row[j] is None:
                    pts.append(gen); idxs.append(0); valid.append(False)
                    continue
                p = bytes(row[j])
                idx = HT.index_of(p)
                try:
                    if not (0 <= idx < self.n_nodes):
                        raise ValueError("bad signer index")
                    pt = from_bytes(p[2:], check_subgroup=False)
                    if pt is None:
                        raise ValueError("infinity partial")
                except (ValueError, AssertionError):
                    pts.append(gen); idxs.append(0); valid.append(False)
                    continue
                pts.append(pt); idxs.append(idx); valid.append(True)
        shape = (len(rows), k)
        return pts, np.array(idxs).reshape(shape), np.array(valid).reshape(shape)

    def _encode_slots(self, pts, msgs):
        if self.g2sig:
            sig_jac = DC.encode_g2_points(pts)
            u0, u1 = DH.hash_msgs_to_field_g2(msgs, self.scheme.dst)
        else:
            sig_jac = DC.encode_g1_points(pts)
            u0, u1 = DH.hash_msgs_to_field_g1(msgs, self.scheme.dst)
        return sig_jac, u0, u1

    def _pk_sel(self, signer_list):
        ix = np.asarray(signer_list)
        if self.g2sig:
            return (jnp.asarray(self.pk_x[ix]), jnp.asarray(self.pk_y[ix]))
        sel = lambda pair: (jnp.asarray(pair[0][ix]), jnp.asarray(pair[1][ix]))
        return (sel(self.pk_x), sel(self.pk_y))

    # -- verification --------------------------------------------------------

    def verify_partials(self, msgs, partial_rows) -> np.ndarray:
        """msgs: one digest per round; partial_rows: ragged per-round lists of
        wire partials (be16(index) || sig).  Returns an (r, kmax) validity
        mask (padded slots are False)."""
        r = len(msgs)
        if r == 0:
            return np.zeros((0, 0), dtype=bool)
        k = max((len(row) for row in partial_rows), default=0)
        if k == 0:
            return np.zeros((r, 0), dtype=bool)
        pts, idxs, valid = self._parse(partial_rows, k)
        if not valid.any():
            return valid  # nothing parsed — no device work to do
        sig_jac, u0, u1 = self._encode_slots(pts, msgs)
        rk = r * k

        flat_valid = valid.reshape(-1)
        flat_idx = idxs.reshape(-1)
        signers = sorted(set(flat_idx[flat_valid]))
        onehot = np.zeros((len(signers), rk), dtype=np.uint32)
        for i, s in enumerate(signers):
            onehot[i] = (flat_idx == s) & flat_valid
        # per-slot randomizers are sampled on device from a fresh 128-bit
        # key (batch._device_rlc_bits); invalid slots get zero coefficients
        _, all_ok = _rlc_pipeline(self.g2sig)(
            sig_jac, u0, u1, jnp.asarray(_rlc_keys()),
            jnp.asarray(flat_valid.astype(np.uint32)), jnp.asarray(onehot),
            self._pk_sel(signers), self.fixed_aff)
        if bool(all_ok):
            return valid

        # exact fallback: per-slot pairings with per-slot public shares
        pk_slot = self._pk_sel(idxs.reshape(-1))
        got = np.asarray(_exact_pipeline(self.g2sig)(
            sig_jac, u0, u1, k, pk_slot, self.fixed_aff))
        return got.reshape(r, k) & valid
